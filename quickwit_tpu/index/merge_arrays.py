"""Array-level split merging — segment merge without re-tokenization.

Role of tantivy's segment merger driven by the reference's `MergeExecutor`
(`merge_executor.rs:54`): N immutable splits combine into one by merging
their index structures directly:

- term dictionaries k-way merge (sorted term streams),
- postings concatenate per term with doc-id offsets applied (numpy slicing,
  no decode: the split format's dense arrays make this a copy + add),
- positions rebased, fieldnorms/columns concatenated and re-padded,
- the doc store concatenates **compressed blocks as-is** (blocks are
  independent zlib streams; only the block index shifts).

This replaces the doc-level re-index path (SplitWriter over fetched docs)
whenever no delete tasks must be applied — the common case — making merge
cost IO-bound instead of tokenize-bound.
"""

from __future__ import annotations

import heapq
import logging
import zlib
from typing import Any, Optional

import numpy as np

from .format import DOC_PAD, POSTING_PAD, SplitFileBuilder, SplitFooter, pad_to
from .reader import SplitReader

logger = logging.getLogger(__name__)


def merge_splits(readers: list[SplitReader], reorder_field: Optional[str] = None,
                 fault_hook=None) -> bytes:
    """Merged split file bytes. All inputs must share a doc mapping (the
    caller guarantees it via doc_mapping_uid, as the reference does).

    `reorder_field` opts into cluster-aware doc reordering (the doc-id
    reassignment of arxiv 1411.1220 applied to the timestamp axis): the
    merged split's doc ids follow ascending `reorder_field` values instead
    of input append order, so per-512-doc zonemaps tighten and range
    filters prune more blocks. Purely a layout decision — the doc SET and
    every per-doc structure are conserved, and any failure (including a
    `fault_hook` chaos fault) falls back to the append-order merge.
    `fault_hook` is the merge executor's FaultInjector binding for the
    "merge.reorder" point."""
    if reorder_field is not None:
        try:
            if fault_hook is not None:
                fault_hook()
            order = _cluster_order(readers, reorder_field)
            if order is not None:
                return _merge_splits_ordered(readers, order)
        except Exception as exc:  # noqa: BLE001 - layout opt must never fail a merge
            logger.warning("cluster reorder on %r failed (%s); "
                           "merging in append order", reorder_field, exc)
    return _merge_splits_ordered(readers, None)


def _cluster_order(readers: list[SplitReader],
                   field: str) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """(new_order, old2new) doc permutation clustering the merged split by
    ascending `field` value (docs missing the value last, ties stable in
    append order), or None when inapplicable: no input holds the column,
    the append order is already clustered, or any inverted field records
    positions (their per-posting arrays are not rebased under a permute)."""
    for r in readers:
        for name, meta in r.footer.fields.items():
            if (meta.get("indexed")
                    and r.has_array(f"inv.{name}.positions.offsets")):
                return None
    num_docs = sum(r.num_docs for r in readers)
    doc_offsets = np.cumsum([0] + [r.num_docs for r in readers])[:-1]
    keys = np.full(num_docs, np.inf, dtype=np.float64)
    found = False
    for reader, offset in zip(readers, doc_offsets):
        if reader.footer.fields.get(field, {}).get("column_kind") != "numeric":
            continue
        n = reader.num_docs
        v, p = reader.column_values(field)
        pm = p[:n].astype(bool)
        keys[offset: offset + n][pm] = v[:n][pm].astype(np.float64)
        found = found or bool(pm.any())
    if not found:
        return None
    new_order = np.argsort(keys, kind="stable").astype(np.int64)
    if np.array_equal(new_order, np.arange(num_docs, dtype=np.int64)):
        return None  # already clustered: keep the cheap append-order layout
    old2new = np.empty(num_docs, dtype=np.int64)
    old2new[new_order] = np.arange(num_docs, dtype=np.int64)
    return new_order, old2new


def _merge_splits_ordered(readers: list[SplitReader],
                          order: Optional[tuple[np.ndarray,
                                                np.ndarray]]) -> bytes:
    new_order, old2new = order if order is not None else (None, None)
    num_docs = sum(r.num_docs for r in readers)
    num_docs_padded = pad_to(num_docs, DOC_PAD)
    doc_offsets = np.cumsum([0] + [r.num_docs for r in readers])[:-1]

    builder = SplitFileBuilder()
    fields_meta: dict[str, dict[str, Any]] = {}

    field_names = _union_fields(readers)
    for name in field_names["inverted"]:
        fields_meta[name] = _merge_inverted(
            builder, name, readers, doc_offsets, num_docs, num_docs_padded,
            new_order, old2new)
    for name in field_names["numeric_cols"]:
        meta = fields_meta.setdefault(name, dict(_first_meta(readers, name)))
        meta.update(_merge_numeric_column(
            builder, name, readers, doc_offsets, num_docs, num_docs_padded,
            new_order))
    for name in field_names["ordinal_cols"]:
        meta = fields_meta.setdefault(name, dict(_first_meta(readers, name)))
        meta.update(_merge_ordinal_column(
            builder, name, readers, doc_offsets, num_docs, num_docs_padded,
            new_order, old2new))
    _merge_docstore(builder, readers, doc_offsets, new_order)

    for name, meta in fields_meta.items():
        # dynamic fields: union the observed value classes across inputs
        # and retype the merged column (str coercion wins; mixed numerics
        # promoted to f64 by _merge_numeric_column)
        classes: set[str] = set()
        dynamic = False
        for r in readers:
            rmeta = r.footer.fields.get(name, {})
            if rmeta.get("dynamic"):
                dynamic = True
                classes.update(rmeta.get("value_classes", ()))
        if not dynamic:
            continue
        meta["dynamic"] = True
        meta["value_classes"] = sorted(classes)
        kind = meta.get("column_kind")
        if kind == "ordinal":
            meta["col_type"] = "text"
        elif kind == "numeric":
            col_types = {r.footer.fields.get(name, {}).get("col_type")
                         for r in readers
                         if r.footer.fields.get(name, {}).get("col_type")}
            meta["col_type"] = (col_types.pop() if len(col_types) == 1
                                else "f64")

    time_ranges = [r.footer.time_range for r in readers if r.footer.time_range]
    time_range = None
    if time_ranges:
        time_range = (min(t[0] for t in time_ranges),
                      max(t[1] for t in time_ranges))
    footer = SplitFooter(
        num_docs=num_docs, num_docs_padded=num_docs_padded, arrays={},
        fields=fields_meta, time_range=time_range,
        doc_mapping_uid=readers[0].footer.doc_mapping_uid,
        extra={"uncompressed_docs_size_bytes": sum(
            r.footer.extra.get("uncompressed_docs_size_bytes", 0)
            for r in readers)},
    )
    return builder.finish(footer)


def _union_fields(readers: list[SplitReader]) -> dict[str, list[str]]:
    inverted, numeric_cols, ordinal_cols = set(), set(), set()
    for r in readers:
        for name, meta in r.footer.fields.items():
            if meta.get("indexed"):
                inverted.add(name)
            kind = meta.get("column_kind")
            if kind == "numeric":
                numeric_cols.add(name)
            elif kind == "ordinal":
                ordinal_cols.add(name)
    # a dynamic field coerced numeric in one split and string in another
    # merges as strings (the writer's own coercion order: str wins)
    numeric_cols -= ordinal_cols
    return {"inverted": sorted(inverted), "numeric_cols": sorted(numeric_cols),
            "ordinal_cols": sorted(ordinal_cols)}


def _first_meta(readers, name) -> dict[str, Any]:
    for r in readers:
        if name in r.footer.fields:
            return r.footer.fields[name]
    return {}


class _ArrayCollector:
    """Builder-shaped shim capturing arrays for post-processing (posting
    re-sort, impact ordering, doc reorder) before they hit the real
    SplitFileBuilder."""

    def __init__(self):
        self.arrays: dict[str, np.ndarray] = {}

    def add_array(self, name: str, arr: np.ndarray) -> None:
        self.arrays[name] = arr


def _merge_inverted(builder, name, readers, doc_offsets, num_docs,
                    num_docs_padded, new_order=None,
                    old2new=None) -> dict[str, Any]:
    """Dispatch: native k-way merge (fastindex.merge_inverted) when the
    extension is available, byte-identical Python fallback otherwise.
    Both paths land in a collector so the merged arenas can be
    post-processed: doc ids remapped under a cluster reorder, each term's
    postings restored to ascending-doc order (v3 inputs arrive
    impact-ordered — their concatenation is sorted by NEITHER doc nor
    impact), per-term max tf persisted, and finally the merged field
    re-impact-ordered against its own merged df/fieldnorm/avg_len instead
    of inheriting the inputs' stale quantization scales."""
    with_positions = any(
        r.has_array(f"inv.{name}.positions.offsets") for r in readers)
    collect = _ArrayCollector()
    from ..native import load_fastindex
    fastindex = load_fastindex()
    if fastindex is not None and hasattr(fastindex, "merge_inverted"):
        _merge_inverted_native(
            fastindex, collect, name, readers, doc_offsets, num_docs_padded,
            with_positions)
    else:
        _merge_inverted_python(
            collect, name, readers, doc_offsets, num_docs_padded,
            with_positions)
    prefix = f"inv.{name}."
    arrays = {full[len(prefix):]: arr for full, arr in collect.arrays.items()}

    norms = np.zeros(num_docs_padded, dtype=np.int32)
    total_tokens = 0
    for reader, offset in zip(readers, doc_offsets):
        if not reader.has_array(f"inv.{name}.fieldnorm"):
            continue
        norms[offset: offset + reader.num_docs] = \
            reader.fieldnorm(name)[: reader.num_docs]
        total_tokens += int(reader.field_meta(name).get("total_tokens", 0))
    if new_order is not None:
        norms[:num_docs] = norms[:num_docs][new_order]
    arrays["fieldnorm"] = norms

    dfs = arrays["terms.df"]
    post_offs = arrays["terms.post_off"].astype(np.int64)
    post_lens = arrays["terms.post_len"].astype(np.int64)
    ids = np.array(arrays["postings.ids"], dtype=np.int32, copy=True)
    tfs = np.array(arrays["postings.tfs"], dtype=np.int32, copy=True)
    needs_doc_sort = (old2new is not None or any(
        r.impact_info(name) is not None for r in readers))
    if old2new is not None:
        real = tfs > 0  # pads keep the sentinel id, outside old2new's range
        ids[real] = old2new[ids[real]]
    if len(dfs) and needs_doc_sort and not with_positions:
        # positions fields never reach here reordered: impact ordering
        # skips them at write time and _cluster_order refuses the permute
        seg = np.repeat(np.arange(len(post_offs), dtype=np.int64), post_lens)
        order = np.lexsort((ids, seg))
        ids = ids[order]
        tfs = tfs[order]
    arrays["postings.ids"] = ids
    arrays["postings.tfs"] = tfs
    # per-term max tf: merged splits persist the term_stats input just like
    # freshly written ones, so reader reopens never rescan postings
    if len(dfs):
        arrays["terms.max_tf"] = np.maximum.reduceat(
            tfs, post_offs).astype(np.int32)
    else:
        arrays["terms.max_tf"] = np.zeros(0, dtype=np.int32)

    impact_meta = None
    if not with_positions:
        from .writer import apply_impact_ordering
        avg_len = (total_tokens / num_docs) if num_docs else 0.0
        impact_meta = apply_impact_ordering(arrays, avg_len, num_docs)

    for suffix, arr in arrays.items():
        builder.add_array(prefix + suffix, arr)

    meta = dict(_first_meta(readers, name))
    meta.update({
        "num_terms": len(dfs),
        "total_tokens": total_tokens,
        "avg_len": (total_tokens / num_docs) if num_docs else 0.0,
    })
    if impact_meta is not None:
        meta["impact"] = impact_meta
    else:
        # an inherited first-meta "impact" entry would claim an ordering
        # the merged arenas no longer have
        meta.pop("impact", None)
    return meta


def _merge_inverted_native(fastindex, builder, name, readers, doc_offsets,
                           num_docs_padded, with_positions) -> int:
    inputs = []
    for i, r in enumerate(readers):
        if r.term_dict(name) is None:
            continue
        has_pos = r.has_array(f"inv.{name}.positions.offsets")
        inputs.append((
            np.ascontiguousarray(r.array(f"inv.{name}.terms.blob"),
                                 dtype=np.uint8),
            np.ascontiguousarray(r.array(f"inv.{name}.terms.offsets"),
                                 dtype=np.int64),
            np.ascontiguousarray(r.array(f"inv.{name}.terms.df"),
                                 dtype=np.int32),
            np.ascontiguousarray(r.array(f"inv.{name}.terms.post_off"),
                                 dtype=np.int64),
            np.ascontiguousarray(r.array(f"inv.{name}.postings.ids"),
                                 dtype=np.int32),
            np.ascontiguousarray(r.array(f"inv.{name}.postings.tfs"),
                                 dtype=np.int32),
            np.ascontiguousarray(r.array(f"inv.{name}.positions.offsets"),
                                 dtype=np.int64) if has_pos else None,
            np.ascontiguousarray(r.array(f"inv.{name}.positions.data"),
                                 dtype=np.int32) if has_pos else None,
            int(doc_offsets[i]),
        ))
    (blob, term_offsets, dfs, post_offs, post_lens, ids, tfs,
     pos_offsets, pos_data) = fastindex.merge_inverted(
        inputs, num_docs_padded, with_positions)
    builder.add_array(f"inv.{name}.terms.blob",
                      np.frombuffer(blob, dtype=np.uint8))
    builder.add_array(f"inv.{name}.terms.offsets",
                      np.frombuffer(term_offsets, dtype=np.int64))
    builder.add_array(f"inv.{name}.terms.df", np.frombuffer(dfs, np.int32))
    builder.add_array(f"inv.{name}.terms.post_off",
                      np.frombuffer(post_offs, np.int64))
    builder.add_array(f"inv.{name}.terms.post_len",
                      np.frombuffer(post_lens, np.int32))
    builder.add_array(f"inv.{name}.postings.ids", np.frombuffer(ids, np.int32))
    builder.add_array(f"inv.{name}.postings.tfs", np.frombuffer(tfs, np.int32))
    if with_positions:
        builder.add_array(f"inv.{name}.positions.offsets",
                          np.frombuffer(pos_offsets, np.int64))
        builder.add_array(f"inv.{name}.positions.data",
                          np.frombuffer(pos_data, np.int32))
    return len(dfs) // 4


def _merge_inverted_python(builder, name, readers, doc_offsets,
                           num_docs_padded, with_positions) -> int:
    term_dicts = [(i, r.term_dict(name)) for i, r in enumerate(readers)]
    term_dicts = [(i, td) for i, td in term_dicts if td is not None]
    # prefetch whole arenas once per reader: per-term ranged reads would hit
    # the byte-range cache's range-merge thousands of times (quadratic)
    arenas = {}
    for reader_idx, _td in term_dicts:
        r = readers[reader_idx]
        arenas[reader_idx] = {
            "ids": r.array(f"inv.{name}.postings.ids"),
            "tfs": r.array(f"inv.{name}.postings.tfs"),
            "pos_offs": (r.array(f"inv.{name}.positions.offsets")
                         if r.has_array(f"inv.{name}.positions.offsets") else None),
            "pos_data": (r.array(f"inv.{name}.positions.data")
                         if r.has_array(f"inv.{name}.positions.data") else None),
        }

    # k-way merge of sorted term streams: heap of (term, reader_idx, ordinal)
    streams = []
    for reader_idx, td in term_dicts:
        if len(td):
            streams.append((td.term_at(0), reader_idx, 0, td))
    heapq.heapify(streams)

    blob_parts: list[bytes] = []
    offsets_list = [0]
    dfs_list: list[int] = []
    post_offs_list: list[int] = []
    post_lens_list: list[int] = []
    ids_chunks: list[np.ndarray] = []
    tfs_chunks: list[np.ndarray] = []
    pos_offset_chunks: list[np.ndarray] = []
    pos_data_chunks: list[np.ndarray] = []
    blob_len = 0
    cursor = 0
    pos_cursor = 0

    while streams:
        term = streams[0][0]
        group: list[tuple[int, Any, int]] = []  # (reader_idx, td, ordinal)
        while streams and streams[0][0] == term:
            _, reader_idx, ordinal, td = heapq.heappop(streams)
            group.append((reader_idx, td, ordinal))
            if ordinal + 1 < len(td):
                heapq.heappush(
                    streams, (td.term_at(ordinal + 1), reader_idx, ordinal + 1, td))
        group.sort()  # reader order == ascending doc-id ranges

        df = 0
        term_ids: list[np.ndarray] = []
        term_tfs: list[np.ndarray] = []
        term_pos_offsets: list[np.ndarray] = []
        term_pos_data: list[np.ndarray] = []
        for reader_idx, td, ordinal in group:
            info = _info_at(td, ordinal)
            arena = arenas[reader_idx]
            lo, hi = info.post_off, info.post_off + info.df
            term_ids.append(arena["ids"][lo:hi].astype(np.int64)
                            + doc_offsets[reader_idx])
            term_tfs.append(arena["tfs"][lo:hi])
            if with_positions and arena["pos_offs"] is not None:
                offs = arena["pos_offs"][lo: hi + 1]
                # per-posting position list lengths for the real postings
                lens = (offs[1:] - offs[:-1]).astype(np.int64)
                term_pos_offsets.append(lens)
                term_pos_data.append(
                    arena["pos_data"][int(offs[0]): int(offs[-1])])
            df += info.df

        padded = pad_to(max(df, 1), POSTING_PAD)
        ids_arr = np.full(padded, num_docs_padded, dtype=np.int32)
        tfs_arr = np.zeros(padded, dtype=np.int32)
        merged_ids = np.concatenate(term_ids) if term_ids else np.array([], np.int64)
        ids_arr[:df] = merged_ids.astype(np.int32)
        if term_tfs:
            tfs_arr[:df] = np.concatenate(term_tfs)
        ids_chunks.append(ids_arr)
        tfs_chunks.append(tfs_arr)
        if with_positions:
            lens_all = (np.concatenate(term_pos_offsets)
                        if term_pos_offsets else np.array([], np.int64))
            entry_offsets = np.zeros(padded + 1, dtype=np.int64)
            np.cumsum(lens_all, out=entry_offsets[1: df + 1])
            entry_offsets[df + 1:] = entry_offsets[df]
            pos_offset_chunks.append(entry_offsets + pos_cursor)
            data = (np.concatenate(term_pos_data)
                    if term_pos_data else np.array([], np.int32))
            pos_data_chunks.append(data.astype(np.int32))
            pos_cursor += int(entry_offsets[df])

        encoded = term.encode()
        blob_parts.append(encoded)
        blob_len += len(encoded)
        offsets_list.append(blob_len)
        dfs_list.append(df)
        post_offs_list.append(cursor)
        post_lens_list.append(padded)
        cursor += padded

    builder.add_array(f"inv.{name}.terms.blob",
                      np.frombuffer(b"".join(blob_parts), dtype=np.uint8))
    builder.add_array(f"inv.{name}.terms.offsets",
                      np.array(offsets_list, dtype=np.int64))
    builder.add_array(f"inv.{name}.terms.df", np.array(dfs_list, dtype=np.int32))
    builder.add_array(f"inv.{name}.terms.post_off",
                      np.array(post_offs_list, dtype=np.int64))
    builder.add_array(f"inv.{name}.terms.post_len",
                      np.array(post_lens_list, dtype=np.int32))
    builder.add_array(f"inv.{name}.postings.ids",
                      np.concatenate(ids_chunks) if ids_chunks
                      else np.array([], np.int32))
    builder.add_array(f"inv.{name}.postings.tfs",
                      np.concatenate(tfs_chunks) if tfs_chunks
                      else np.array([], np.int32))
    if with_positions:
        # trailing guard entry so slice arithmetic matches the writer layout
        all_offsets = (np.concatenate(
            [c[:-1] for c in pos_offset_chunks] + [[pos_cursor]])
            if pos_offset_chunks else np.array([0], np.int64))
        builder.add_array(f"inv.{name}.positions.offsets",
                          np.asarray(all_offsets, dtype=np.int64))
        builder.add_array(f"inv.{name}.positions.data",
                          np.concatenate(pos_data_chunks) if pos_data_chunks
                          else np.array([], np.int32))
    return len(dfs_list)


def _info_at(td, ordinal: int):
    from .reader import TermInfo
    return TermInfo(ordinal, int(td.dfs[ordinal]), int(td.post_offs[ordinal]),
                    int(td.post_lens[ordinal]))


def _merge_numeric_column(builder, name, readers, doc_offsets, num_docs,
                          num_docs_padded, new_order=None) -> dict[str, Any]:
    dtypes = {r.column_values(name)[0].dtype for r in readers
              if r.footer.fields.get(name, {}).get("column_kind") == "numeric"}
    # dynamic columns typed differently per split (i64 here, f64 there)
    # coerce to f64 on merge — the writer's own mixed-numeric rule
    dtype = dtypes.pop() if len(dtypes) == 1 else np.dtype(np.float64)
    values = np.zeros(num_docs_padded, dtype=dtype)
    present = np.zeros(num_docs_padded, dtype=np.uint8)
    vmin, vmax = None, None
    for reader, offset in zip(readers, doc_offsets):
        meta = reader.footer.fields.get(name, {})
        if meta.get("column_kind") != "numeric":
            continue
        v, p = reader.column_values(name)
        values[offset: offset + reader.num_docs] = v[: reader.num_docs]
        present[offset: offset + reader.num_docs] = p[: reader.num_docs]
        if meta.get("min_value") is not None:
            vmin = meta["min_value"] if vmin is None else min(vmin, meta["min_value"])
            vmax = meta["max_value"] if vmax is None else max(vmax, meta["max_value"])
    if new_order is not None:
        values[:num_docs] = values[:num_docs][new_order]
        present[:num_docs] = present[:num_docs][new_order]
    builder.add_array(f"col.{name}.values", values)
    builder.add_array(f"col.{name}.present", present)
    # merged splits regain per-512-doc zonemaps (the reason the cluster
    # reorder exists: sorted values make the block bounds tight). Domain
    # is the raw values array — the merged column is never FOR-packed
    from .format import ZONEMAP_BLOCK
    from .writer import _column_zonemaps
    zmin, zmax = _column_zonemaps(values, present)
    builder.add_array(f"col.{name}.zmin", zmin)
    builder.add_array(f"col.{name}.zmax", zmax)
    return {"fast": True, "column_kind": "numeric",
            "min_value": vmin, "max_value": vmax,
            "zonemap_block": ZONEMAP_BLOCK, "packed": None}


def _canonical_numeric_strings(reader, name) -> "list[tuple[int, str]]":
    """Per-doc canonical strings of a NUMERIC column — used when a
    dynamic field is string-typed in the merged split but numeric in
    this input. Rendering follows the source split's value classes so it
    matches what the writer's own str-coercion (dynamic_canonical) would
    have produced: bool columns → true/false, integer-only → "5", floats
    → repr. (A long stored in an f64 column — the input split saw both —
    is unrecoverable and renders as repr(float).)"""
    meta = reader.footer.fields.get(name, {})
    classes = set(meta.get("value_classes", ()))
    v, p = reader.column_values(name)
    out = []
    is_bool = meta.get("col_type") == "bool" or classes == {"boolean"}
    ints_only = classes and "double" not in classes and not is_bool
    for doc_id in np.nonzero(p[: reader.num_docs])[0]:
        val = v[doc_id]
        if is_bool:
            text = "true" if val else "false"
        elif ints_only or not np.issubdtype(v.dtype, np.floating):
            text = str(int(val))
        else:
            text = repr(float(val))
        out.append((int(doc_id), text))
    return out

def _merge_ordinal_column(builder, name, readers, doc_offsets, num_docs,
                          num_docs_padded, new_order=None,
                          old2new=None) -> dict[str, Any]:
    # (doc, value-string) pairs per reader; ordinal inputs keep EVERY
    # value via the mv arrays when present, numeric inputs contribute
    # canonical strings (mixed-type dynamic columns coerce to strings)
    per_reader_pairs: list[list[tuple[int, str]]] = []
    union: set[str] = set()
    for reader in readers:
        kind = reader.footer.fields.get(name, {}).get("column_kind")
        if kind == "ordinal":
            local_keys = reader.column_dict(name)
            pairs: list[tuple[int, str]] = []
            if reader.has_array(f"col.{name}.mv_docs"):
                docs = reader.array(f"col.{name}.mv_docs")
                ords = reader.array(f"col.{name}.mv_ords")
                for d, o in zip(docs.tolist(), ords.tolist()):
                    if o >= 0:
                        pairs.append((d, local_keys[o]))
            else:
                local = reader.column_ordinals(name)[: reader.num_docs]
                for doc_id in np.nonzero(local >= 0)[0]:
                    pairs.append((int(doc_id), local_keys[local[doc_id]]))
            per_reader_pairs.append(pairs)
        elif kind == "numeric":
            per_reader_pairs.append(_canonical_numeric_strings(reader, name))
        else:
            per_reader_pairs.append([])
        union.update(v for _d, v in per_reader_pairs[-1])
    uniques = sorted(union)
    ordinal_of = {t: i for i, t in enumerate(uniques)}
    ordinals = np.full(num_docs_padded, -1, dtype=np.int32)
    all_pairs: list[tuple[int, int]] = []  # (global doc, global ordinal)
    multivalued = False
    for pairs, offset in zip(per_reader_pairs, doc_offsets):
        seen_docs: set[int] = set()
        for doc_id, value in pairs:
            g = int(offset) + doc_id
            o = ordinal_of[value]
            if g not in seen_docs:
                ordinals[g] = o  # dense column keeps the first value
                seen_docs.add(g)
            all_pairs.append((g, o))
        if len(seen_docs) != len(pairs):
            multivalued = True
    if new_order is not None:
        ordinals[:num_docs] = ordinals[:num_docs][new_order]
        # pair docs follow the permuted ids; stable doc-ascending re-sort
        # keeps each doc's distinct-value order intact
        all_pairs = sorted(((int(old2new[g]), o) for g, o in all_pairs),
                           key=lambda p: p[0])
    blob = "".join(uniques).encode()
    dict_offsets = np.zeros(len(uniques) + 1, dtype=np.int64)
    acc = 0
    for i, term in enumerate(uniques):
        acc += len(term.encode())
        dict_offsets[i + 1] = acc
    builder.add_array(f"col.{name}.ordinals", ordinals)
    builder.add_array(f"col.{name}.dict_blob", np.frombuffer(blob, dtype=np.uint8))
    builder.add_array(f"col.{name}.dict_offsets", dict_offsets)
    meta = {"fast": True, "column_kind": "ordinal",
            "cardinality": len(uniques)}
    if multivalued:
        from .format import POSTING_PAD, pad_to as _pad_to
        seen_pairs: set[tuple[int, int]] = set()
        mv = [p for p in all_pairs
              if p not in seen_pairs and not seen_pairs.add(p)]
        padded = _pad_to(max(len(mv), 1), POSTING_PAD)
        docs_arr = np.zeros(padded, dtype=np.int32)
        ords_arr = np.full(padded, -1, dtype=np.int32)
        docs_arr[: len(mv)] = [d for d, _o in mv]
        ords_arr[: len(mv)] = [o for _d, o in mv]
        builder.add_array(f"col.{name}.mv_docs", docs_arr)
        builder.add_array(f"col.{name}.mv_ords", ords_arr)
        meta["multivalued"] = True
    return meta


def _merge_docstore(builder, readers, doc_offsets, new_order=None) -> None:
    if new_order is not None:
        _rebuild_docstore(builder, readers, new_order)
        return
    data_chunks: list[np.ndarray] = []
    block_offsets = [0]
    block_first = []
    byte_cursor = 0
    for reader, offset in zip(readers, doc_offsets):
        offsets = reader.array("store.block_offsets")
        firsts = reader.array("store.block_first_doc")
        data = reader.array("store.data")
        data_chunks.append(data)
        for b in range(len(firsts) - 1):
            block_first.append(int(firsts[b]) + int(offset))
        for b in range(1, len(offsets)):
            block_offsets.append(byte_cursor + int(offsets[b]))
        byte_cursor += int(offsets[-1])
    total_docs = int(doc_offsets[-1]) + readers[-1].num_docs if len(readers) else 0
    block_first.append(total_docs)
    builder.add_array("store.data",
                      np.concatenate(data_chunks) if data_chunks
                      else np.array([], np.uint8))
    builder.add_array("store.block_offsets", np.array(block_offsets, dtype=np.int64))
    builder.add_array("store.block_first_doc", np.array(block_first, dtype=np.int32))


def _rebuild_docstore(builder, readers, new_order) -> None:
    """Doc-level docstore rebuild for the cluster reorder: the compressed
    input blocks cannot be reused (their doc runs are no longer
    contiguous), so every source line re-blocks in the new order with the
    writer's own blocking parameters."""
    from .writer import _STORE_BLOCK_BYTES
    sources: list[bytes] = []
    for reader in readers:
        block_first = reader.array("store.block_first_doc")
        block_offsets = reader.array("store.block_offsets")
        data = reader.array("store.data")
        for b in range(len(block_first) - 1):
            raw = data[int(block_offsets[b]): int(block_offsets[b + 1])]
            sources.extend(line for line in
                           zlib.decompress(raw.tobytes()).split(b"\n")
                           if line)
    num_docs = len(sources)
    if num_docs != new_order.shape[0]:
        raise ValueError(f"docstore holds {num_docs} docs, permutation "
                         f"covers {new_order.shape[0]}")
    blocks: list[bytes] = []
    block_first_doc = [0]
    block_offsets_out = [0]
    current: list[bytes] = []
    current_size = 0
    for new_id, old_id in enumerate(new_order.tolist()):
        source = sources[old_id]
        current.append(source)
        current_size += len(source) + 1
        if current_size >= _STORE_BLOCK_BYTES:
            blocks.append(zlib.compress(b"\n".join(current), 1))
            block_offsets_out.append(block_offsets_out[-1] + len(blocks[-1]))
            block_first_doc.append(new_id + 1)
            current, current_size = [], 0
    if current:
        blocks.append(zlib.compress(b"\n".join(current), 1))
        block_offsets_out.append(block_offsets_out[-1] + len(blocks[-1]))
        block_first_doc.append(num_docs)
    builder.add_array("store.data",
                      np.frombuffer(b"".join(blocks), dtype=np.uint8))
    builder.add_array("store.block_offsets",
                      np.array(block_offsets_out, dtype=np.int64))
    builder.add_array("store.block_first_doc",
                      np.array(block_first_doc, dtype=np.int32))
