"""Split reader: ranged reads of the array layout, term lookups, doc fetch.

Role of the reference's directory stack (`open_index_with_caches`,
`quickwit-search/src/leaf.rs:219`: StorageDirectory → CachingDirectory →
HotDirectory over the hotcache): opens a split with one footer GET, then
serves exact byte-range reads for postings/columns through a ByteRangeCache.
Device transfer (warmup) lives in `search/leaf.py`; this class is pure host.
"""

from __future__ import annotations

import bisect
import json
import zlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Iterator, Optional

import numpy as np

from ..storage.base import Storage
from ..storage.cache import ByteRangeCache
from .format import DEFAULT_FOOTER_HINT, ArrayMeta, SplitFooter, read_footer
from .impact import IMPACT_BLOCK
from ..common import sync


class _TermStatsCache:
    """Process-wide (path, field, term) → stats LRU shared across reader
    reopens. Splits are immutable, so stats computed by one reader instance
    stay valid for every later open of the same path — without this, a v2
    split lacking the `terms.max_tf` footer re-scans the term's postings on
    EVERY reader reopen (the leaf reader cache evicts under pressure)."""

    _MAX = 1 << 17

    def __init__(self) -> None:
        self._lock = sync.lock("_TermStatsCache._lock")
        self._entries: OrderedDict[tuple, Any] = OrderedDict()

    def get(self, key: tuple) -> Any:
        with self._lock:
            value = self._entries.get(key)
            if value is not None:
                self._entries.move_to_end(key)
            return value

    def put(self, key: tuple, value: Any) -> None:
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self._MAX:
                self._entries.popitem(last=False)


_GLOBAL_TERM_STATS = _TermStatsCache()   # ((uri, path), field, term) -> (df, max_tf)
_GLOBAL_TERM_CAPS = _TermStatsCache()    # ((uri, path), field, term) -> float | 0.0


@dataclass(frozen=True)
class TermInfo:
    ordinal: int
    df: int
    post_off: int   # element offset into the postings arenas
    post_len: int   # padded element count


class _TermDict:
    """Sorted term dictionary of one field: binary-searchable blob+offsets."""

    def __init__(self, blob: bytes, offsets: np.ndarray, dfs: np.ndarray,
                 post_offs: np.ndarray, post_lens: np.ndarray):
        self.blob = blob
        self.offsets = offsets
        self.dfs = dfs
        self.post_offs = post_offs
        self.post_lens = post_lens

    def __len__(self) -> int:
        return len(self.dfs)

    def term_at(self, ordinal: int) -> str:
        return self.blob[self.offsets[ordinal]: self.offsets[ordinal + 1]].decode()

    def lookup(self, term: str) -> Optional[TermInfo]:
        target = term.encode()
        lo, hi = 0, len(self.dfs)
        while lo < hi:
            mid = (lo + hi) // 2
            cand = self.blob[self.offsets[mid]: self.offsets[mid + 1]]
            if cand < target:
                lo = mid + 1
            elif cand > target:
                hi = mid
            else:
                return TermInfo(mid, int(self.dfs[mid]), int(self.post_offs[mid]),
                                int(self.post_lens[mid]))
        return None

    def iter_terms(self, start: Optional[str] = None) -> Iterator[tuple[str, int]]:
        """(term, df) pairs in sorted order, optionally from `start`."""
        begin = 0
        if start is not None:
            target = start.encode()
            lo, hi = 0, len(self.dfs)
            while lo < hi:
                mid = (lo + hi) // 2
                if self.blob[self.offsets[mid]: self.offsets[mid + 1]] < target:
                    lo = mid + 1
                else:
                    hi = mid
            begin = lo
        for i in range(begin, len(self.dfs)):
            yield self.term_at(i), int(self.dfs[i])


class SplitReader:
    def __init__(self, storage: Storage, path: str,
                 footer_hint: int = DEFAULT_FOOTER_HINT,
                 cache: Optional[ByteRangeCache] = None,
                 file_len: Optional[int] = None):
        self.storage = storage
        self.path = path
        # key for the process-wide stats/caps caches: the bare path is not
        # unique across storages (two indexes both have an "s0.split")
        self._stats_scope = (str(storage.uri), path)
        self.cache = cache or ByteRangeCache()
        self.file_len = file_len if file_len is not None else storage.file_num_bytes(path)
        self.footer: SplitFooter = read_footer(self._get_slice, self.file_len, footer_hint)
        self._term_dicts: dict[str, _TermDict] = {}
        self._arrays: dict[str, np.ndarray] = {}
        self._term_stats: dict[tuple[str, str], tuple[int, int]] = {}

    # --- IO ----------------------------------------------------------------
    def _get_slice(self, start: int, end: int) -> bytes:
        cached = self.cache.get(self.path, start, end)
        if cached is not None:
            return cached
        data = self.storage.get_slice(self.path, start, end)
        # per-query storage attribution: every split read (footer,
        # postings, columns) funnels through here on a byte-range-cache
        # miss; no-op (one ContextVar get) when no profile is bound
        from ..observability.profile import current_profile
        profile = current_profile()
        if profile is not None:
            profile.add("storage_read_bytes", len(data))
            profile.add("storage_reads", 1)
        self.cache.put(self.path, start, data)
        return data

    def _array_meta(self, name: str) -> ArrayMeta:
        meta = self.footer.arrays.get(name)
        if meta is None:
            raise KeyError(f"split has no array {name!r}")
        return meta

    def has_array(self, name: str) -> bool:
        return name in self.footer.arrays

    def array(self, name: str) -> np.ndarray:
        """Fetch a whole named array (cached)."""
        arr = self._arrays.get(name)
        if arr is None:
            meta = self._array_meta(name)
            raw = self._get_slice(meta.offset, meta.offset + meta.nbytes)
            arr = np.frombuffer(raw, dtype=np.dtype(meta.dtype)).reshape(meta.shape)
            self._arrays[name] = arr
        return arr

    def array_slice(self, name: str, start_elem: int, num_elems: int) -> np.ndarray:
        """Fetch `num_elems` elements of a named array without reading it all —
        the exact-byte-range read postings warmup relies on."""
        meta = self._array_meta(name)
        dtype = np.dtype(meta.dtype)
        byte_start = meta.offset + start_elem * dtype.itemsize
        raw = self._get_slice(byte_start, byte_start + num_elems * dtype.itemsize)
        return np.frombuffer(raw, dtype=dtype)

    # --- inverted index ----------------------------------------------------
    def term_dict(self, field: str) -> Optional[_TermDict]:
        td = self._term_dicts.get(field)
        if td is None:
            if f"inv.{field}.terms.offsets" not in self.footer.arrays:
                return None
            td = _TermDict(
                blob=self.array(f"inv.{field}.terms.blob").tobytes(),
                offsets=self.array(f"inv.{field}.terms.offsets"),
                dfs=self.array(f"inv.{field}.terms.df"),
                post_offs=self.array(f"inv.{field}.terms.post_off"),
                post_lens=self.array(f"inv.{field}.terms.post_len"),
            )
            self._term_dicts[field] = td
        return td

    def lookup_term(self, field: str, term: str) -> Optional[TermInfo]:
        td = self.term_dict(field)
        return td.lookup(term) if td else None

    def postings(self, field: str, info: TermInfo) -> tuple[np.ndarray, np.ndarray]:
        """Padded (doc_ids, tfs) for one term; reads only that term's range."""
        ids = self.array_slice(f"inv.{field}.postings.ids", info.post_off, info.post_len)
        tfs = self.array_slice(f"inv.{field}.postings.tfs", info.post_off, info.post_len)
        return ids, tfs

    def positions(self, field: str, info: TermInfo) -> tuple[np.ndarray, np.ndarray]:
        """(offsets[post_len+1], data) position lists for a term's postings."""
        offsets = self.array_slice(f"inv.{field}.positions.offsets",
                                   info.post_off, info.post_len + 1)
        data_start, data_end = int(offsets[0]), int(offsets[-1])
        data = self.array_slice(f"inv.{field}.positions.data",
                                data_start, data_end - data_start)
        return offsets - data_start, data

    def fieldnorm(self, field: str) -> np.ndarray:
        return self.array(f"inv.{field}.fieldnorm")

    # --- fast-field columns ------------------------------------------------
    def column_packing(self, field: str) -> Optional[dict[str, Any]]:
        """FOR packing info (`for_min`/`for_scale`/`bit_width`) when the
        column is stored as packed deltas (format v2), else None."""
        info = self.field_meta(field).get("packed")
        if info and self.has_array(f"col.{field}.packed"):
            return info
        return None

    def column_packed(self, field: str) -> tuple[np.ndarray, np.ndarray]:
        """(deltas, present) — the compact on-device representation of a
        packed column; `value = for_min + delta * for_scale`."""
        return (self.array(f"col.{field}.packed"),
                self.array(f"col.{field}.present"))

    def column_zonemaps(self, field: str) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Per-block (zmin, zmax) bounds in the column's on-disk domain
        (scaled deltas when packed, raw values otherwise); None for v1
        splits, which predate zonemaps."""
        if not self.has_array(f"col.{field}.zmin"):
            return None
        return self.array(f"col.{field}.zmin"), self.array(f"col.{field}.zmax")

    def column_values(self, field: str) -> tuple[np.ndarray, np.ndarray]:
        """(values, present) for a numeric column, padded to num_docs_padded.

        Packed columns (format v2) are reconstructed full-width host-side
        and cached, so every host consumer (exact sort-value re-reads,
        ordinalization, derived seconds columns, the doc-store-free bench
        comparator) sees the exact array a raw split would store. Device
        staging should prefer `column_packed` — that is where the byte
        savings live."""
        key = f"col.{field}.values"
        if key not in self._arrays and not self.has_array(key):
            info = self.column_packing(field)
            if info is not None:
                packed = self.array(f"col.{field}.packed")
                fm = self.field_meta(field)
                kind = fm.get("col_type") or fm.get("type")
                if kind == "u64":
                    values = (packed.astype(np.uint64)
                              * np.uint64(info["for_scale"])
                              + np.uint64(info["for_min"]))
                else:
                    values = (packed.astype(np.int64)
                              * np.int64(info["for_scale"])
                              + np.int64(info["for_min"]))
                # raw splits scatter into zeros: absent lanes hold 0, not
                # for_min — reconstruct bit-identically
                present = self.array(f"col.{field}.present")
                values = np.where(present != 0, values, values.dtype.type(0))
                self._arrays[key] = values
        return self.array(key), self.array(f"col.{field}.present")

    def column_ordinals(self, field: str) -> np.ndarray:
        return self.array(f"col.{field}.ordinals")

    def column_dict(self, field: str) -> list[str]:
        blob = self.array(f"col.{field}.dict_blob").tobytes()
        offsets = self.array(f"col.{field}.dict_offsets")
        return [blob[offsets[i]: offsets[i + 1]].decode() for i in range(len(offsets) - 1)]

    # --- doc store ---------------------------------------------------------
    def fetch_docs(self, doc_ids: list[int]) -> list[dict[str, Any]]:
        """Random-access doc fetch (reference: `fetch_docs.rs` over the doc
        store); decompresses each needed block once."""
        block_first = self.array("store.block_first_doc")
        block_offsets = self.array("store.block_offsets")
        by_block: dict[int, list[int]] = {}
        for doc_id in doc_ids:
            if not (0 <= doc_id < self.footer.num_docs):
                raise IndexError(f"doc id {doc_id} out of range")
            block = bisect.bisect_right(block_first, doc_id) - 1
            by_block.setdefault(block, []).append(doc_id)
        docs_by_id: dict[int, dict[str, Any]] = {}
        for block, ids in by_block.items():
            raw = self.array_slice("store.data", int(block_offsets[block]),
                                   int(block_offsets[block + 1] - block_offsets[block]))
            lines = zlib.decompress(raw.tobytes()).split(b"\n")
            first = int(block_first[block])
            for doc_id in ids:
                docs_by_id[doc_id] = json.loads(lines[doc_id - first])
        return [docs_by_id[d] for d in doc_ids]

    # --- stats -------------------------------------------------------------
    @property
    def num_docs(self) -> int:
        return self.footer.num_docs

    @property
    def num_docs_padded(self) -> int:
        return self.footer.num_docs_padded

    def field_meta(self, field: str) -> dict[str, Any]:
        return self.footer.fields.get(field, {})

    def term_stats(self, field: str, term: str) -> tuple[int, int]:
        """(df, max_tf) of one term — the inputs of the BM25 per-split score
        upper bound (search/pruning.py). Absent term → (0, 0). Served from
        the persisted `terms.max_tf` footer array when present (one 4-byte
        ranged read); older splits without it fall back to scanning the
        term's padded tf slice (pads are 0, so the max is unaffected).
        Scan results backfill a process-wide per-path cache so a reader
        reopened on the same (immutable) split never rescans."""
        cached = self._term_stats.get((field, term))
        if cached is not None:
            return cached
        info = self.lookup_term(field, term)
        if info is None:
            stats = (0, 0)
        elif self.has_array(f"inv.{field}.terms.max_tf"):
            max_tf = self.array_slice(f"inv.{field}.terms.max_tf",
                                      info.ordinal, 1)
            stats = (info.df, int(max_tf[0]))
        else:
            global_key = (self._stats_scope, field, term)
            stats = _GLOBAL_TERM_STATS.get(global_key)
            if stats is None:
                _ids, tfs = self.postings(field, info)
                stats = (info.df, int(tfs.max()) if tfs.size else 0)
                _GLOBAL_TERM_STATS.put(global_key, stats)
        self._term_stats[(field, term)] = stats
        return stats

    # --- impact-ordered postings (format v3) --------------------------------
    def impact_info(self, field: str) -> Optional[dict[str, Any]]:
        """The field's impact descriptor ({"buckets","block","ordered"}) when
        its postings are impact-ordered with the v3 side arrays present,
        else None (v1/v2 splits, positions-recording fields, kill switch)."""
        info = self.field_meta(field).get("impact")
        if info and info.get("ordered") and self.has_array(
                f"inv.{field}.impact.bmax"):
            return info
        return None

    def impact_term_bounds(self, field: str,
                           info: TermInfo) -> tuple[np.ndarray, np.float64]:
        """(block_maxima u8, scale f64) for one term — per-IMPACT_BLOCK
        quantized upper bounds; `bmax * scale` bounds the query-time score
        of every posting in the block. Non-increasing across a term's
        blocks by construction (postings sorted by descending impact)."""
        bmax = self.array_slice(f"inv.{field}.impact.bmax",
                                info.post_off // IMPACT_BLOCK,
                                info.post_len // IMPACT_BLOCK)
        scale = self.array_slice(f"inv.{field}.impact.scale",
                                 info.ordinal, 1)[0]
        return bmax, scale

    def term_score_cap(self, field: str, term: str) -> Optional[float]:
        """Exact dequantized upper bound on the term's best query-time BM25
        score (boost 1), or None when the split has no impact arrays for
        the field. Strictly sharper than the `max_tf` formula bound — it
        reflects the actual best (tf, fieldnorm) pair in the split, not the
        norms-free worst case. Cached process-wide per path (immutable
        splits) alongside the term stats."""
        global_key = (self._stats_scope, field, term)
        cached = _GLOBAL_TERM_CAPS.get(global_key)
        if cached is not None:
            return cached[0]
        if self.impact_info(field) is None:
            cap = None
        else:
            info = self.lookup_term(field, term)
            if info is None:
                cap = 0.0
            else:
                # impact order puts the best posting first, so the first
                # block's max IS the term's max quant
                bmax, scale = self.impact_term_bounds(field, info)
                cap = float(bmax[0]) * float(scale) if bmax.size else 0.0
        _GLOBAL_TERM_CAPS.put(global_key, (cap,))
        return cap
