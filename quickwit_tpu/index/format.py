"""The split container format — TPU-first.

Role of the reference's split format (`docs/internals/split-format.md`,
`quickwit-directories/src/hot_directory.rs` + the tantivy file formats): one
immutable `.split` object holding the inverted index, columnar fast fields,
doc store and a "hotcache" so a searcher can open it with a single ranged GET.

TPU-first divergence from tantivy: tantivy's postings are block-compressed
variable-byte streams decoded by scalar CPU code. Here **every index structure
is a named little-endian ndarray** — postings are padded dense int32 arrays,
columns are contiguous padded buffers — so warmup is `storage.get_slice →
np.frombuffer → jax.device_put` with zero decode work, and kernel shapes are
static. The price is bytes on disk (quantified tradeoff the reference's
parquet experiment also makes, `docs/internals/tantivy-parquet-architecture.md`);
the win is that the hot loop never touches a branchy decoder.

Layout of a split file:

    [array arena ... 128-byte aligned arrays ...]
    [metadata JSON (the "hotcache": schema, stats, array registry)]
    [u64 metadata_len][8-byte MAGIC]

Array naming convention (see writer.py):
    inv.{field}.terms.blob / .offsets / .df / .post_off / .post_len
    inv.{field}.terms.max_tf
    inv.{field}.postings.ids / .tfs
    inv.{field}.positions.offsets / .data      (record="position" fields)
    inv.{field}.impact.quant / .bmax / .scale  (format v3, see index/impact.py)
    inv.{field}.fieldnorm
    col.{field}.values / .present / .ordinals / .dict_blob / .dict_offsets
    col.{field}.packed / .zmin / .zmax      (format v2, see docs/device-layout.md)
    store.data / store.block_offsets / store.block_first_doc

Format v2 stores eligible numeric fast-field columns frame-of-reference
bit-packed (`col.{field}.packed`, u8/u16/u32 deltas from the column min,
optionally GCD-scaled) instead of the full-width `col.{field}.values`,
plus per-512-doc-block min/max zonemaps (`.zmin`/`.zmax`). v1 splits (raw
full-width columns, no zonemaps) remain readable and searchable.

Format v3 stores each text field's postings **impact-ordered**: within a
term, postings are sorted by descending quantized BM25 contribution
(`inv.{field}.impact.quant`, u8 buckets), with per-128-posting block
maxima (`.bmax`, u8) and a per-term dequantization scale (`.scale`, f64)
whose product is a sound upper bound on the query-time score. Readers
treat the absence of the impact arrays as the v2/v1 fallback — every v3
structure is optional per field, so older splits stay searchable and
positions-recording fields simply keep doc order.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

MAGIC = b"QWTPU001"
FORMAT_VERSION = 3
# Versions this reader still opens: v1 splits carry raw full-width columns
# only; every v2 structure is optional per column and every v3 structure is
# optional per field, so the fallback is simply "the packed/zonemap/impact
# arrays are absent".
SUPPORTED_FORMAT_VERSIONS = (1, 2, 3)
ALIGN = 128

# Zonemap granularity: per-block min/max over present docs, one block =
# ZONEMAP_BLOCK doc lanes. Divides DOC_PAD so padded tails are whole blocks.
ZONEMAP_BLOCK = 512

# Docs are padded to a multiple of DOC_PAD (8 sublanes x 128 lanes) so dense
# per-doc arrays tile cleanly onto the VPU; postings to POSTING_PAD lanes.
DOC_PAD = 1024
POSTING_PAD = 128

# Default number of tail bytes fetched on open; one GET covers the metadata
# footer for typical splits (role of the reference's footer_size_hint).
DEFAULT_FOOTER_HINT = 1 << 20


def pad_to(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


@dataclass(frozen=True)
class ArrayMeta:
    name: str
    dtype: str       # numpy dtype string, little-endian ("<i4", "<i8", "<f8", "|u1")
    shape: tuple[int, ...]
    offset: int      # byte offset in the split file
    nbytes: int

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "dtype": self.dtype, "shape": list(self.shape),
                "offset": self.offset, "nbytes": self.nbytes}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ArrayMeta":
        return ArrayMeta(d["name"], d["dtype"], tuple(d["shape"]), d["offset"], d["nbytes"])


@dataclass
class SplitFooter:
    """Parsed split metadata — everything needed to plan a search and issue
    exact byte-range reads (the hotcache role)."""
    num_docs: int
    num_docs_padded: int
    arrays: dict[str, ArrayMeta]
    # field name -> {"type","tokenizer","record","fast","indexed",
    #               "num_terms","total_tokens","avg_len" (text),
    #               "min_value","max_value" (numeric cols), "cardinality"}
    fields: dict[str, dict[str, Any]]
    time_range: Optional[tuple[int, int]] = None  # micros, inclusive
    doc_mapping_uid: str = "default"
    extra: dict[str, Any] = None  # type: ignore[assignment]

    def to_json_bytes(self) -> bytes:
        doc = {
            "format_version": FORMAT_VERSION,
            "num_docs": self.num_docs,
            "num_docs_padded": self.num_docs_padded,
            "arrays": [a.to_dict() for a in self.arrays.values()],
            "fields": self.fields,
            "time_range": list(self.time_range) if self.time_range else None,
            "doc_mapping_uid": self.doc_mapping_uid,
            "extra": self.extra or {},
        }
        return json.dumps(doc, separators=(",", ":")).encode()

    @staticmethod
    def from_json_bytes(data: bytes) -> "SplitFooter":
        doc = json.loads(data)
        if doc.get("format_version") not in SUPPORTED_FORMAT_VERSIONS:
            raise ValueError(f"unsupported split format version {doc.get('format_version')}")
        arrays = {a["name"]: ArrayMeta.from_dict(a) for a in doc["arrays"]}
        tr = doc.get("time_range")
        return SplitFooter(
            num_docs=doc["num_docs"],
            num_docs_padded=doc["num_docs_padded"],
            arrays=arrays,
            fields=doc["fields"],
            time_range=(tr[0], tr[1]) if tr else None,
            doc_mapping_uid=doc.get("doc_mapping_uid", "default"),
            extra=doc.get("extra", {}),
        )


class SplitFileBuilder:
    """Accumulates named arrays + metadata, emits the final file bytes."""

    def __init__(self) -> None:
        self._chunks: list[bytes] = []
        self._arrays: dict[str, ArrayMeta] = {}
        self._pos = 0

    def add_array(self, name: str, array: np.ndarray) -> None:
        if name in self._arrays:
            raise ValueError(f"duplicate array {name!r}")
        arr = np.ascontiguousarray(array)
        # normalize to little-endian
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        pad = pad_to(self._pos, ALIGN) - self._pos
        if pad:
            self._chunks.append(b"\x00" * pad)
            self._pos += pad
        data = arr.tobytes()
        dtype_str = arr.dtype.str if arr.dtype.kind != "u" or arr.dtype.itemsize != 1 else "|u1"
        self._arrays[name] = ArrayMeta(name, arr.dtype.str, arr.shape, self._pos, len(data))
        self._chunks.append(data)
        self._pos += len(data)

    def finish(self, footer: SplitFooter) -> bytes:
        footer.arrays = dict(self._arrays)
        meta = footer.to_json_bytes()
        parts = self._chunks + [meta, len(meta).to_bytes(8, "little"), MAGIC]
        return b"".join(parts)


def read_footer(get_slice, file_len: int, footer_hint: int = DEFAULT_FOOTER_HINT) -> SplitFooter:
    """Parse the footer with at most two ranged reads.

    `get_slice(start, end) -> bytes`. First read grabs the last
    min(file_len, footer_hint) bytes (the single-GET open the hotcache design
    targets); a second read happens only if the metadata is larger.
    """
    tail_len = min(file_len, footer_hint)
    tail = get_slice(file_len - tail_len, file_len)
    if tail[-8:] != MAGIC:
        raise ValueError("not a quickwit_tpu split file (bad magic)")
    meta_len = int.from_bytes(tail[-16:-8], "little")
    if meta_len + 16 > file_len:
        raise ValueError("corrupt split footer: metadata length exceeds file")
    if meta_len + 16 <= tail_len:
        meta = tail[tail_len - 16 - meta_len: tail_len - 16]
    else:
        meta = get_slice(file_len - 16 - meta_len, file_len - 16)
    return SplitFooter.from_json_bytes(meta)
