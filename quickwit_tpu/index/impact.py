"""Impact-ordered postings: write-time BM25 quantization (format v3).

Role of the impact-sorted index family (BM25S, arxiv 2407.03618): each
posting's BM25 contribution is fully determined at write time (tf, the
doc's fieldnorm, the field's avg_len and the term's df are all frozen
when the split seals), so the score can be precomputed, quantized into
u8 buckets, and the postings stored sorted by descending impact. At
query time a pushed-down threshold then prunes whole 128-posting blocks
— and because the order is by impact, the live set is a *prefix*, so the
reader can skip staging the tail entirely.

Soundness contract (property-asserted in tests/test_impact_postings.py):

  quant[i] * scale  >=  exact query-time score of posting i   (always)

with `scale` persisted per term as f64. The quantized value is used ONLY
for skipping; survivors are rescored by the seed `ops.bm25` path, so
results stay bit-identical to doc-ordered execution.

Tie-break equivalence: the sort key is the *f32* score exactly as the
query kernel computes it (`_exact_scores_f32` mirrors
`ops.bm25.score_postings` operation by operation), secondary key doc id
ascending. Equal-f32-score groups therefore stay contiguous and
doc-ascending, so `lax.top_k`'s lowest-index-wins tie rule selects the
same docs in the same order as the seed doc-ordered layout for score
sorts. Field-primary sorts over impact-ordered postings are NOT
tie-equivalent and must not take the posting-space path (the executor
gates on `PPostings.impact_ordered`).

Everything here is plain numpy on host wire-state — no jax, no device
sync (this module is in qwlint QW001/QW002 scope).
"""

from __future__ import annotations

import numpy as np

from ..ops.bm25 import B, K1, idf as bm25_idf

# One impact block == POSTING_PAD, so per-term posting ranges (always
# 128-multiples, see writer.py arena layout) cover whole blocks and a
# block never straddles two terms.
IMPACT_BLOCK = 128
IMPACT_BUCKETS = 255
# Headroom on the persisted scale so `quant * scale` stays an upper bound
# even against scores recomputed through a differently-rounded path
# (e.g. the f64 "exact" score in the property suite, ~1e-7 relative off
# the f32 kernel value).
SCALE_MARGIN = 1e-4

_F32 = np.float32


def exact_scores_f32(tfs: np.ndarray, doc_ids: np.ndarray,
                     fieldnorms: np.ndarray, avg_len: float,
                     idf_value) -> np.ndarray:
    """The query kernel's score, replicated in numpy f32.

    Must stay operation-for-operation identical to
    `ops.bm25.score_postings` (same casts, same constant placement, same
    maximum clamps) so the write-time sort key equals the query-time f32
    score bit-for-bit — that equality is what makes impact-ordered
    tie-breaks reproduce the doc-ordered ones.
    """
    tf = tfs.astype(_F32)
    idx = np.clip(doc_ids, 0, fieldnorms.shape[0] - 1)
    norms = fieldnorms[idx].astype(_F32)
    avg = np.maximum(_F32(avg_len), _F32(1e-9))
    denom = tf + _F32(K1) * (_F32(1.0 - B) + _F32(B) * norms / avg)
    return (_F32(idf_value) * _F32(K1 + 1.0)) * tf / np.maximum(denom,
                                                                _F32(1e-9))


def quantize_term(scores_f32: np.ndarray):
    """(quant u8, scale f64) for one term's exact f32 scores.

    quant = ceil(score * 255 / max_score), scale = max_score * (1+margin)
    / 255, so quant*scale >= score*(1+margin) > score for every posting,
    and the first (highest-impact) posting lands exactly on bucket 255.
    """
    if scores_f32.size == 0:
        return (np.zeros(0, dtype=np.uint8), np.float64(0.0))
    s64 = scores_f32.astype(np.float64)
    m = s64.max()
    if not (m > 0.0):
        return (np.zeros(scores_f32.shape[0], dtype=np.uint8),
                np.float64(0.0))
    q = np.ceil(s64 * (np.float64(IMPACT_BUCKETS) / m))
    q = np.minimum(q, np.float64(IMPACT_BUCKETS)).astype(np.uint8)
    scale = m * (1.0 + SCALE_MARGIN) / np.float64(IMPACT_BUCKETS)
    return q, scale


def build_impact_arrays(ids_arena: np.ndarray, tfs_arena: np.ndarray,
                        post_offs: np.ndarray, dfs: np.ndarray,
                        fieldnorms: np.ndarray, avg_len: float,
                        num_docs: int):
    """Impact-order every term's postings and emit the v3 side arrays.

    Inputs are the writer's padded posting arenas (pad lanes: id ==
    sentinel >= num_docs, tf == 0) plus the per-term layout. Returns
    (ids, tfs, quant, bmax, scales):

      ids/tfs  — copies of the arenas with each term's real postings
                 stably reordered by (-f32_score, doc_id); pads untouched
      quant    — u8 per posting (pads 0), aligned with the arenas
      bmax     — u8 per IMPACT_BLOCK postings, max quant in the block;
                 non-increasing within a term by construction
      scales   — f64 per term
    """
    ids = np.array(ids_arena, dtype=np.int32, copy=True)
    tfs = np.array(tfs_arena, dtype=np.int32, copy=True)
    quant = np.zeros(ids.shape[0], dtype=np.uint8)
    num_terms = post_offs.shape[0]
    scales = np.zeros(num_terms, dtype=np.float64)
    # one bulk host decode for the whole loop instead of two per-term
    # casts (inputs are host numpy wire-state by module contract)
    post_offs_l = post_offs.tolist()
    dfs_l = dfs.tolist()
    for t in range(num_terms):
        lo = post_offs_l[t]
        df = dfs_l[t]
        if df <= 0:
            continue
        term_ids = ids[lo:lo + df]
        term_tfs = tfs[lo:lo + df]
        idf32 = _F32(bm25_idf(num_docs, df))
        s32 = exact_scores_f32(term_tfs, term_ids, fieldnorms, avg_len,
                               idf32)
        # lexsort: last key is primary — descending score, then doc asc
        order = np.lexsort((term_ids, -s32))
        ids[lo:lo + df] = term_ids[order]
        tfs[lo:lo + df] = term_tfs[order]
        q, scale = quantize_term(s32[order])
        quant[lo:lo + df] = q
        scales[t] = scale
    nblocks = ids.shape[0] // IMPACT_BLOCK
    bmax = quant[:nblocks * IMPACT_BLOCK].reshape(
        nblocks, IMPACT_BLOCK).max(axis=1)
    return ids, tfs, quant, bmax, scales
