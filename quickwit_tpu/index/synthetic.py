"""Fast synthetic split generation for benchmarks and dry-runs.

Builds hdfs-logs-shaped splits (timestamp + tenant_id + severity_text +
tokenized body) directly as numpy arrays through `SplitFileBuilder`,
bypassing the per-document Python writer loop so multi-million-doc splits
materialize in seconds. The output is byte-identical in format to
`SplitWriter` output and is read through the normal `SplitReader` path, so
benchmarks exercise the real search stack.
"""

from __future__ import annotations

import json
import zlib
from typing import Optional

import numpy as np

from ..models.doc_mapper import DocMapper, FieldMapping, FieldType
from .format import DOC_PAD, POSTING_PAD, SplitFileBuilder, SplitFooter, pad_to
from .writer import apply_impact_ordering

# sorted — these double as dictionary/term ordinals
SEVERITIES = ["DEBUG", "ERROR", "INFO", "WARN"]
_SEVERITY_P = [0.30, 0.10, 0.45, 0.15]

HDFS_MAPPER = DocMapper(
    field_mappings=[
        FieldMapping("timestamp", FieldType.DATETIME, fast=True,
                     input_formats=("unix_timestamp",)),
        FieldMapping("tenant_id", FieldType.U64, fast=True),
        FieldMapping("severity_text", FieldType.TEXT, tokenizer="raw", fast=True),
        FieldMapping("body", FieldType.TEXT),
    ],
    timestamp_field="timestamp",
    default_search_fields=("body",),
)

# zipf-ish body vocabulary; term 0 is the frequent term, tail terms are
# rare. Sized to the real hdfs-logs corpus scale the reference benchmarks
# against (tutorial-hdfs-logs-distributed-search-aws-s3.md:9): ~10^5
# distinct body terms, ~20 tokens/doc — NOT a toy 1k-term vocabulary, so
# term-dictionary cost and posting-padding blowup are measured at
# realistic shape (round-4 verdict weak-point #6).
_BODY_VOCAB_SIZE = 100_000
_BODY_TOKENS_PER_DOC = 20
_BODY_TERM_WIDTH = 6


def body_term(k: int) -> str:
    """The k-th body vocabulary term (shared by bench queries + tests)."""
    return f"term{k:0{_BODY_TERM_WIDTH}d}"


def synthetic_hdfs_split(num_docs: int, seed: int = 0,
                         start_ts: int = 1_600_000_000,
                         span_seconds: int = 7 * 86400,
                         store_docs: bool = False) -> bytes:
    """One split of `num_docs` synthetic hdfs-logs docs (sorted by time)."""
    rng = np.random.RandomState(seed)
    num_docs_padded = pad_to(num_docs, DOC_PAD)
    builder = SplitFileBuilder()
    fields: dict = {}

    # --- timestamp column (sorted, micros) --------------------------------
    ts_seconds = np.sort(rng.randint(0, span_seconds, size=num_docs)) + start_ts
    ts_micros = np.zeros(num_docs_padded, dtype=np.int64)
    ts_micros[:num_docs] = ts_seconds.astype(np.int64) * 1_000_000
    present = np.zeros(num_docs_padded, dtype=np.uint8)
    present[:num_docs] = 1
    builder.add_array("col.timestamp.values", ts_micros)
    builder.add_array("col.timestamp.present", present)
    fields["timestamp"] = {
        "type": "datetime", "fast": True, "column_kind": "numeric",
        "min_value": int(ts_micros[0]), "max_value": int(ts_micros[num_docs - 1]),
    }

    # --- tenant_id column --------------------------------------------------
    tenants = rng.randint(0, 10, size=num_docs).astype(np.int64)
    tenant_col = np.zeros(num_docs_padded, dtype=np.int64)
    tenant_col[:num_docs] = tenants
    builder.add_array("col.tenant_id.values", tenant_col)
    builder.add_array("col.tenant_id.present", present)
    fields["tenant_id"] = {
        "type": "u64", "fast": True, "column_kind": "numeric",
        "min_value": 0, "max_value": 9,
    }

    # --- severity: ordinal column + inverted field ------------------------
    sev = rng.choice(len(SEVERITIES), size=num_docs, p=_SEVERITY_P).astype(np.int32)
    _write_categorical(builder, fields, "severity_text", SEVERITIES, sev,
                       num_docs, num_docs_padded)

    # --- body: zipf terms, inverted only ----------------------------------
    _write_body(builder, fields, rng, num_docs, num_docs_padded)

    # --- doc store (optional; benchmarks usually skip fetch phase) --------
    if store_docs:
        _write_store(builder, ts_seconds, tenants, sev, num_docs)
    else:
        builder.add_array("store.data", np.zeros(0, dtype=np.uint8))
        builder.add_array("store.block_offsets", np.array([0], dtype=np.int64))
        builder.add_array("store.block_first_doc", np.array([0], dtype=np.int32))

    # raw-ingest size estimate (what a user would have POSTed as ndjson),
    # for the split-bytes-vs-raw padding-blowup metric the bench reports:
    # per-doc JSON skeleton + 10-digit ts + tenant digit + severity string
    # + `tokens_per_doc` space-joined body terms
    skeleton = len('{"timestamp": , "tenant_id": , '
                   '"severity_text": "", "body": ""}\n')
    sev_char_total = int(np.array([len(s) for s in SEVERITIES],
                                  dtype=np.int64)[sev].sum())
    body_chars = _BODY_TOKENS_PER_DOC * (len(body_term(0)) + 1) - 1
    raw_json_est = int(num_docs * (skeleton + 10 + 1 + body_chars)
                       + sev_char_total)
    footer = SplitFooter(
        num_docs=num_docs, num_docs_padded=num_docs_padded, arrays={},
        fields=fields,
        time_range=(int(ts_micros[0]), int(ts_micros[num_docs - 1])),
        extra={"synthetic": True, "raw_json_bytes_est": raw_json_est},
    )
    return builder.finish(footer)


def _write_categorical(builder, fields, name, vocab, ordinals_raw,
                       num_docs, num_docs_padded):
    """Dict-encoded fast column + inverted postings for a categorical field.

    vocab must be sorted (ordinals are dictionary ordinals)."""
    assert list(vocab) == sorted(vocab)
    ordinals = np.full(num_docs_padded, -1, dtype=np.int32)
    ordinals[:num_docs] = ordinals_raw
    builder.add_array(f"col.{name}.ordinals", ordinals)
    blob = "".join(vocab).encode()
    offsets = np.zeros(len(vocab) + 1, dtype=np.int64)
    acc = 0
    for i, term in enumerate(vocab):
        acc += len(term)
        offsets[i + 1] = acc
    builder.add_array(f"col.{name}.dict_blob", np.frombuffer(blob, dtype=np.uint8))
    builder.add_array(f"col.{name}.dict_offsets", offsets)

    # postings per term
    order = np.argsort(ordinals_raw, kind="stable")
    sorted_ords = ordinals_raw[order]
    starts = np.searchsorted(sorted_ords, np.arange(len(vocab)))
    ends = np.searchsorted(sorted_ords, np.arange(len(vocab)), side="right")
    dfs = (ends - starts).astype(np.int32)
    post_lens = np.array([pad_to(max(int(d), 1), POSTING_PAD) for d in dfs],
                         dtype=np.int32)
    post_offs = np.zeros(len(vocab), dtype=np.int64)
    np.cumsum(post_lens[:-1], out=post_offs[1:])
    total = int(post_lens.sum())
    ids_arena = np.full(total, num_docs_padded, dtype=np.int32)
    tfs_arena = np.zeros(total, dtype=np.int32)
    for t in range(len(vocab)):
        ids = order[starts[t]:ends[t]].astype(np.int32)
        ids_arena[post_offs[t]: post_offs[t] + dfs[t]] = ids
        tfs_arena[post_offs[t]: post_offs[t] + dfs[t]] = 1
    term_blob_parts = [t.encode() for t in vocab]
    term_offsets = np.zeros(len(vocab) + 1, dtype=np.int64)
    acc = 0
    for i, t in enumerate(term_blob_parts):
        acc += len(t)
        term_offsets[i + 1] = acc
    builder.add_array(f"inv.{name}.terms.blob",
                      np.frombuffer(b"".join(term_blob_parts), dtype=np.uint8))
    builder.add_array(f"inv.{name}.terms.offsets", term_offsets)
    builder.add_array(f"inv.{name}.terms.df", dfs)
    builder.add_array(f"inv.{name}.terms.post_off", post_offs)
    builder.add_array(f"inv.{name}.terms.post_len", post_lens)
    builder.add_array(f"inv.{name}.postings.ids", ids_arena)
    builder.add_array(f"inv.{name}.postings.tfs", tfs_arena)
    norms = np.zeros(num_docs_padded, dtype=np.int32)
    norms[:num_docs] = 1
    builder.add_array(f"inv.{name}.fieldnorm", norms)
    fields[name] = {
        "type": "text", "tokenizer": "raw", "record": "basic", "indexed": True,
        "fast": True, "column_kind": "ordinal", "cardinality": len(vocab),
        "num_terms": len(vocab), "total_tokens": num_docs,
        "avg_len": 1.0,
    }


def _write_body(builder, fields, rng, num_docs, num_docs_padded):
    """Zipf-distributed body terms, fully vectorized (one draw + one sort),
    so 10M-doc benchmark splits generate in seconds."""
    vocab = [body_term(k) for k in range(_BODY_VOCAB_SIZE)]
    draws = rng.zipf(1.5, size=num_docs * _BODY_TOKENS_PER_DOC) - 1
    flat_terms = np.minimum(draws, _BODY_VOCAB_SIZE - 1).astype(np.int64)
    flat_docs = np.repeat(np.arange(num_docs, dtype=np.int64), _BODY_TOKENS_PER_DOC)
    # dedupe (term, doc) pairs -> tf=1 postings sorted by (term, doc)
    keys = np.unique(flat_terms * num_docs_padded + flat_docs)
    terms_sorted = (keys // num_docs_padded).astype(np.int32)
    docs_sorted = (keys % num_docs_padded).astype(np.int32)
    starts = np.searchsorted(terms_sorted, np.arange(_BODY_VOCAB_SIZE))
    ends = np.searchsorted(terms_sorted, np.arange(_BODY_VOCAB_SIZE), side="right")
    dfs = (ends - starts).astype(np.int32)
    post_lens = np.array([pad_to(max(int(d), 1), POSTING_PAD) for d in dfs],
                         dtype=np.int32)
    post_offs = np.zeros(_BODY_VOCAB_SIZE, dtype=np.int64)
    np.cumsum(post_lens[:-1], out=post_offs[1:])
    total = int(post_lens.sum())
    ids_arena = np.full(total, num_docs_padded, dtype=np.int32)
    tfs_arena = np.zeros(total, dtype=np.int32)
    # scatter each term's slice into its padded arena range, vectorized:
    # target positions = post_off[term] + rank within term
    ranks = np.arange(len(keys), dtype=np.int64) - starts[terms_sorted]
    positions = post_offs[terms_sorted] + ranks
    ids_arena[positions] = docs_sorted
    tfs_arena[positions] = 1
    norms = np.zeros(num_docs_padded, dtype=np.int32)
    np.add.at(norms, docs_sorted, 1)
    term_offsets = (np.arange(_BODY_VOCAB_SIZE + 1, dtype=np.int64)
                    * len(body_term(0)))
    avg_len = float(norms[:num_docs].mean()) if num_docs else 0.0
    # same impact-ordering pass as the real writer (format v3), so bench
    # splits exercise the block-max prefix cutoff; QW_DISABLE_IMPACT=1
    # builds the doc-ordered comparator
    body_arrays = {
        "postings.ids": ids_arena, "postings.tfs": tfs_arena,
        "terms.df": dfs, "terms.post_off": post_offs, "fieldnorm": norms,
    }
    impact_meta = apply_impact_ordering(body_arrays, avg_len, num_docs)
    builder.add_array("inv.body.terms.blob",
                      np.frombuffer("".join(vocab).encode(), dtype=np.uint8))
    builder.add_array("inv.body.terms.offsets", term_offsets)
    builder.add_array("inv.body.terms.df", dfs)
    builder.add_array("inv.body.terms.post_off", post_offs)
    builder.add_array("inv.body.terms.post_len", post_lens)
    builder.add_array("inv.body.terms.max_tf",
                      np.maximum.reduceat(body_arrays["postings.tfs"],
                                          post_offs).astype(np.int32))
    builder.add_array("inv.body.postings.ids", body_arrays["postings.ids"])
    builder.add_array("inv.body.postings.tfs", body_arrays["postings.tfs"])
    builder.add_array("inv.body.fieldnorm", norms)
    if impact_meta is not None:
        builder.add_array("inv.body.impact.quant",
                          body_arrays["impact.quant"])
        builder.add_array("inv.body.impact.bmax", body_arrays["impact.bmax"])
        builder.add_array("inv.body.impact.scale",
                          body_arrays["impact.scale"])
    fields["body"] = {
        "type": "text", "tokenizer": "default", "record": "basic",
        "indexed": True, "num_terms": _BODY_VOCAB_SIZE,
        "total_tokens": int(norms.sum()),
        "avg_len": avg_len,
    }
    if impact_meta is not None:
        fields["body"]["impact"] = impact_meta


SO_MAPPER = DocMapper(
    field_mappings=[
        FieldMapping("creation_date", FieldType.DATETIME, fast=True,
                     input_formats=("unix_timestamp",)),
        FieldMapping("body", FieldType.TEXT, record="position"),
    ],
    timestamp_field="creation_date",
    default_search_fields=("body",),
)

# like the body vocabulary above: sized so phrase search runs against a
# realistic term dictionary, not a toy one (tokens stay at 12 — the
# positional (term, doc, position) sort is the generation bottleneck and
# the >=20-token directive targets the flagship hdfs corpus)
_SO_VOCAB_SIZE = 50_000
_SO_TOKENS_PER_DOC = 12
_SO_TERM_WIDTH = 6


def so_term(k: int) -> str:
    """The k-th stackoverflow vocabulary term (bench queries + tests)."""
    return f"t{k:0{_SO_TERM_WIDTH}d}"


def synthetic_stackoverflow_split(num_docs: int, seed: int = 0,
                                  start_ts: int = 1_500_000_000
                                  ) -> bytes:
    """A stackoverflow-shaped split: positional body postings for BM25
    phrase queries (BASELINE config #4). Fully vectorized: one zipf draw +
    one lexicographic sort produce the (term, doc, position) postings."""
    rng = np.random.RandomState(seed)
    num_docs_padded = pad_to(num_docs, DOC_PAD)
    builder = SplitFileBuilder()
    fields: dict = {}

    ts_seconds = np.sort(rng.randint(0, 90 * 86400, size=num_docs)) + start_ts
    ts_micros = np.zeros(num_docs_padded, dtype=np.int64)
    ts_micros[:num_docs] = ts_seconds.astype(np.int64) * 1_000_000
    present = np.zeros(num_docs_padded, dtype=np.uint8)
    present[:num_docs] = 1
    builder.add_array("col.creation_date.values", ts_micros)
    builder.add_array("col.creation_date.present", present)
    fields["creation_date"] = {
        "type": "datetime", "fast": True, "column_kind": "numeric",
        "min_value": int(ts_micros[0]),
        "max_value": int(ts_micros[num_docs - 1]),
    }

    vocab = [so_term(k) for k in range(_SO_VOCAB_SIZE)]
    length = _SO_TOKENS_PER_DOC
    draws = rng.zipf(1.4, size=num_docs * length) - 1
    flat_terms = np.minimum(draws, _SO_VOCAB_SIZE - 1).astype(np.int64)
    flat_docs = np.repeat(np.arange(num_docs, dtype=np.int64), length)
    flat_pos = np.tile(np.arange(length, dtype=np.int64), num_docs)
    # sort by (term, doc, position): groups become term postings with
    # each (term, doc) pair's positions contiguous and ascending
    order = np.argsort(flat_terms * (num_docs * length)
                       + flat_docs * length + flat_pos, kind="stable")
    terms_s = flat_terms[order]
    docs_s = flat_docs[order]
    pos_s = flat_pos[order].astype(np.int32)
    pair_key = terms_s * num_docs + docs_s
    boundary = np.concatenate([[True], pair_key[1:] != pair_key[:-1]])
    pair_starts = np.nonzero(boundary)[0]
    pair_terms = terms_s[pair_starts]
    pair_docs = docs_s[pair_starts].astype(np.int32)
    pair_tfs = np.diff(np.append(pair_starts, len(pair_key))).astype(np.int32)

    starts = np.searchsorted(pair_terms, np.arange(_SO_VOCAB_SIZE))
    ends = np.searchsorted(pair_terms, np.arange(_SO_VOCAB_SIZE),
                           side="right")
    dfs = (ends - starts).astype(np.int32)
    post_lens = np.array([pad_to(max(int(d), 1), POSTING_PAD) for d in dfs],
                         dtype=np.int32)
    post_offs = np.zeros(_SO_VOCAB_SIZE, dtype=np.int64)
    np.cumsum(post_lens[:-1], out=post_offs[1:])
    total = int(post_lens.sum())
    ids_arena = np.full(total, num_docs_padded, dtype=np.int32)
    tfs_arena = np.zeros(total, dtype=np.int32)
    ranks = np.arange(len(pair_terms), dtype=np.int64) - starts[pair_terms]
    slots = post_offs[pair_terms] + ranks
    ids_arena[slots] = pair_docs
    tfs_arena[slots] = pair_tfs
    # positions arena: offsets indexed by posting slot; data rides the
    # (term, doc, position) sort order directly
    pos_counts = np.zeros(total, dtype=np.int64)
    pos_counts[slots] = pair_tfs
    pos_offsets = np.zeros(total + 1, dtype=np.int64)
    np.cumsum(pos_counts, out=pos_offsets[1:])

    term_offsets = (np.arange(_SO_VOCAB_SIZE + 1, dtype=np.int64)
                    * len(so_term(0)))
    builder.add_array("inv.body.terms.blob",
                      np.frombuffer("".join(vocab).encode(), dtype=np.uint8))
    builder.add_array("inv.body.terms.offsets", term_offsets)
    builder.add_array("inv.body.terms.df", dfs)
    builder.add_array("inv.body.terms.post_off", post_offs)
    builder.add_array("inv.body.terms.post_len", post_lens)
    builder.add_array("inv.body.postings.ids", ids_arena)
    builder.add_array("inv.body.postings.tfs", tfs_arena)
    builder.add_array("inv.body.positions.offsets", pos_offsets)
    builder.add_array("inv.body.positions.data", pos_s)
    norms = np.zeros(num_docs_padded, dtype=np.int32)
    norms[:num_docs] = length
    builder.add_array("inv.body.fieldnorm", norms)
    fields["body"] = {
        "type": "text", "tokenizer": "default", "record": "position",
        "indexed": True, "num_terms": _SO_VOCAB_SIZE,
        "total_tokens": num_docs * length, "avg_len": float(length),
    }

    builder.add_array("store.data", np.zeros(0, dtype=np.uint8))
    builder.add_array("store.block_offsets", np.array([0], dtype=np.int64))
    builder.add_array("store.block_first_doc", np.array([0], dtype=np.int32))
    footer = SplitFooter(
        num_docs=num_docs, num_docs_padded=num_docs_padded, arrays={},
        fields=fields,
        time_range=(int(ts_micros[0]), int(ts_micros[num_docs - 1])),
        extra={"synthetic": True},
    )
    return builder.finish(footer)


OTEL_BENCH_MAPPER = DocMapper(
    field_mappings=[
        FieldMapping("span_start_timestamp", FieldType.DATETIME, fast=True,
                     input_formats=("unix_timestamp",)),
        FieldMapping("span_duration_micros", FieldType.I64, fast=True),
        FieldMapping("service_name", FieldType.TEXT, tokenizer="raw",
                     fast=True),
    ],
    timestamp_field="span_start_timestamp",
    default_search_fields=(),
)

_OTEL_SERVICES = ["api", "auth", "billing", "cart", "search", "web"]


def synthetic_otel_split(num_docs: int, seed: int = 0,
                         start_ts: int = 1_700_000_000) -> bytes:
    """An otel-traces-shaped split (BASELINE config #5): span duration
    i64 fast column (log-normal micros), timestamp, service ordinal."""
    rng = np.random.RandomState(seed)
    num_docs_padded = pad_to(num_docs, DOC_PAD)
    builder = SplitFileBuilder()
    fields: dict = {}

    ts_seconds = np.sort(rng.randint(0, 3600, size=num_docs)) + start_ts
    ts_micros = np.zeros(num_docs_padded, dtype=np.int64)
    ts_micros[:num_docs] = ts_seconds.astype(np.int64) * 1_000_000
    present = np.zeros(num_docs_padded, dtype=np.uint8)
    present[:num_docs] = 1
    builder.add_array("col.span_start_timestamp.values", ts_micros)
    builder.add_array("col.span_start_timestamp.present", present)
    fields["span_start_timestamp"] = {
        "type": "datetime", "fast": True, "column_kind": "numeric",
        "min_value": int(ts_micros[0]),
        "max_value": int(ts_micros[num_docs - 1]),
    }

    durations = np.zeros(num_docs_padded, dtype=np.int64)
    durations[:num_docs] = np.exp(
        rng.normal(9.0, 1.5, size=num_docs)).astype(np.int64) + 1
    builder.add_array("col.span_duration_micros.values", durations)
    builder.add_array("col.span_duration_micros.present", present)
    fields["span_duration_micros"] = {
        "type": "i64", "fast": True, "column_kind": "numeric",
        "min_value": 1, "max_value": int(durations.max()),
    }

    services = rng.randint(0, len(_OTEL_SERVICES),
                           size=num_docs).astype(np.int32)
    _write_categorical(builder, fields, "service_name", _OTEL_SERVICES,
                       services, num_docs, num_docs_padded)

    builder.add_array("store.data", np.zeros(0, dtype=np.uint8))
    builder.add_array("store.block_offsets", np.array([0], dtype=np.int64))
    builder.add_array("store.block_first_doc", np.array([0], dtype=np.int32))
    footer = SplitFooter(
        num_docs=num_docs, num_docs_padded=num_docs_padded, arrays={},
        fields=fields,
        time_range=(int(ts_micros[0]), int(ts_micros[num_docs - 1])),
        extra={"synthetic": True},
    )
    return builder.finish(footer)


def _write_store(builder, ts_seconds, tenants, sev, num_docs):
    lines = []
    for i in range(num_docs):
        lines.append(json.dumps({
            "timestamp": int(ts_seconds[i]), "tenant_id": int(tenants[i]),
            "severity_text": SEVERITIES[int(sev[i])]},
            separators=(",", ":")).encode())
    block = zlib.compress(b"\n".join(lines), 1)
    builder.add_array("store.data", np.frombuffer(block, dtype=np.uint8))
    builder.add_array("store.block_offsets", np.array([0, len(block)], dtype=np.int64))
    builder.add_array("store.block_first_doc", np.array([0, num_docs], dtype=np.int32))
