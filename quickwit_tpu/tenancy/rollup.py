"""Cluster-wide tenant usage rollup.

A tenant's footprint is not one node's counters: its queries land on
whichever searcher the root fans to, its cold splits run on offload
workers, and under the DST harness its traffic spreads over sim nodes.
`merge_tenant_reports` folds any number of per-node
`TenancyRegistry.report()` payloads into one cluster view — counters sum,
identity fields (class, priority, weight, limits, metric_label) come from
the first node that knows the tenant — and
`collect_cluster_tenant_report` drives it over the live membership: the
local registry, every alive cluster member's
`/api/v1/developer/tenants` endpoint, and any configured offload worker
endpoints. Per-endpoint failures degrade to an `errors` entry instead of
failing the rollup (a dead peer must not hide the live ones).

Served behind `GET /api/v1/developer/tenants?scope=cluster`
(serve/rest.py); with `scope=local` (the default) the endpoint keeps its
single-node shape.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional

TENANTS_PATH = "/api/v1/developer/tenants"


def merge_tenant_reports(reports: list[dict]) -> dict[str, Any]:
    """Fold per-node tenancy reports into one cluster-scope report.

    Pure function (no I/O): the DST harness merges sim-node reports
    through the same code the REST endpoint uses against live peers."""
    tenants: dict[str, dict[str, Any]] = {}
    node_ids: list[str] = []
    enabled = False
    default_class: Optional[str] = None
    for rep in reports:
        if not isinstance(rep, dict):
            continue
        node_ids.append(str(rep.get("node_id", f"node-{len(node_ids)}")))
        enabled = enabled or bool(rep.get("enabled"))
        if default_class is None:
            default_class = rep.get("default_class")
        for tenant_id, entry in (rep.get("tenants") or {}).items():
            if not isinstance(entry, dict):
                continue
            slot = tenants.get(tenant_id)
            if slot is None:
                slot = tenants[tenant_id] = {
                    key: value for key, value in entry.items()
                    if key != "counters"}
                slot["counters"] = dict(entry.get("counters") or {})
                slot["nodes"] = 1
                continue
            slot["nodes"] += 1
            counters = slot["counters"]
            for key, value in (entry.get("counters") or {}).items():
                if isinstance(value, bool) or not isinstance(
                        value, (int, float)):
                    continue
                counters[key] = counters.get(key, 0) + value
    return {
        "scope": "cluster",
        "nodes": node_ids,
        "enabled": enabled,
        "default_class": default_class,
        "tenants": tenants,
    }


def _fetch_report(endpoint: str, timeout_secs: float) -> dict:
    """One peer's local-scope tenants report over REST."""
    base = endpoint if "://" in endpoint else f"http://{endpoint}"
    url = base.rstrip("/") + TENANTS_PATH
    with urllib.request.urlopen(url, timeout=timeout_secs) as resp:
        return json.loads(resp.read().decode("utf-8"))


def collect_cluster_tenant_report(node, timeout_secs: float = 2.0) -> dict:
    """The full rollup for `node`: local registry + alive cluster peers +
    configured offload worker endpoints. `node` is a serve.node.Node (or
    anything exposing `.config` and `.cluster` the same way)."""
    from ..observability.slo import SLO_TRACKER
    from .registry import GLOBAL_TENANCY

    local = GLOBAL_TENANCY.report()
    local["node_id"] = node.config.node_id
    reports: list[dict] = [local]
    errors: dict[str, str] = {}

    targets: list[tuple[str, str]] = []
    for member in node.cluster.members(alive_only=True):
        if member.node_id == node.config.node_id:
            continue
        if member.rest_endpoint:
            targets.append((member.node_id, member.rest_endpoint))
    offload_cfg = getattr(node.config, "offload", None) or {}
    for endpoint in offload_cfg.get("endpoints", ()):
        targets.append((f"offload:{endpoint}", endpoint))

    seen: set[str] = set()
    for name, endpoint in targets:
        if endpoint in seen:
            continue
        seen.add(endpoint)
        try:
            rep = _fetch_report(endpoint, timeout_secs)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            errors[name] = str(exc)
            continue
        rep.setdefault("node_id", name)
        reports.append(rep)

    merged = merge_tenant_reports(reports)
    merged["errors"] = errors
    merged["slo"] = SLO_TRACKER.report()
    merged["overload"] = local.get("overload")
    return merged
