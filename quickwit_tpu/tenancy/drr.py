"""Weighted deficit-round-robin scheduler for admission tickets.

Replaces the single FIFO ticket deque of `search/admission.py` with
per-tenant FIFO sub-queues served deficit-round-robin (Shreedhar &
Varghese): each visit tops a tenant's deficit up by `quantum * weight`,
and the tenant at the front of the round-robin ring is granted the head
of its queue once its deficit covers the ticket's byte cost. Over a
contended interval each tenant's admitted bytes converge to its weight
share, yet within one tenant order stays strictly FIFO.

Two properties the old FIFO queue guaranteed are preserved by
construction:

- **no starvation**: a waiting tenant's deficit grows by at least
  `quantum * weight` per ring revolution, so any finite-cost ticket is
  eventually granted — large requests cannot be starved by a stream of
  small ones (same argument as the old ticket queue, now per tenant);
- **single-tenant neutrality**: with one tenant the ring has one entry
  and grants degrade to exact FIFO — the scheduler with tenancy disabled
  is behaviorally the pre-tenancy scheduler.

NOT thread-safe: the caller (`HbmBudget`) already serializes on its
condition-variable lock, and a second lock here would only invite
lock-order bugs.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Optional

# Deficit top-up per visit for weight 1.0. Sized so a typical single-split
# staging footprint (tens of MB compact columns) is granted within a few
# ring revolutions.
DEFAULT_QUANTUM_BYTES = 64 << 20


class DrrTicket:
    __slots__ = ("seq", "tenant_id", "weight", "cost")

    def __init__(self, seq: int, tenant_id: str, weight: float, cost: int):
        self.seq = seq
        self.tenant_id = tenant_id
        self.weight = weight
        self.cost = cost

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"DrrTicket(seq={self.seq}, tenant={self.tenant_id!r}, "
                f"cost={self.cost})")


class DrrScheduler:
    def __init__(self, quantum_bytes: int = DEFAULT_QUANTUM_BYTES):
        self.quantum = quantum_bytes
        # Per-ticket scheduling cost floor. Deficit interleaving happens at
        # quantum granularity, so without a floor a stream of tiny tickets
        # rides one top-up for quantum/cost consecutive grants — tens of
        # thousands for KB-sized tickets — and every other tenant's latency
        # convoys behind the burst. Charging at least a quarter quantum per
        # grant (a per-query scheduling overhead, like a slot cost) bounds
        # any tenant's burst per ring visit to ~4x its weight; tickets at or
        # above typical staging footprints are unaffected.
        self._min_cost = max(1, quantum_bytes // 4)
        self._seq = itertools.count()
        self._queues: dict[str, deque[DrrTicket]] = {}
        self._deficit: dict[str, float] = {}
        self._weights: dict[str, float] = {}
        self._ring: deque[str] = deque()
        # the ticket currently scheduled next; sticky until removed so a
        # grantee waiting for budget space keeps its turn (head-of-line
        # semantics identical to the old FIFO head)
        self._grant: Optional[DrrTicket] = None

    def enqueue(self, tenant_id: str, weight: float, cost: int) -> DrrTicket:
        ticket = DrrTicket(next(self._seq), tenant_id,
                           max(float(weight), 1e-3),
                           max(int(cost), self._min_cost))
        queue = self._queues.get(tenant_id)
        if queue is None:
            self._queues[tenant_id] = deque((ticket,))
            self._deficit[tenant_id] = 0.0
            self._ring.append(tenant_id)
        else:
            queue.append(ticket)
        # latest weight wins: a tenant's class can be reconfigured between
        # queries without draining its queue
        self._weights[tenant_id] = ticket.weight
        return ticket

    def head(self) -> Optional[DrrTicket]:
        """The ticket whose turn it is. Runs DRR visits until some tenant's
        deficit covers its queue head; each visit adds `quantum * weight`,
        so the loop terminates in at most `ceil(max_cost / quantum)`
        revolutions of the ring."""
        if self._grant is None and self._ring:
            while True:
                tenant_id = self._ring[0]
                candidate = self._queues[tenant_id][0]
                if self._deficit[tenant_id] >= candidate.cost:
                    self._grant = candidate
                    break
                self._deficit[tenant_id] += \
                    self.quantum * self._weights[tenant_id]
                self._ring.rotate(-1)
        return self._grant

    def remove(self, ticket: DrrTicket, served: bool) -> None:
        """Drop a ticket — `served=True` after a grant (charges the
        tenant's deficit), `served=False` on timeout/shed (no charge: the
        tenant got nothing). A tenant whose queue empties leaves the ring
        and forfeits accumulated deficit — idle tenants must not bank
        credit (standard DRR reset)."""
        queue = self._queues.get(ticket.tenant_id)
        if queue is None:
            return
        try:
            queue.remove(ticket)
        except ValueError:
            return
        if served:
            self._deficit[ticket.tenant_id] = max(
                0.0, self._deficit[ticket.tenant_id] - ticket.cost)
        if self._grant is ticket:
            self._grant = None
        if not queue:
            del self._queues[ticket.tenant_id]
            self._deficit.pop(ticket.tenant_id, None)
            self._weights.pop(ticket.tenant_id, None)
            try:
                self._ring.remove(ticket.tenant_id)
            except ValueError:  # pragma: no cover - ring mirrors _queues
                pass

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def waiting_by_tenant(self) -> dict[str, int]:
        return {tenant: len(queue)
                for tenant, queue in self._queues.items()}
