"""Multi-tenant workload isolation.

Tenant identity (`context`), weighted deficit-round-robin admission
scheduling (`drr`), token-bucket quotas + accounting (`registry`) and
adaptive lowest-priority-first overload shedding (`overload`). See
docs/multi-tenancy.md for the end-to-end contract.
"""

from .context import (  # noqa: F401
    DEFAULT_CLASS, DEFAULT_TENANT, ES_FALLBACK_HEADER, MAX_PRIORITY,
    PRIORITY_CLASSES, TENANT_HEADER, TenantContext, bind_tenant,
    current_tenant, effective_tenant, tenant_scope,
)
from .drr import DrrScheduler, DrrTicket  # noqa: F401
from .overload import OVERLOAD, OverloadController, OverloadShed  # noqa: F401
from .registry import (  # noqa: F401
    GLOBAL_TENANCY, MAX_TENANT_LABELS, OVERFLOW_LABEL, TenancyRegistry,
    TenantRateLimited, configure_tenancy,
)
