"""Tenant identity and ambient propagation.

Multi-tenant scheduling needs a tenant attached to every query without
threading a parameter through every call signature in the serving stack.
`TenantContext` mirrors `common/deadline.py`: an immutable context object
carried by a `contextvars.ContextVar`, bound per-request with
`tenant_scope` and re-bound across thread-pool hops with `bind_tenant`
(contextvars do not propagate into pool worker threads).

A query with NO bound tenant is scheduled as `DEFAULT_TENANT` — a single
implicit tenant, under which weighted deficit-round-robin admission
degenerates to the exact FIFO the scheduler had before tenancy existed.
Tenancy being "off" is therefore not a separate code path, just the
one-tenant case of the same scheduler.

Priority classes are deliberately coarse — three bands, like an
inference-serving scheduler's interactive/batch split, not a continuous
priority space: classes are what operators reason about, and the shed
ladder of the overload controller needs discrete rungs.
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Optional

# class name -> (priority rank, DRR weight). Rank orders the overload shed
# ladder (lowest shed first); weight sets the fair-share ratio of admission
# bytes under contention.
PRIORITY_CLASSES: dict[str, tuple[int, float]] = {
    "interactive": (2, 4.0),
    "standard": (1, 2.0),
    "background": (0, 1.0),
}
DEFAULT_CLASS = "standard"
MAX_PRIORITY = max(rank for rank, _ in PRIORITY_CLASSES.values())

# REST header carrying the tenant id. `x-opaque-id` (the ES attribution
# header) is accepted as a fallback so unmodified ES clients land in the
# right bucket.
TENANT_HEADER = "x-qw-tenant"
ES_FALLBACK_HEADER = "x-opaque-id"


@dataclass(frozen=True)
class TenantContext:
    """Resolved identity of the tenant a query runs on behalf of."""

    tenant_id: str
    priority_class: str = DEFAULT_CLASS
    priority: int = PRIORITY_CLASSES[DEFAULT_CLASS][0]
    weight: float = PRIORITY_CLASSES[DEFAULT_CLASS][1]

    @classmethod
    def for_class(cls, tenant_id: str, priority_class: str = DEFAULT_CLASS,
                  weight: Optional[float] = None) -> "TenantContext":
        """Build a context from a class name; unknown classes map to the
        default class instead of failing — a typo'd header must degrade to
        standard service, not a 500."""
        if priority_class not in PRIORITY_CLASSES:
            priority_class = DEFAULT_CLASS
        rank, class_weight = PRIORITY_CLASSES[priority_class]
        return cls(tenant_id=tenant_id, priority_class=priority_class,
                   priority=rank,
                   weight=float(weight) if weight else class_weight)

    # --- wire format (additive optional request field) -------------------
    def to_wire(self) -> dict:
        """Compact dict for the leaf request wire field. The CLASS travels
        with the id so a remote leaf enforces the same scheduling band
        without sharing the root's tenant config."""
        return {"id": self.tenant_id, "class": self.priority_class}

    @classmethod
    def from_wire(cls, payload) -> Optional["TenantContext"]:
        if not isinstance(payload, dict) or not payload.get("id"):
            return None
        return cls.for_class(str(payload["id"]),
                             str(payload.get("class", DEFAULT_CLASS)))


# The implicit tenant of unlabeled traffic: one queue, standard class.
DEFAULT_TENANT = TenantContext.for_class("default", DEFAULT_CLASS)


# --- ambient propagation --------------------------------------------------

_CURRENT_TENANT: contextvars.ContextVar[Optional[TenantContext]] = (
    contextvars.ContextVar("quickwit_tpu_tenant", default=None))


def current_tenant() -> Optional[TenantContext]:
    """The tenant bound to this thread of execution, if any."""
    return _CURRENT_TENANT.get()


def effective_tenant() -> TenantContext:
    """The bound tenant, or the implicit default for unlabeled traffic."""
    return _CURRENT_TENANT.get() or DEFAULT_TENANT


@contextmanager
def tenant_scope(tenant: Optional[TenantContext]):
    token = _CURRENT_TENANT.set(tenant)
    try:
        yield tenant
    finally:
        _CURRENT_TENANT.reset(token)


def bind_tenant(fn: Callable, tenant: Optional[TenantContext] = None) -> Callable:
    """Wrap `fn` so it runs under `tenant` (default: the caller's current
    tenant). Needed for ThreadPoolExecutor hops, exactly like
    `bind_deadline` / `bind_profile`."""
    captured = tenant if tenant is not None else current_tenant()

    def wrapper(*args, **kwargs):
        with tenant_scope(captured):
            return fn(*args, **kwargs)

    return wrapper
