"""Tenant registry: configuration, token-bucket quotas, accounting.

One process-global registry (matching METRICS / SLOW_QUERY_LOG /
OVERLOAD) holds everything the serving stack needs to know about tenants:

- **config**: per-tenant priority class / weight overrides and rate
  limits, parsed from the node config's ``tenancy`` section;
- **resolution**: header value (or wire field at a leaf) -> a
  `TenantContext`. With tenancy disabled and no header, resolution
  returns None and the stack stays tenant-blind — the behavior-neutral
  off state;
- **quotas**: lazily-created `TokenBucket`s per tenant for QPS and
  staged-HBM-bytes/s, rejecting with `TenantRateLimited` (→ HTTP 429 +
  Retry-After);
- **accounting**: per-tenant counters mirrored into bounded-cardinality
  labeled metrics, and a JSON report for
  ``GET /api/v1/developer/tenants``.

Label cardinality: tenant ids are client-controlled strings, so they are
laundered through `metric_label` before becoming Prometheus label values —
long ids are hashed, and once `MAX_TENANT_LABELS` distinct ids have been
seen every further id collapses into the ``_other`` bucket. Configured
tenants always keep their own label (config size bounds them).
"""

from __future__ import annotations

import hashlib
from typing import Optional

from ..common.tower import TokenBucket
from ..observability.metrics import (
    TENANT_ADMISSION_WAIT, TENANT_EXECUTE_SECONDS_TOTAL,
    TENANT_QUERIES_TOTAL, TENANT_REJECTED_TOTAL, TENANT_SHED_TOTAL,
    TENANT_STAGED_BYTES_TOTAL,
)
from .context import DEFAULT_CLASS, DEFAULT_TENANT, TenantContext
from .overload import OVERLOAD
from ..common import sync

MAX_TENANT_LABELS = 64
_LABEL_ID_MAX_LEN = 32
OVERFLOW_LABEL = "_other"


class TenantRateLimited(Exception):
    """A tenant exceeded one of its token buckets. Carries the seconds
    until the bucket refills for the 429 Retry-After header."""

    def __init__(self, tenant_id: str, limit: str, retry_after_secs: float):
        self.tenant_id = tenant_id
        self.limit = limit  # "qps" | "staged_bytes"
        self.retry_after_secs = max(float(retry_after_secs), 0.0)
        super().__init__(
            f"tenant {tenant_id!r} over its {limit} limit; "
            f"retry after {self.retry_after_secs:.2f}s")


class TenancyRegistry:
    def __init__(self, config: Optional[dict] = None):
        self._lock = sync.lock("TenancyRegistry._lock")
        sync.register_shared(self, "TenancyRegistry")
        self.configure(config)

    # --- configuration ----------------------------------------------------
    def configure(self, config: Optional[dict]) -> None:
        """(Re)load from a ``tenancy`` config dict::

            {"enabled": true,
             "default_class": "standard",
             "default_tenant": "default",
             "default_limits": {"qps_limit": 50,
                                "staged_bytes_per_sec_limit": 1e9},
             "tenants": {"acme": {"class": "interactive",
                                  "weight": 8.0,
                                  "qps_limit": 100,
                                  "staged_bytes_per_sec_limit": 2e9}},
             "overload": {"enabled": true, "target_wait_secs": 0.5}}

        Unset limits mean unlimited. The overload section arms the global
        controller as a side effect so one config block governs the whole
        isolation stack."""
        config = dict(config or {})
        with self._lock:
            self.enabled = bool(config.get("enabled", False))
            self.default_class = str(
                config.get("default_class", DEFAULT_CLASS))
            self.default_tenant_id = str(
                config.get("default_tenant", DEFAULT_TENANT.tenant_id))
            self.default_limits = dict(config.get("default_limits") or {})
            self._specs: dict[str, dict] = {
                str(tid): dict(spec or {})
                for tid, spec in (config.get("tenants") or {}).items()}
            self._buckets: dict[tuple[str, str], Optional[TokenBucket]] = {}
            self._counters: dict[str, dict[str, float]] = {}
            self._labels: dict[str, str] = {}
        overload = config.get("overload")
        if overload:
            OVERLOAD.configure(
                target_wait_secs=overload.get("target_wait_secs"),
                enabled=overload.get("enabled"))

    def reset_usage(self) -> None:
        """Drop buckets/counters/labels, keep config — test isolation."""
        with self._lock:
            self._buckets.clear()
            self._counters.clear()
            self._labels.clear()

    # --- resolution -------------------------------------------------------
    def resolve(self, tenant_id: Optional[str]) -> Optional[TenantContext]:
        """Header/wire value -> TenantContext. No id + tenancy disabled
        -> None (the tenant-blind path existing tests exercise); no id +
        enabled -> the configured default tenant. An id is always honored,
        even with tenancy disabled, so a single labeled request can be
        attributed without flipping the global switch."""
        if not tenant_id:
            if not self.enabled:
                return None
            tenant_id = self.default_tenant_id
        tenant_id = str(tenant_id).strip()[:128]
        if not tenant_id:
            return None
        with self._lock:
            spec = self._specs.get(tenant_id, {})
            default_class = self.default_class
        return TenantContext.for_class(
            tenant_id, str(spec.get("class", default_class)),
            weight=spec.get("weight"))

    # --- quotas -----------------------------------------------------------
    def _limit_for(self, tenant_id: str, key: str):
        spec = self._specs.get(tenant_id, {})
        return spec.get(key, self.default_limits.get(key))

    def _bucket(self, tenant_id: str, kind: str) -> Optional[TokenBucket]:
        with self._lock:
            cache_key = (tenant_id, kind)
            if cache_key in self._buckets:
                return self._buckets[cache_key]
            limit_key = ("qps_limit" if kind == "qps"
                         else "staged_bytes_per_sec_limit")
            limit = self._limit_for(tenant_id, limit_key)
            bucket = None
            if limit is not None and float(limit) > 0:
                rate = float(limit)
                # one second of burst: a tenant can spend its whole
                # per-second allowance at once, then refills smoothly
                bucket = TokenBucket(rate_per_sec=rate, burst=rate)
            self._buckets[cache_key] = bucket
            return bucket

    def check_query_rate(self, tenant: TenantContext) -> None:
        """QPS bucket at root admission; cost 1 per root search."""
        bucket = self._bucket(tenant.tenant_id, "qps")
        if bucket is None:
            return
        if not bucket.try_acquire(1.0):
            self.note_rejected(tenant.tenant_id, "qps")
            raise TenantRateLimited(tenant.tenant_id, "qps",
                                    bucket.time_to_available(1.0))

    def charge_staged_bytes(self, tenant: TenantContext, nbytes: int) -> None:
        """Staged-bytes/s bucket at the HBM admission checkpoint. A query
        larger than one second's allowance drains the bucket fully instead
        of being permanently unadmittable — the hard byte ceiling is the
        HBM budget's job, this bucket only paces the *rate*."""
        if nbytes <= 0:
            return
        bucket = self._bucket(tenant.tenant_id, "staged_bytes")
        if bucket is None:
            return
        cost = min(float(nbytes), bucket.burst)
        if not bucket.try_acquire(cost):
            self.note_rejected(tenant.tenant_id, "staged_bytes")
            raise TenantRateLimited(tenant.tenant_id, "staged_bytes",
                                    bucket.time_to_available(cost))

    # --- bounded-cardinality labels ----------------------------------------
    def metric_label(self, tenant_id: str) -> str:
        with self._lock:
            label = self._labels.get(tenant_id)
            if label is not None:
                return label
            configured = tenant_id in self._specs \
                or tenant_id == self.default_tenant_id
            if not configured and len(self._labels) >= MAX_TENANT_LABELS:
                return OVERFLOW_LABEL
            if len(tenant_id) > _LABEL_ID_MAX_LEN:
                digest = hashlib.blake2b(tenant_id.encode("utf-8", "replace"),
                                         digest_size=6).hexdigest()
                label = f"t-{digest}"
            else:
                label = tenant_id
            self._labels[tenant_id] = label
            return label

    # --- accounting ---------------------------------------------------------
    def _count(self, tenant_id: str, field: str, amount: float = 1.0) -> None:
        with self._lock:
            counters = self._counters.setdefault(tenant_id, {})
            counters[field] = counters.get(field, 0.0) + amount

    def note_query(self, tenant_id: str, status: str = "ok") -> None:
        self._count(tenant_id, f"queries_{status}")
        TENANT_QUERIES_TOTAL.inc(tenant=self.metric_label(tenant_id),
                                 status=status)

    def note_shed(self, tenant_id: str, stage: str) -> None:
        self._count(tenant_id, "shed")
        TENANT_SHED_TOTAL.inc(tenant=self.metric_label(tenant_id),
                              stage=stage)

    def note_rejected(self, tenant_id: str, limit: str) -> None:
        self._count(tenant_id, "rejected")
        TENANT_REJECTED_TOTAL.inc(tenant=self.metric_label(tenant_id),
                                  limit=limit)

    def note_staged_bytes(self, tenant_id: str, nbytes: int) -> None:
        if nbytes <= 0:
            return
        self._count(tenant_id, "staged_bytes", float(nbytes))
        TENANT_STAGED_BYTES_TOTAL.inc(float(nbytes),
                                      tenant=self.metric_label(tenant_id))

    def note_admission_wait(self, tenant_id: str, wait_secs: float) -> None:
        self._count(tenant_id, "admission_wait_seconds", wait_secs)
        TENANT_ADMISSION_WAIT.observe(wait_secs,
                                      tenant=self.metric_label(tenant_id))

    def note_execute_seconds(self, tenant_id: str, seconds: float) -> None:
        if seconds <= 0:
            return
        self._count(tenant_id, "execute_seconds", seconds)
        TENANT_EXECUTE_SECONDS_TOTAL.inc(
            seconds, tenant=self.metric_label(tenant_id))

    # --- introspection ------------------------------------------------------
    def report(self) -> dict:
        """JSON body of ``GET /api/v1/developer/tenants``: configured and
        observed tenants with their class, limits and counters, plus the
        overload controller's live state."""
        with self._lock:
            tenant_ids = sorted(set(self._specs) | set(self._counters))
            specs = {tid: dict(self._specs.get(tid, {}))
                     for tid in tenant_ids}
            counters = {tid: dict(self._counters.get(tid, {}))
                        for tid in tenant_ids}
            enabled = self.enabled
            default_class = self.default_class
            default_limits = dict(self.default_limits)
        tenants = {}
        for tid in tenant_ids:
            spec = specs[tid]
            context = TenantContext.for_class(
                tid, str(spec.get("class", default_class)),
                weight=spec.get("weight"))
            tenants[tid] = {
                "class": context.priority_class,
                "priority": context.priority,
                "weight": context.weight,
                "limits": {
                    "qps": spec.get("qps_limit",
                                    default_limits.get("qps_limit")),
                    "staged_bytes_per_sec": spec.get(
                        "staged_bytes_per_sec_limit",
                        default_limits.get("staged_bytes_per_sec_limit")),
                },
                "counters": counters[tid],
                "metric_label": self.metric_label(tid),
            }
        return {"enabled": enabled, "default_class": default_class,
                "tenants": tenants, "overload": OVERLOAD.state()}


# Process-global registry: REST resolution, admission accounting and the
# developer endpoint share it; `serve/node.py` configures it from the node
# config's `tenancy` section.
GLOBAL_TENANCY = TenancyRegistry()


def configure_tenancy(config: Optional[dict]) -> TenancyRegistry:
    GLOBAL_TENANCY.configure(config)
    return GLOBAL_TENANCY
