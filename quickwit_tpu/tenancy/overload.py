"""Adaptive overload control: shed lowest-priority-first.

The serving stack already sheds *expired* queries at checkpoints
(admission, batcher dispatch, group boundaries). That protects each query's
deadline but not the system: under sustained overload every tenant's queue
wait degrades together until everything is shed at random by expiry.

`OverloadController` watches the queue waits the stack already measures
(admission wait, batcher queue wait — the same signals behind
`qw_search_batcher_queue_wait_seconds`) as an EWMA. When the smoothed wait
breaches the target, the established checkpoints start rejecting the
lowest priority class up front with a typed, retryable error instead of
letting it burn queue slots it will lose anyway; if waits keep climbing a
second rung sheds the standard class too. The top class is never shed by
the controller — its protection is the point of having classes.

Disabled by default (`enabled=False`): with the controller off,
`should_shed` is constant-false and the serving path is byte-for-byte the
pre-tenancy behavior.
"""

from __future__ import annotations


from ..common.clock import monotonic
from .context import MAX_PRIORITY
from ..common import sync


class OverloadShed(Exception):
    """A query was rejected up front by the overload controller. Maps to
    HTTP 429 with a Retry-After hint (the smoothed queue wait — the time
    after which a retry plausibly meets a drained queue)."""

    def __init__(self, stage: str, retry_after_secs: float):
        self.stage = stage
        self.retry_after_secs = max(retry_after_secs, 0.0)
        super().__init__(
            f"overload shed at {stage} (retry after "
            f"{self.retry_after_secs:.2f}s)")


class OverloadController:
    """EWMA queue-wait tracker with a priority shed ladder."""

    def __init__(self, target_wait_secs: float = 0.5, alpha: float = 0.3,
                 idle_reset_secs: float = 10.0, enabled: bool = False):
        self.target_wait_secs = float(target_wait_secs)
        self.alpha = float(alpha)
        self.idle_reset_secs = float(idle_reset_secs)
        self.enabled = bool(enabled)
        self._lock = sync.lock("OverloadController._lock")
        self._ewma = 0.0
        self._last_update = 0.0
        self._last_floor = 0

    def configure(self, target_wait_secs=None, enabled=None,
                  alpha=None) -> None:
        with self._lock:
            if target_wait_secs is not None:
                self.target_wait_secs = float(target_wait_secs)
            if enabled is not None:
                self.enabled = bool(enabled)
            if alpha is not None:
                self.alpha = float(alpha)

    def reset(self) -> None:
        with self._lock:
            self._ewma = 0.0
            self._last_update = 0.0

    def note_wait(self, wait_secs: float) -> None:
        """Feed one observed queue wait (admission or batcher). Zero waits
        count too — an uncontended system must pull the EWMA back down."""
        with self._lock:
            self._ewma = (self.alpha * max(wait_secs, 0.0)
                          + (1.0 - self.alpha) * self._ewma)
            self._last_update = monotonic()
        self._note_floor_transition()

    def _note_floor_transition(self) -> None:
        """Flight-record ladder rung changes (0 → shed background → shed
        standard and back). Lazy import: flight → tenancy.context would
        cycle at module scope through tenancy/__init__."""
        floor = self.shed_floor()
        if floor != self._last_floor:
            from ..observability import flight
            flight.emit("overload.ladder",
                        attrs={"floor": floor, "from": self._last_floor,
                               "severity": round(self.severity(), 4)})
            self._last_floor = floor

    def severity(self) -> float:
        """Smoothed wait over target; 0 when disabled or idle. Staleness
        guard: if nothing has been admitted for `idle_reset_secs`, the old
        EWMA says nothing about the current queue — treat as calm."""
        with self._lock:
            if not self.enabled or self._last_update == 0.0:
                return 0.0
            if monotonic() - self._last_update > self.idle_reset_secs:
                self._ewma = 0.0
                return 0.0
            if self.target_wait_secs <= 0.0:
                return 0.0
            return self._ewma / self.target_wait_secs

    def shed_floor(self) -> int:
        """Priorities strictly below this rank are shed. severity <= 1:
        nothing; 1 < severity < 2: the bottom class; >= 2: everything but
        the top class (which is never shed)."""
        severity = self.severity()
        if severity <= 1.0:
            return 0
        return min(int(severity), MAX_PRIORITY)

    def should_shed(self, priority: int) -> bool:
        return priority < self.shed_floor()

    def retry_after_secs(self) -> float:
        with self._lock:
            return max(self._ewma, self.target_wait_secs, 0.1)

    def state(self) -> dict:
        with self._lock:
            ewma = self._ewma
        return {"enabled": self.enabled,
                "target_wait_secs": self.target_wait_secs,
                "ewma_wait_secs": round(ewma, 6),
                "severity": round(self.severity(), 4),
                "shed_floor": self.shed_floor()}


# Process-global controller, matching the process-global METRICS /
# SLOW_QUERY_LOG pattern: admission and the batcher feed it, the node
# config arms it.
OVERLOAD = OverloadController()
