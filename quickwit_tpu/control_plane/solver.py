"""Multi-phase indexing-placement solver.

Role of the reference's scheduling optimizer
(`quickwit-control-plane/src/indexing_scheduler/scheduling/
scheduling_logic.rs:41` and the README in that directory): given sources
(each a number of equal-load shards) and indexers (each a millicpu
capacity), produce a placement matrix `counts[indexer][source]` that

  - places every shard (growing capacity by 1.2x steps when bin-packing
    fails, then descending to the minimal feasible level so repeated
    calls are idempotent — the reference's inflation ascent/descent),
  - never exceeds the (inflated) per-indexer capacity,
  - stays close to the previous solution (phase ordering starts FROM the
    previous matrix and only shaves what must move),
  - prefers placing shards on indexers with declared affinity
    (the reference's ingester-colocation scores).

The mechanics are our own: the matrix lives in numpy, phases are pure
functions over it, and tie-breaks are deterministic (ordinal order, no
RNG) so the control loop converges instead of oscillating.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# capacity head-room factor: indexers are virtually inflated so the
# cluster always offers >= 120% of the total load (reference README:
# "We calculate 120% of the total load ... divide it up proportionally")
HEADROOM = 1.2
MAX_INFLATION_ATTEMPTS = 12


class NotEnoughCapacity(Exception):
    """Placement failed at the current inflation level."""


@dataclass
class SchedulingProblem:
    """`num_shards[s]` shards of `load_per_shard[s]` millicpu each, to be
    placed on indexers with `capacities[i]` millicpu."""
    num_shards: np.ndarray          # (S,) int
    load_per_shard: np.ndarray      # (S,) int millicpu
    capacities: np.ndarray          # (I,) int millicpu
    # affinity[s] -> {indexer_ord: score}; higher score = stronger pull
    affinities: dict[int, dict[int, int]] = field(default_factory=dict)

    @property
    def num_sources(self) -> int:
        return int(self.num_shards.size)

    @property
    def num_indexers(self) -> int:
        return int(self.capacities.size)

    def total_load(self) -> int:
        return int(np.dot(self.num_shards, self.load_per_shard))


def _inflate_capacities(problem: SchedulingProblem, factor: float,
                        headroom: float = HEADROOM) -> np.ndarray:
    """VIRTUAL capacities, the balancing mechanism (reference README):
    each indexer gets its proportional share of HEADROOM * total load, so
    respecting the virtual bound keeps every node near the average load.
    Shards place freely up to 30% of the REAL capacity (tiny cluster
    loads need not be balanced). The attempt factor grows the bound by
    HEADROOM steps when bin-packing fails."""
    caps = problem.capacities.astype(np.float64)
    total_cap = caps.sum()
    if total_cap <= 0:
        return np.zeros_like(problem.capacities)
    share = caps / total_cap * (headroom * problem.total_load())
    virtual = np.maximum(share, 0.3 * caps)
    return np.ceil(virtual * factor).astype(np.int64)


def _node_loads(problem: SchedulingProblem, counts: np.ndarray) -> np.ndarray:
    return counts @ problem.load_per_shard.astype(np.int64)


def _remove_extraneous(problem: SchedulingProblem,
                       counts: np.ndarray) -> None:
    """Phase 1: a source may have shrunk (or vanished) since the previous
    solution; shave surplus shards, taking first from indexers holding
    the FEWEST shards of that source (minimizes the number of nodes the
    source touches — reference phase 1)."""
    assigned = counts.sum(axis=0)
    for s in range(problem.num_sources):
        surplus = int(assigned[s]) - int(problem.num_shards[s])
        while surplus > 0:
            holders = np.nonzero(counts[:, s])[0]
            # fewest-first, ordinal tie-break
            i = min(holders, key=lambda n: (counts[n, s], n))
            take = min(surplus, int(counts[i, s]))
            counts[i, s] -= take
            surplus -= take
    # sources no longer in the problem were already trimmed to num_shards=0


def _enforce_capacity(problem: SchedulingProblem, counts: np.ndarray,
                      caps: np.ndarray) -> None:
    """Phase 2: shard loads may have grown; evict whole sources from
    overloaded indexers, smallest on-node load first (reference: "we
    remove in priority sources that have an overall small load")."""
    loads = _node_loads(problem, counts)
    for i in range(problem.num_indexers):
        while loads[i] > caps[i]:
            present = np.nonzero(counts[i])[0]
            if present.size == 0:
                break
            on_node = counts[i, present] * problem.load_per_shard[present]
            s = int(present[np.lexsort((present, on_node))[0]])
            loads[i] -= int(counts[i, s]) * int(problem.load_per_shard[s])
            counts[i, s] = 0


def _place_with_affinity(problem: SchedulingProblem, counts: np.ndarray,
                         caps: np.ndarray) -> None:
    """Phase 3a: route missing shards to indexers that declared affinity
    for the source (strongest score first), capacity permitting."""
    loads = _node_loads(problem, counts)
    missing = problem.num_shards - counts.sum(axis=0)
    for s, scores in sorted(problem.affinities.items()):
        if s >= problem.num_sources or missing[s] <= 0:
            continue
        lps = int(problem.load_per_shard[s])
        for i, _score in sorted(scores.items(),
                                key=lambda kv: (-kv[1], kv[0])):
            while missing[s] > 0 and loads[i] + lps <= caps[i]:
                counts[i, s] += 1
                loads[i] += lps
                missing[s] -= 1


def _place_remaining(problem: SchedulingProblem, counts: np.ndarray,
                     caps: np.ndarray) -> None:
    """Phase 3b: greedy best-fit for whatever is still unassigned, source
    by source in decreasing total-load order, preferring the indexer with
    the most remaining capacity (keeps sources on few nodes: each shard
    of a source keeps landing on the same node until it fills)."""
    loads = _node_loads(problem, counts)
    avail = caps - loads
    source_order = np.lexsort(
        (np.arange(problem.num_sources),
         -(problem.num_shards * problem.load_per_shard)))
    for s in source_order:
        lps = int(problem.load_per_shard[s])
        missing = int(problem.num_shards[s]) - int(counts[:, s].sum())
        while missing > 0:
            i = int(np.lexsort((np.arange(avail.size), -avail))[0])
            if avail[i] < lps:
                raise NotEnoughCapacity()
            fit = min(missing, int(avail[i] // lps)) if lps > 0 else missing
            counts[i, s] += fit
            avail[i] -= fit * lps
            missing -= fit


def _attempt(problem: SchedulingProblem, previous: np.ndarray,
             caps: np.ndarray) -> np.ndarray:
    counts = previous.copy()
    _remove_extraneous(problem, counts)
    _enforce_capacity(problem, counts, caps)
    _place_with_affinity(problem, counts, caps)
    _place_remaining(problem, counts, caps)
    return counts


def solve(problem: SchedulingProblem,
          previous: np.ndarray | None = None,
          headroom: float = HEADROOM) -> np.ndarray:
    """Returns `counts[indexer][source]` placing every shard.

    Ascends inflation levels (1.2^k) until bin-packing succeeds, then
    descends re-feeding the candidate to find the minimal feasible level
    — the reference's stability trick: re-solving from the returned
    solution is a no-op, so the control loop does not thrash."""
    shape = (problem.num_indexers, problem.num_sources)
    if previous is None:
        previous = np.zeros(shape, dtype=np.int64)
    else:
        fixed = np.zeros(shape, dtype=np.int64)
        src = previous[: shape[0], : shape[1]]
        fixed[: src.shape[0], : src.shape[1]] = src
        previous = fixed
    if problem.num_indexers == 0:
        if int(problem.num_shards.sum()) > 0:
            raise NotEnoughCapacity()
        return previous

    best: np.ndarray | None = None
    best_level = 0
    for level in range(MAX_INFLATION_ATTEMPTS):
        caps = _inflate_capacities(problem, HEADROOM ** level, headroom)
        try:
            best = _attempt(problem, previous, caps)
            best_level = level
            break
        except NotEnoughCapacity:
            continue
    if best is None:
        raise NotEnoughCapacity(
            f"cannot place {int(problem.num_shards.sum())} shards / "
            f"{problem.total_load()} millicpu on capacity "
            f"{int(problem.capacities.sum())}")
    while best_level > 0:
        caps = _inflate_capacities(problem, HEADROOM ** (best_level - 1),
                                   headroom)
        try:
            best = _attempt(problem, best, caps)
            best_level -= 1
        except NotEnoughCapacity:
            break
    return best
