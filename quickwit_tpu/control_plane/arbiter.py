"""Shard autoscaling arbiter.

Role of the reference's `ScalingArbiter` + the shard table's scaling
permits (`quickwit-control-plane/src/ingest/scaling_arbiter.rs:19`,
`model/shard_table.rs:33`): decide, per source, whether to open or close
ingest shards from the observed per-shard ingestion rates.

Semantics preserved from the reference:
  - scale-up triggers on the SHORT-term average rate (reactive, ~5s
    window) at 80% of the per-shard throughput limit, but the target
    shard count is capped so the LONG-term average never drops below 30%
    of the limit (avoids up/down flapping on spikes);
  - the target grows by `scale_up_factor` per decision (geometric ramp);
  - scale-down triggers only on the LONG-term average at 20% of the
    limit, one shard at a time;
  - both directions are permit-rate-limited per source (up: bursts of 5
    per minute; down: 1 per minute) so a noisy metric cannot thrash the
    shard table;
  - the scale-down victim is a shard on the ingester holding the MOST
    open shards of the source (`find_scale_down_candidate`,
    `ingest_controller.rs:1300`) — deterministic here (oldest shard id)
    instead of RNG tie-breaks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..common.clock import monotonic
from ..common.tower import TokenBucket


@dataclass(frozen=True)
class ShardStats:
    num_open_shards: int
    avg_short_term_rate_mib: float  # per open shard, MiB/s
    avg_long_term_rate_mib: float


@dataclass(frozen=True)
class ScaleUp:
    num_shards: int


@dataclass(frozen=True)
class ScaleDown:
    pass


class ScalingArbiter:
    def __init__(self, max_shard_throughput_mib: float = 5.0,
                 scale_up_factor: float = 1.5):
        self.short_term_up_threshold = max_shard_throughput_mib * 0.8
        self.long_term_up_floor = max_shard_throughput_mib * 0.3
        self.down_threshold = max_shard_throughput_mib * 0.2
        self.scale_up_factor = scale_up_factor

    def should_scale(self, stats: ShardStats,
                     min_shards: int = 1) -> Optional[ScaleUp | ScaleDown]:
        if stats.num_open_shards == 0 or stats.avg_long_term_rate_mib == 0.0:
            # idle sources are closed by the ingesters themselves; a
            # source with no open shard scales on first ingest instead
            return None
        if stats.num_open_shards < min_shards:
            return ScaleUp(min_shards - stats.num_open_shards)
        if stats.avg_short_term_rate_mib >= self.short_term_up_threshold:
            # total long-term volume spread over the new count must stay
            # above the long-term floor
            max_by_volume = int(
                stats.avg_long_term_rate_mib * stats.num_open_shards
                / self.long_term_up_floor)
            by_factor = int(-(-stats.num_open_shards
                              * self.scale_up_factor // 1))  # ceil
            target = max(min_shards, min(max_by_volume, by_factor))
            if target > stats.num_open_shards:
                return ScaleUp(target - stats.num_open_shards)
        if (stats.avg_long_term_rate_mib <= self.down_threshold
                and stats.num_open_shards > min_shards):
            return ScaleDown()
        return None


@dataclass
class _SourcePermits:
    up: "TokenBucket"
    down: "TokenBucket"


class ScalingPermits:
    """Per-source decision rate limiting (reference:
    `shard_table.rs:33` SCALING_{UP,DOWN}_RATE_LIMITER_SETTINGS)."""

    def __init__(self, clock=monotonic):
        self._clock = clock
        self._per_source: dict[str, _SourcePermits] = {}

    def _entry(self, source_key: str) -> _SourcePermits:
        entry = self._per_source.get(source_key)
        if entry is None:
            entry = _SourcePermits(
                up=TokenBucket(rate_per_sec=5 / 60.0, burst=5,
                               clock=self._clock),
                down=TokenBucket(rate_per_sec=1 / 60.0, burst=1,
                                 clock=self._clock))
            self._per_source[source_key] = entry
        return entry

    def acquire(self, source_key: str,
                decision: ScaleUp | ScaleDown) -> int:
        """Returns the number of shards the caller may act on now (0 =
        denied). A ScaleUp larger than the remaining burst budget is
        GRANTED PARTIALLY rather than stalling forever — the arbiter will
        re-request the rest next tick once permits refill."""
        entry = self._entry(source_key)
        if isinstance(decision, ScaleUp):
            for n in range(decision.num_shards, 0, -1):
                if entry.up.try_acquire(n):
                    return n
            return 0
        return 1 if entry.down.try_acquire(1) else 0

    def release(self, source_key: str, decision: ScaleUp | ScaleDown,
                granted: Optional[int] = None) -> None:
        """Give permits back when the metastore/ingester op failed — a
        failed attempt must not eat the budget for the retry. Pass the
        count `acquire` actually returned: refunding the full decision
        after a partial grant would mint permits never consumed."""
        entry = self._entry(source_key)
        if isinstance(decision, ScaleUp):
            entry.up.release(granted if granted is not None
                             else decision.num_shards)
        elif granted is None or granted > 0:
            # same partial-grant rule as ScaleUp: a denied acquire
            # (granted == 0) must not mint a down permit on release
            entry.down.release(1)


def find_scale_down_candidate(
        open_shards: dict[str, str]) -> Optional[tuple[str, str]]:
    """`{shard_id: leader_node_id}` -> (leader, shard) to close: a shard
    on the node with the most open shards of this source, oldest shard id
    (deterministic; the reference breaks ties randomly)."""
    if not open_shards:
        return None
    per_leader: dict[str, list[str]] = {}
    for shard_id, leader in open_shards.items():
        per_leader.setdefault(leader, []).append(shard_id)
    leader = max(per_leader, key=lambda n: (len(per_leader[n]), n))
    return leader, min(per_leader[leader])


class ShardRateTracker:
    """Turns cumulative per-shard byte counters into short/long-term
    ingestion-rate EMAs (MiB/s). The reference keeps two windows on the
    ingester side (~5s reactive, longer-term smoothing) and gossips them;
    here the control loop samples `Ingester.shard_throughput_state()`
    and owns the smoothing."""

    def __init__(self, short_tau_secs: float = 5.0,
                 long_tau_secs: float = 60.0, clock=monotonic):
        self.short_tau = short_tau_secs
        self.long_tau = long_tau_secs
        self.clock = clock
        # queue_id -> (last_bytes, last_t, short_ema, long_ema)
        self._state: dict[str, tuple[int, float, float, float]] = {}

    def observe(self, queue_id: str, total_bytes: int) -> None:
        import math
        now = self.clock()
        prev = self._state.get(queue_id)
        if prev is None:
            self._state[queue_id] = (total_bytes, now, 0.0, 0.0)
            return
        last_bytes, last_t, short, long_ = prev
        dt = max(now - last_t, 1e-6)
        rate = max(total_bytes - last_bytes, 0) / dt / (1 << 20)  # MiB/s
        a_s = 1.0 - math.exp(-dt / self.short_tau)
        a_l = 1.0 - math.exp(-dt / self.long_tau)
        self._state[queue_id] = (total_bytes, now,
                                 short + a_s * (rate - short),
                                 long_ + a_l * (rate - long_))

    def forget(self, queue_id: str) -> None:
        self._state.pop(queue_id, None)

    def retain(self, live_queue_ids) -> None:
        """Drop state for shards that no longer exist (closed/deleted by
        any path) — the tracker must not grow with shard churn."""
        live = set(live_queue_ids)
        for queue_id in [q for q in self._state if q not in live]:
            del self._state[queue_id]

    def rates(self, queue_id: str) -> tuple[float, float]:
        _, _, short, long_ = self._state.get(queue_id, (0, 0.0, 0.0, 0.0))
        return short, long_

    def source_stats(self, queue_ids: list[str]) -> ShardStats:
        if not queue_ids:
            return ShardStats(0, 0.0, 0.0)
        shorts, longs = zip(*(self.rates(q) for q in queue_ids))
        n = len(queue_ids)
        return ShardStats(n, sum(shorts) / n, sum(longs) / n)
