from .arbiter import (ScaleDown, ScaleUp, ScalingArbiter, ScalingPermits,
                      ShardRateTracker, ShardStats,
                      find_scale_down_candidate)
from .scheduler import IndexingScheduler, IndexingTask, PhysicalIndexingPlan
from .solver import NotEnoughCapacity, SchedulingProblem, solve

__all__ = [
    "IndexingScheduler", "IndexingTask", "PhysicalIndexingPlan",
    "SchedulingProblem", "solve", "NotEnoughCapacity",
    "ScalingArbiter", "ScalingPermits", "ShardRateTracker", "ShardStats",
    "ScaleUp", "ScaleDown", "find_scale_down_candidate",
]
