from .scheduler import IndexingScheduler, IndexingTask, PhysicalIndexingPlan

__all__ = ["IndexingScheduler", "IndexingTask", "PhysicalIndexingPlan"]
