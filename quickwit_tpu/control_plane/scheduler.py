"""Control-plane indexing scheduler.

Role of the reference's `IndexingScheduler` + its 3-phase bin-packing solver
(`quickwit-control-plane/src/indexing_scheduler/mod.rs:111,360`,
`scheduling/scheduling_logic.rs`): turn the set of (index, source[, shard])
logical indexing tasks into a `PhysicalIndexingPlan` assigning tasks to
indexer nodes, preferring to keep a task where it already runs (affinity —
the solver's phase-1 "conserve previous assignments"), balancing load by
task weight, and re-converging when nodes or sources change. The reference's
LP-style refinement phases collapse here into affinity-preserving greedy
packing with a capacity bound — same invariants (every task placed, no node
above capacity unless unavoidable), simpler mechanics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class IndexingTask:
    index_uid: str
    source_id: str
    shard_id: Optional[str] = None
    weight: int = 1  # relative CPU weight (reference: load per pipeline)

    @property
    def key(self) -> tuple:
        return (self.index_uid, self.source_id, self.shard_id)


@dataclass
class PhysicalIndexingPlan:
    assignments: dict[str, list[IndexingTask]] = field(default_factory=dict)

    def node_of(self, task: IndexingTask) -> Optional[str]:
        for node_id, tasks in self.assignments.items():
            if task in tasks:
                return node_id
        return None

    def tasks_for(self, node_id: str) -> list[IndexingTask]:
        return self.assignments.get(node_id, [])

    @property
    def num_tasks(self) -> int:
        return sum(len(t) for t in self.assignments.values())


class IndexingScheduler:
    def __init__(self, max_load_factor: float = 1.2):
        self.max_load_factor = max_load_factor
        self.last_plan = PhysicalIndexingPlan()

    def schedule(self, tasks: list[IndexingTask],
                 indexer_nodes: list[str]) -> PhysicalIndexingPlan:
        """Build the next physical plan; deterministic given inputs + the
        previous plan (affinity)."""
        if not indexer_nodes:
            self.last_plan = PhysicalIndexingPlan()
            return self.last_plan
        nodes = sorted(indexer_nodes)
        total_weight = sum(t.weight for t in tasks) or 1
        capacity = (total_weight / len(nodes)) * self.max_load_factor
        previous: dict[tuple, str] = {}
        for node_id, node_tasks in self.last_plan.assignments.items():
            for task in node_tasks:
                previous[task.key] = node_id

        load: dict[str, float] = {n: 0.0 for n in nodes}
        plan = PhysicalIndexingPlan(assignments={n: [] for n in nodes})

        # phase 1: keep tasks where they already run, capacity permitting
        remaining: list[IndexingTask] = []
        for task in sorted(tasks, key=lambda t: (-t.weight, t.key)):
            prev_node = previous.get(task.key)
            if prev_node in load and load[prev_node] + task.weight <= capacity:
                plan.assignments[prev_node].append(task)
                load[prev_node] += task.weight
            else:
                remaining.append(task)
        # phase 2: place the rest on the least-loaded node
        for task in remaining:
            node_id = min(nodes, key=lambda n: (load[n], n))
            plan.assignments[node_id].append(task)
            load[node_id] += task.weight

        plan.assignments = {n: t for n, t in plan.assignments.items() if t}
        self.last_plan = plan
        return plan

    def plan_drift(self, running: dict[str, list[IndexingTask]]) -> bool:
        """True if what nodes report running differs from the last plan
        (the reference's periodic drift re-check, §3.4)."""
        want = {n: sorted(t.key for t in ts)
                for n, ts in self.last_plan.assignments.items()}
        have = {n: sorted(t.key for t in ts) for n, ts in running.items() if ts}
        return want != have
