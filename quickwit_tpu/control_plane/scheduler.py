"""Control-plane indexing scheduler.

Role of the reference's `IndexingScheduler`
(`quickwit-control-plane/src/indexing_scheduler/mod.rs:111,360`): turn
the set of (index, source[, shard]) logical indexing tasks into a
`PhysicalIndexingPlan` assigning tasks to indexer nodes, then watch for
drift between the plan and what nodes report running.

The placement decision itself is delegated to the multi-phase solver
(`solver.py`, the analogue of `scheduling_logic.rs`): tasks are grouped
into uniform-load "sources" (index, source, weight), solved as a
`counts[indexer][source]` matrix starting FROM the previous solution
(stability), and the matrix is expanded back into concrete tasks with
shard-level stickiness — a task stays on its previous node whenever that
node still holds a slot for its group.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .solver import NotEnoughCapacity, SchedulingProblem, solve

# millicpu ascribed to one unit of task weight (reference:
# `PIPELINE_FULL_CAPACITY` — one pipeline saturates 4 cpus; our weights
# are relative so the scale only matters for capacity accounting)
MILLICPU_PER_WEIGHT = 250
DEFAULT_INDEXER_MILLICPU = 4000


@dataclass(frozen=True)
class IndexingTask:
    index_uid: str
    source_id: str
    shard_id: Optional[str] = None
    weight: int = 1  # relative CPU weight (reference: load per pipeline)

    @property
    def key(self) -> tuple:
        return (self.index_uid, self.source_id, self.shard_id)

    @property
    def group(self) -> tuple:
        # solver "source": tasks of one source with one load level
        return (self.index_uid, self.source_id, self.weight)


@dataclass
class PhysicalIndexingPlan:
    assignments: dict[str, list[IndexingTask]] = field(default_factory=dict)

    def node_of(self, task: IndexingTask) -> Optional[str]:
        for node_id, tasks in self.assignments.items():
            if task in tasks:
                return node_id
        return None

    def tasks_for(self, node_id: str) -> list[IndexingTask]:
        return self.assignments.get(node_id, [])

    @property
    def num_tasks(self) -> int:
        return sum(len(t) for t in self.assignments.values())


class IndexingScheduler:
    def __init__(self, max_load_factor: float = 1.2,
                 indexer_millicpu: int = DEFAULT_INDEXER_MILLICPU):
        # headroom over the average load a node may carry before the
        # solver balances away from it (the solver's virtual capacity)
        self.max_load_factor = max_load_factor
        self.indexer_millicpu = indexer_millicpu
        self.last_plan = PhysicalIndexingPlan()

    def schedule(self, tasks: list[IndexingTask],
                 indexer_nodes: list[str],
                 affinities: Optional[dict[tuple, dict[str, int]]] = None,
                 ) -> PhysicalIndexingPlan:
        """Build the next physical plan; deterministic given inputs + the
        previous plan. `affinities` optionally maps a task group
        (index_uid, source_id, weight) to {node_id: score} — the
        reference's ingester-colocation pull for ingest-API sources."""
        if not indexer_nodes:
            self.last_plan = PhysicalIndexingPlan()
            return self.last_plan
        nodes = sorted(indexer_nodes)
        node_ord = {n: i for i, n in enumerate(nodes)}

        groups = sorted({t.group for t in tasks})
        group_ord = {g: s for s, g in enumerate(groups)}
        by_group: dict[tuple, list[IndexingTask]] = {g: [] for g in groups}
        for t in sorted(tasks, key=lambda t: t.key):
            by_group[t.group].append(t)

        problem = SchedulingProblem(
            num_shards=np.array([len(by_group[g]) for g in groups],
                                dtype=np.int64),
            load_per_shard=np.array(
                [g[2] * MILLICPU_PER_WEIGHT for g in groups],
                dtype=np.int64),
            capacities=np.full(len(nodes), self.indexer_millicpu,
                               dtype=np.int64),
        )
        # affinity: explicit colocation scores, else the previous plan's
        # footprint (keeps a source on the nodes it already touches)
        for g, s in group_ord.items():
            scores: dict[int, int] = {}
            for node_id, score in (affinities or {}).get(g, {}).items():
                if node_id in node_ord:
                    scores[node_ord[node_id]] = score
            if not scores:
                for node_id, prev_tasks in self.last_plan.assignments.items():
                    if node_id in node_ord:
                        n = sum(1 for t in prev_tasks if t.group == g)
                        if n:
                            scores[node_ord[node_id]] = n
            if scores:
                problem.affinities[s] = scores

        previous = np.zeros((len(nodes), len(groups)), dtype=np.int64)
        prev_node_of: dict[tuple, str] = {}
        for node_id, prev_tasks in self.last_plan.assignments.items():
            if node_id not in node_ord:
                continue
            for t in prev_tasks:
                prev_node_of[t.key] = node_id
                if t.group in group_ord:
                    previous[node_ord[node_id], group_ord[t.group]] += 1

        try:
            counts = solve(problem, previous,
                           headroom=self.max_load_factor)
        except NotEnoughCapacity:
            # degenerate fallback: spread evenly; the solver only gives
            # up past 1.2^12 inflation (pathological weights)
            counts = np.zeros((len(nodes), len(groups)), dtype=np.int64)
            for s, g in enumerate(groups):
                for k in range(len(by_group[g])):
                    counts[k % len(nodes), s] += 1

        # expand the matrix into concrete tasks: previous node first
        # (stickiness), then fill remaining slots in node order
        plan = PhysicalIndexingPlan(assignments={n: [] for n in nodes})
        for g, s in group_ord.items():
            slots = {i: int(counts[i, s]) for i in range(len(nodes))}
            pending: list[IndexingTask] = []
            for t in by_group[g]:
                prev = prev_node_of.get(t.key)
                i = node_ord.get(prev) if prev is not None else None
                if i is not None and slots.get(i, 0) > 0:
                    plan.assignments[nodes[i]].append(t)
                    slots[i] -= 1
                else:
                    pending.append(t)
            for t in pending:
                i = min((i for i, c in slots.items() if c > 0), default=None)
                if i is None:  # fallback counts may under-allocate: spread
                    i = min(range(len(nodes)),
                            key=lambda n: len(plan.assignments[nodes[n]]))
                else:
                    slots[i] -= 1
                plan.assignments[nodes[i]].append(t)

        plan.assignments = {n: t for n, t in plan.assignments.items() if t}
        self.last_plan = plan
        return plan

    def plan_drift(self, running: dict[str, list[IndexingTask]]) -> bool:
        """True if what nodes report running differs from the last plan
        (the reference's periodic drift re-check, §3.4)."""
        want = {n: sorted(t.key for t in ts)
                for n, ts in self.last_plan.assignments.items()}
        have = {n: sorted(t.key for t in ts) for n, ts in running.items() if ts}
        return want != have
