"""UDP scuttlebutt gossip — the cluster dissemination layer.

Role of the reference's chitchat (`quickwit-cluster/src/cluster.rs:61`,
chitchat crate): each node keeps a versioned key/value state per peer and
anti-entropy-syncs it over UDP with random peers. The three-way exchange
is chitchat's:

    SYN      {digest: {node_id: max_version_seen}}
    SYN-ACK  {deltas: entries the sender has newer than the digest,
              digest: sender's own digest}
    ACK      {deltas: entries the receiver has newer than that digest}

Each node's own state version bumps every gossip round, and applying a
newer (generation, version) records a Cluster heartbeat — so a peer that
stops gossiping stops producing versions and ages out through
`dead_after_secs` (the phi-accrual curve collapses to an age threshold
under regular intervals, like cluster/membership.py). `generation` is
the service start time: a restarted node begins a higher generation, so
peers accept its reset version immediately (chitchat's incarnation).

Gossip shares the REST port NUMBER over UDP (the reference's convention
— TCP and UDP namespaces don't collide), so `peer_seeds` work unchanged.
Messages are JSON datagrams; deltas are capped per packet to stay under
typical MTU for small clusters and rely on subsequent rounds for the
rest (scuttlebutt converges incrementally by design).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from typing import Optional

from ..common.clock import get_clock, get_rng
from .membership import Cluster, ClusterMember

logger = logging.getLogger(__name__)

_MAX_DELTAS_PER_PACKET = 16
_MAX_DATAGRAM = 60_000


class GossipService:
    """One node's gossip endpoint: a UDP listener + a periodic gossip loop,
    feeding discovered peers and liveness into the Cluster."""

    def __init__(self, cluster: Cluster, node_id: str, roles: tuple[str, ...],
                 rest_endpoint: str, bind_host: str, bind_port: int,
                 seeds: tuple[str, ...] = (), interval_secs: float = 1.0,
                 fanout: int = 3, cluster_id: str = "quickwit-tpu",
                 grpc_endpoint: str = ""):
        self.cluster = cluster
        self.node_id = node_id
        # chitchat embeds the cluster_id in every message and rejects
        # mismatches (`quickwit-cluster/src/cluster.rs:61`): without it a
        # spoofed datagram or a second cluster sharing seeds could inject
        # members the root searcher would fan leaf requests out to.
        self.cluster_id = cluster_id
        self.interval_secs = interval_secs
        self.fanout = fanout
        self.seeds = tuple(seeds)
        # versioned node states:
        # node_id -> {"generation", "version", "data"}; identity order is
        # (generation, version) so a restart (new generation, version 1)
        # supersedes any pre-crash version
        self._state: dict[str, dict] = {
            node_id: {"generation": get_clock().time_ns(), "version": 1,
                      "data": {"roles": list(roles),
                               "rest_endpoint": rest_endpoint,
                               "grpc_endpoint": grpc_endpoint,
                               "gossip_port": 0}},  # patched after bind
        }
        # qwlint: disable-next-line=QW008 - gossip/membership background loops
        # run on real time outside the DST op path; leaf primitives with no
        # seam locks held inside
        self._lock = threading.Lock()
        # qwlint: disable-next-line=QW008 - gossip/membership background loops
        # run on real time outside the DST op path; leaf primitives with no
        # seam locks held inside
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((bind_host, bind_port))
        self.port = self._sock.getsockname()[1]
        self._state[node_id]["data"]["gossip_port"] = self.port
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------------
    def start(self) -> None:
        for name, target in (("gossip-rx", self._listen_loop),
                             ("gossip-tx", self._gossip_loop)):
            # qwlint: disable-next-line=QW003 - cluster gossip loops are
            # node-lifetime background threads, never query-scoped
            # qwlint: disable-next-line=QW008 - gossip/membership background
            # loops run on real time outside the DST op path; leaf primitives
            # with no seam locks held inside
            thread = threading.Thread(target=target, name=name, daemon=True)
            thread.start()
            self._threads.append(thread)
        logger.info("gossip listening on udp:%d (%s)", self.port, self.node_id)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # --- state helpers -----------------------------------------------------
    def _digest(self) -> dict[str, list[int]]:
        """node_id -> [generation, version] (chitchat digests carry the
        incarnation too — a version-only digest would never re-ship a
        restarted node whose version reset below the peer's last view)."""
        with self._lock:
            return {nid: [s.get("generation", 0), s["version"]]
                    for nid, s in self._state.items()}

    def _deltas_for(self, digest: dict) -> list[dict]:
        """Entries the peer has not seen (newer (generation, version))."""
        out = []
        with self._lock:
            for nid, state in self._state.items():
                seen = digest.get(nid) or [0, 0]
                try:
                    seen_key = (int(seen[0]), int(seen[1]))
                except (TypeError, ValueError, IndexError):
                    seen_key = (0, 0)
                if (state.get("generation", 0), state["version"]) > seen_key:
                    out.append({"node_id": nid, **state})
                    if len(out) >= _MAX_DELTAS_PER_PACKET:
                        break
        return out

    def _apply_deltas(self, deltas: list[dict],
                      source_host: Optional[str] = None) -> None:
        from .membership import substitute_wildcard_host
        if not isinstance(deltas, list):
            return
        for delta in deltas:
            if not isinstance(delta, dict):
                continue
            nid = delta.get("node_id")
            if not isinstance(nid, str) or nid == self.node_id:
                continue  # own state is authoritative locally
            generation = int(delta.get("generation", 0))
            version = int(delta.get("version", 0))
            data = dict(delta.get("data") or {})
            # a wildcard-bound node advertises 0.0.0.0: substitute the
            # address the datagram actually came from (first-hop only —
            # the fixed endpoint then propagates onward)
            endpoint = str(data.get("rest_endpoint", ""))
            if source_host:
                data["rest_endpoint"] = substitute_wildcard_host(
                    endpoint, source_host)
                data["grpc_endpoint"] = substitute_wildcard_host(
                    str(data.get("grpc_endpoint", "")), source_host)
            with self._lock:
                current = self._state.get(nid)
                if current is not None and (
                        current.get("generation", 0),
                        current["version"]) >= (generation, version):
                    continue
                self._state[nid] = {"generation": generation,
                                    "version": version, "data": data}
            member = ClusterMember(
                node_id=nid, roles=tuple(data.get("roles", ())),
                rest_endpoint=str(data.get("rest_endpoint", "")),
                grpc_endpoint=str(data.get("grpc_endpoint", "")))
            self.cluster.upsert_heartbeat(member)

    def _gossip_addresses(self) -> list[tuple[str, int]]:
        """Seeds + every known peer's advertised gossip address."""
        addresses = {}
        for seed in self.seeds:
            host, _, port = seed.rpartition(":")
            try:
                addresses[(host, int(port))] = True
            except ValueError:
                logger.debug("bad gossip seed %r", seed)
        with self._lock:
            for nid, state in self._state.items():
                if nid == self.node_id:
                    continue
                endpoint = state["data"].get("rest_endpoint", "")
                gossip_port = state["data"].get("gossip_port")
                host = endpoint.rpartition(":")[0]
                if host and gossip_port:
                    addresses[(host, int(gossip_port))] = True
        return [a for a in addresses if a != ("127.0.0.1", self.port)]

    # --- protocol ----------------------------------------------------------
    def _send(self, message: dict, addr: tuple[str, int]) -> None:
        try:
            message = {"cluster_id": self.cluster_id, **message}
            payload = json.dumps(message).encode()
            if len(payload) <= _MAX_DATAGRAM:
                self._sock.sendto(payload, addr)
        except OSError as exc:
            logger.debug("gossip send to %s failed: %s", addr, exc)

    def _gossip_loop(self) -> None:
        # interval waits route through the process clock so an accelerated
        # clock compresses rounds; fanout sampling uses the process rng so
        # a seeded run picks the same targets
        while not get_clock().wait(self._stop, self.interval_secs):
            with self._lock:
                self._state[self.node_id]["version"] += 1
            targets = self._gossip_addresses()
            if not targets:
                continue
            digest = self._digest()
            for addr in get_rng().sample(targets,
                                         min(self.fanout, len(targets))):
                self._send({"kind": "syn", "digest": digest}, addr)

    def _listen_loop(self) -> None:
        while not self._stop.is_set():
            try:
                payload, addr = self._sock.recvfrom(_MAX_DATAGRAM + 1024)
            except OSError as exc:
                if self._stop.is_set():
                    return  # socket closed on stop()
                # transient (e.g. WSAECONNRESET from a dead peer's ICMP):
                # a deaf gossip node is worse than a noisy one
                logger.debug("gossip recv error: %s", exc)
                continue
            try:
                message = json.loads(payload)
                if message.get("cluster_id") != self.cluster_id:
                    logger.debug("dropping gossip datagram from %s: "
                                 "cluster_id mismatch", addr)
                    continue
                kind = message.get("kind")
                digest = dict(message.get("digest") or {})
                if kind == "syn":
                    self._send({"kind": "syn-ack",
                                "deltas": self._deltas_for(digest),
                                "digest": self._digest()}, addr)
                elif kind == "syn-ack":
                    self._apply_deltas(message.get("deltas") or [],
                                       source_host=addr[0])
                    self._send({"kind": "ack",
                                "deltas": self._deltas_for(digest)}, addr)
                elif kind == "ack":
                    self._apply_deltas(message.get("deltas") or [],
                                       source_host=addr[0])
            except Exception as exc:  # noqa: BLE001 - a deaf gossip node
                # is invisible failure; any malformed datagram must be
                # droppable without killing the listener
                logger.debug("bad gossip datagram from %s: %s", addr, exc)

    # Liveness: _apply_deltas records a Cluster heartbeat whenever a newer
    # version arrives; a peer that stops gossiping stops producing versions
    # and ages out through Cluster.dead_after_secs.
