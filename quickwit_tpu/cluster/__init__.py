from .membership import Cluster, ClusterChange, ClusterMember

__all__ = ["Cluster", "ClusterChange", "ClusterMember"]
