"""Cluster membership + failure detection.

Role of the reference's `quickwit-cluster` (chitchat scuttlebutt gossip +
phi-accrual failure detection, `cluster.rs:61,167`): who is in the cluster,
which roles they run, and liveness. This implementation keeps the same
surface — members with roles/generation, readiness, a change stream feeding
client pools — over a pluggable dissemination layer: in-process registry now
(single-process clusters, tests), heartbeats over the REST transport for
multi-process (serve layer); the gossip state machine is the same either way.

Failure detection is a simplified phi-accrual: a node is suspected dead when
its heartbeat age exceeds `dead_after_secs` (the reference's phi threshold
collapses to this under regular heartbeat intervals).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Optional

from ..common.pubsub import EventBroker

ALL_ROLES = ("searcher", "indexer", "metastore", "control_plane", "janitor",
             "ingester")

_WILDCARD_HOSTS = ("0.0.0.0", "::", "")


def substitute_wildcard_host(endpoint: str, reachable_host: str) -> str:
    """A node bound to a wildcard address advertises an unroutable
    `0.0.0.0:port` endpoint; replace the host with the address the peer
    was actually reached at (the reference solves this with a dedicated
    advertise-address config; here the transport knows the real address)."""
    if not endpoint:
        return endpoint
    host, _, port = endpoint.rpartition(":")
    if host in _WILDCARD_HOSTS and reachable_host:
        return f"{reachable_host}:{port}"
    return endpoint


@dataclass
class ClusterMember:
    node_id: str
    roles: tuple[str, ...]
    rest_endpoint: str = ""          # "host:port" for cross-process transport
    generation: int = 0
    is_ready: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)


@dataclass
class ClusterChange:
    kind: str  # "add" | "remove" | "update"
    member: ClusterMember


class Cluster:
    def __init__(self, self_node_id: str, roles: tuple[str, ...],
                 rest_endpoint: str = "", heartbeat_interval_secs: float = 1.0,
                 dead_after_secs: float = 10.0,
                 broker: Optional[EventBroker] = None):
        self.self_node_id = self_node_id
        self.broker = broker or EventBroker()
        self._members: dict[str, ClusterMember] = {}
        self._lock = threading.Lock()
        self.heartbeat_interval_secs = heartbeat_interval_secs
        self.dead_after_secs = dead_after_secs
        self_member = ClusterMember(self_node_id, roles, rest_endpoint)
        self._members[self_node_id] = self_member

    # --- membership --------------------------------------------------------
    def join(self, member: ClusterMember) -> None:
        with self._lock:
            existing = self._members.get(member.node_id)
            self._members[member.node_id] = member
        self.broker.publish(ClusterChange("update" if existing else "add", member))

    def leave(self, node_id: str) -> None:
        with self._lock:
            member = self._members.pop(node_id, None)
        if member is not None:
            self.broker.publish(ClusterChange("remove", member))

    def record_heartbeat(self, node_id: str) -> None:
        with self._lock:
            member = self._members.get(node_id)
            if member is not None:
                member.last_heartbeat = time.monotonic()

    def upsert_heartbeat(self, member: ClusterMember) -> None:
        """Gossip upsert shared by both heartbeat transports (outbound
        client + inbound REST route): join only when the peer is new or
        its roles/endpoint changed (avoids a ClusterChange broadcast per
        tick), then stamp liveness either way."""
        current = self.member(member.node_id)
        if (current is None or current.roles != member.roles
                or current.rest_endpoint != member.rest_endpoint):
            self.join(member)
        self.record_heartbeat(member.node_id)

    # --- queries -----------------------------------------------------------
    def members(self, alive_only: bool = True) -> list[ClusterMember]:
        now = time.monotonic()
        with self._lock:
            out = []
            for member in self._members.values():
                if alive_only and member.node_id != self.self_node_id:
                    if now - member.last_heartbeat > self.dead_after_secs:
                        continue
                out.append(member)
            return sorted(out, key=lambda m: m.node_id)

    def nodes_with_role(self, role: str, alive_only: bool = True) -> list[str]:
        return [m.node_id for m in self.members(alive_only) if role in m.roles]

    def member(self, node_id: str) -> Optional[ClusterMember]:
        with self._lock:
            return self._members.get(node_id)

    def is_ready(self) -> bool:
        return bool(self.nodes_with_role("searcher") or
                    self.nodes_with_role("indexer"))

    def subscribe(self, handler: Callable[[ClusterChange], None]):
        return self.broker.subscribe(ClusterChange, handler)
