"""Cluster membership + failure detection.

Role of the reference's `quickwit-cluster` (chitchat scuttlebutt gossip +
phi-accrual failure detection, `cluster.rs:61,167`): who is in the cluster,
which roles they run, and liveness. This implementation keeps the same
surface — members with roles/generation, readiness, a change stream feeding
client pools — over a pluggable dissemination layer: in-process registry now
(single-process clusters, tests), heartbeats over the REST transport for
multi-process (serve layer); the gossip state machine is the same either way.

Failure detection is phi-accrual (reference: chitchat's
FailureDetectorConfig, cluster.rs:25-27): each member keeps a sliding
window of inter-arrival intervals; phi = age / mean_interval · log10(e)
(the exponential-distribution suspicion level). A node is suspected dead
when phi exceeds `phi_threshold` — adaptive to the OBSERVED cadence, so
jittery-but-alive peers are not declared dead the way a fixed age
threshold would. `dead_after_secs` remains a hard upper bound (and the
fallback before enough samples accumulate).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Optional

from ..common.clock import monotonic
from ..common.pubsub import EventBroker

ALL_ROLES = ("searcher", "indexer", "metastore", "control_plane", "janitor",
             "ingester")

_WILDCARD_HOSTS = ("0.0.0.0", "::", "")


def substitute_wildcard_host(endpoint: str, reachable_host: str) -> str:
    """A node bound to a wildcard address advertises an unroutable
    `0.0.0.0:port` endpoint; replace the host with the address the peer
    was actually reached at (the reference solves this with a dedicated
    advertise-address config; here the transport knows the real address)."""
    if not endpoint:
        return endpoint
    host, _, port = endpoint.rpartition(":")
    if host in _WILDCARD_HOSTS and reachable_host:
        return f"{reachable_host}:{port}"
    return endpoint


@dataclass
class ClusterMember:
    node_id: str
    roles: tuple[str, ...]
    rest_endpoint: str = ""          # "host:port" for cross-process transport
    grpc_endpoint: str = ""          # "host:port" gRPC plane ("" = REST only)
    generation: int = 0
    is_ready: bool = True
    last_heartbeat: float = field(default_factory=monotonic)
    # sliding window of heartbeat inter-arrival intervals (phi-accrual)
    intervals: list = field(default_factory=list)


@dataclass
class ClusterChange:
    kind: str  # "add" | "remove" | "update"
    member: ClusterMember


class Cluster:
    def __init__(self, self_node_id: str, roles: tuple[str, ...],
                 rest_endpoint: str = "", heartbeat_interval_secs: float = 1.0,
                 dead_after_secs: float = 10.0,
                 broker: Optional[EventBroker] = None):
        self.self_node_id = self_node_id
        self.broker = broker or EventBroker()
        self._members: dict[str, ClusterMember] = {}
        # qwlint: disable-next-line=QW008 - gossip/membership background loops
        # run on real time outside the DST op path; leaf primitives with no
        # seam locks held inside
        self._lock = threading.Lock()
        self.heartbeat_interval_secs = heartbeat_interval_secs
        self.dead_after_secs = dead_after_secs
        # chitchat's default phi threshold is 8.0 (~1 false positive per
        # 10^8 under the model); jitter-tolerant
        self.phi_threshold = 8.0
        self_member = ClusterMember(self_node_id, roles, rest_endpoint)
        self._members[self_node_id] = self_member

    # --- membership --------------------------------------------------------
    def join(self, member: ClusterMember) -> None:
        with self._lock:
            existing = self._members.get(member.node_id)
            self._members[member.node_id] = member
        self.broker.publish(ClusterChange("update" if existing else "add", member))

    def leave(self, node_id: str) -> None:
        with self._lock:
            member = self._members.pop(node_id, None)
        if member is not None:
            self.broker.publish(ClusterChange("remove", member))

    PHI_WINDOW = 32
    MIN_SAMPLES = 4

    def record_heartbeat(self, node_id: str) -> None:
        with self._lock:
            member = self._members.get(node_id)
            if member is not None:
                now = monotonic()
                interval = now - member.last_heartbeat
                if 0 < interval < self.dead_after_secs * 4:
                    member.intervals.append(interval)
                    if len(member.intervals) > self.PHI_WINDOW:
                        member.intervals.pop(0)
                member.last_heartbeat = now

    def phi(self, member: ClusterMember, now: Optional[float] = None) -> float:
        """Suspicion level (phi-accrual): -log10 P(no heartbeat for this
        long | observed cadence), exponential model. Below MIN_SAMPLES the
        detector abstains (returns 0) and the hard age bound governs."""
        import math
        if len(member.intervals) < self.MIN_SAMPLES:
            return 0.0
        now = monotonic() if now is None else now
        mean = sum(member.intervals) / len(member.intervals)
        age = now - member.last_heartbeat
        return age / max(mean, 1e-6) * math.log10(math.e)

    def is_alive(self, member: ClusterMember,
                 now: Optional[float] = None) -> bool:
        """Hybrid accrual: phi ACCELERATES detection of fast-cadence peers
        (a 100ms heartbeater silent for seconds is suspect long before the
        wall-clock bound), floored so a single GC pause cannot flap
        membership; `dead_after_secs` stays the authoritative upper
        bound regardless of cadence."""
        if member.node_id == self.self_node_id:
            return True
        now = monotonic() if now is None else now
        age = now - member.last_heartbeat
        if age > self.dead_after_secs:
            return False  # hard bound
        if age < min(self.dead_after_secs / 4, 2.0):
            return True  # flap floor: brief pauses never kill a peer
        return self.phi(member, now) < self.phi_threshold

    def upsert_heartbeat(self, member: ClusterMember) -> None:
        """Gossip upsert shared by both heartbeat transports (outbound
        client + inbound REST route): join only when the peer is new or
        its roles/endpoint changed (avoids a ClusterChange broadcast per
        tick), then stamp liveness either way."""
        current = self.member(member.node_id)
        if (current is None or current.roles != member.roles
                or current.rest_endpoint != member.rest_endpoint
                or current.grpc_endpoint != member.grpc_endpoint):
            self.join(member)
        self.record_heartbeat(member.node_id)

    # --- queries -----------------------------------------------------------
    def members(self, alive_only: bool = True) -> list[ClusterMember]:
        now = monotonic()
        with self._lock:
            out = []
            for member in self._members.values():
                if alive_only and not self.is_alive(member, now):
                    continue
                out.append(member)
            return sorted(out, key=lambda m: m.node_id)

    def nodes_with_role(self, role: str, alive_only: bool = True) -> list[str]:
        return [m.node_id for m in self.members(alive_only) if role in m.roles]

    def member(self, node_id: str) -> Optional[ClusterMember]:
        with self._lock:
            return self._members.get(node_id)

    def is_ready(self) -> bool:
        return bool(self.nodes_with_role("searcher") or
                    self.nodes_with_role("indexer"))

    def subscribe(self, handler: Callable[[ClusterChange], None]):
        return self.broker.subscribe(ClusterChange, handler)
