"""Partition routing expressions (reference:
`quickwit-doc-mapper/src/routing_expression/mod.rs`).

A doc mapping's `partition_key` is a tiny DSL over document fields:

    RoutingExpr   := SubExpr [ "," RoutingExpr ]
    SubExpr       := Identifier [ "(" Arguments ")" ]
    Identifier    := field path chars (alnum _ - . \\ / @ $), `\\.` escapes
                     a literal dot inside one path segment
    Arguments     := ( "(" RoutingExpr ")" | SubExpr | Number ) [ "," ... ]

with one function, `hash_mod(expr, N)`. Evaluation hashes the addressed
document values into a stable 64-bit partition id: docs with equal keys
land in the same partition, so splits hold value-homogeneous doc sets
(better tag pruning, cheaper targeted deletes) and only same-partition
splits merge.

Hashing diverges from the reference deliberately: instead of SipHash we
feed the same type-tagged byte encoding (injective per JSON value) into
blake2b — stable across processes and platforms, no third-party dep. The
expression structure is folded into the hash exactly like the reference
salts its hasher with the expression tree, so changing the expression
changes every partition id.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass
from typing import Any, Optional


class RoutingExprError(ValueError):
    pass


# --------------------------------------------------------------------------
# AST

@dataclass(frozen=True)
class _Field:
    path: tuple[str, ...]


@dataclass(frozen=True)
class _Composite:
    children: tuple[Any, ...]


@dataclass(frozen=True)
class _Modulo:
    inner: Any
    modulo: int


# --------------------------------------------------------------------------
# parser

_IDENT_CHARS = set("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-.\\/@$")


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def _ws(self) -> None:
        while self.pos < len(self.text) and self.text[self.pos].isspace():
            self.pos += 1

    def _peek(self) -> str:
        return self.text[self.pos] if self.pos < len(self.text) else ""

    def parse(self) -> Any:
        exprs = self._routing_expr()
        self._ws()
        if self.pos != len(self.text):
            raise RoutingExprError(
                f"unexpected trailing input at {self.pos}: "
                f"{self.text[self.pos:]!r}")
        if not exprs:
            return _Composite(())
        if len(exprs) == 1:
            return exprs[0]
        return _Composite(tuple(exprs))

    def _routing_expr(self) -> list:
        out = [self._sub_expr()]
        while True:
            self._ws()
            if self._peek() != ",":
                break
            self.pos += 1
            out.append(self._sub_expr())
        return out

    def _sub_expr(self) -> Any:
        self._ws()
        ident = self._identifier()
        self._ws()
        if self._peek() != "(":
            return _Field(_split_field_path(ident))
        self.pos += 1
        args = self._arguments()
        self._ws()
        if self._peek() != ")":
            raise RoutingExprError(f"expected ')' at {self.pos}")
        self.pos += 1
        if ident != "hash_mod":
            raise RoutingExprError(f"unknown function {ident!r}")
        if (len(args) != 2 or isinstance(args[0], int)
                or not isinstance(args[1], int)):
            raise RoutingExprError(
                "hash_mod expects (expression, number) arguments")
        if args[1] <= 0:
            raise RoutingExprError("hash_mod modulo must be positive")
        return _Modulo(args[0], args[1])

    def _arguments(self) -> list:
        args = [self._argument()]
        while True:
            self._ws()
            if self._peek() != ",":
                break
            self.pos += 1
            args.append(self._argument())
        return args

    def _argument(self) -> Any:
        self._ws()
        ch = self._peek()
        if ch.isdigit():
            start = self.pos
            while self._peek().isdigit():
                self.pos += 1
            return int(self.text[start:self.pos])
        if ch == "(":
            self.pos += 1
            exprs = self._routing_expr()
            self._ws()
            if self._peek() != ")":
                raise RoutingExprError(f"expected ')' at {self.pos}")
            self.pos += 1
            if len(exprs) == 1:
                return exprs[0]
            return _Composite(tuple(exprs))
        return self._sub_expr()

    def _identifier(self) -> str:
        start = self.pos
        while self._peek() in _IDENT_CHARS and self._peek():
            # `\x` consumes the escaped char with the backslash
            if self.text[self.pos] == "\\" and self.pos + 1 < len(self.text):
                self.pos += 2
            else:
                self.pos += 1
        if self.pos == start:
            raise RoutingExprError(
                f"expected identifier at position {self.pos}")
        return self.text[start:self.pos]


def _split_field_path(ident: str) -> tuple[str, ...]:
    """Split on unescaped dots; `\\.` is a literal dot in a segment."""
    parts: list[str] = []
    cur: list[str] = []
    i = 0
    while i < len(ident):
        ch = ident[i]
        if ch == "\\" and i + 1 < len(ident):
            cur.append(ident[i + 1])
            i += 2
        elif ch == ".":
            parts.append("".join(cur))
            cur = []
            i += 1
        else:
            cur.append(ch)
            i += 1
    parts.append("".join(cur))
    if any(not p for p in parts):
        raise RoutingExprError(f"empty path segment in {ident!r}")
    return tuple(parts)


# --------------------------------------------------------------------------
# evaluation

class _Hasher:
    """Structured stable hasher (role of the reference's SipHasher use)."""

    def __init__(self, seed: bytes = b""):
        self._h = hashlib.blake2b(seed, digest_size=8)

    def write(self, data: bytes) -> None:
        self._h.update(data)

    def write_u8(self, v: int) -> None:
        self._h.update(bytes([v]))

    def write_u64(self, v: int) -> None:
        self._h.update(struct.pack("<Q", v & (2**64 - 1)))

    def finish(self) -> int:
        return struct.unpack("<Q", self._h.digest())[0]

    def state(self) -> bytes:
        return self._h.digest()


_TAG_FIELD, _TAG_COMPOSITE, _TAG_MODULO = 0, 1, 2


def _hash_json_value(value: Any, hasher: _Hasher) -> None:
    """Injective per-value byte feed (reference `hash_json_val`)."""
    if value is None:
        hasher.write_u8(0)
    elif isinstance(value, bool):
        hasher.write_u8(1)
        hasher.write_u8(1 if value else 0)
    elif isinstance(value, (int, float)):
        hasher.write_u8(2)
        hasher.write(repr(value).encode())
    elif isinstance(value, str):
        data = value.encode()
        hasher.write_u8(3)
        hasher.write_u64(len(data))
        hasher.write(data)
    elif isinstance(value, list):
        hasher.write_u8(4)
        hasher.write_u64(len(value))
        for item in value:
            _hash_json_value(item, hasher)
    elif isinstance(value, dict):
        hasher.write_u8(5)
        hasher.write_u64(len(value))
        # sorted order: JSON-equal objects must hash equal regardless of
        # key insertion order (equal-key-same-partition contract)
        for key, val in sorted(value.items(), key=lambda kv: str(kv[0])):
            kdata = str(key).encode()
            hasher.write_u64(len(kdata))
            hasher.write(kdata)
            _hash_json_value(val, hasher)
    else:
        hasher.write_u8(6)
        hasher.write(str(value).encode())


_MISSING = object()


def _find_value(doc: Any, path: tuple[str, ...]) -> Any:
    """Value at `path`, or the _MISSING sentinel (a present null is a
    value, distinct from an absent key — matching the reference)."""
    for key in path:
        if not isinstance(doc, dict) or key not in doc:
            return _MISSING
        doc = doc[key]
    return doc


def _eval(node: Any, doc: dict, hasher: _Hasher) -> None:
    if isinstance(node, _Field):
        hasher.write_u8(_TAG_FIELD)
        value = _find_value(doc, node.path)
        if value is _MISSING:
            hasher.write_u8(0)
        else:
            hasher.write_u8(1)
            _hash_json_value(value, hasher)
    elif isinstance(node, _Composite):
        hasher.write_u8(_TAG_COMPOSITE)
        for child in node.children:
            _eval(child, doc, hasher)
    else:  # _Modulo
        hasher.write_u8(_TAG_MODULO)
        sub = _Hasher()
        _eval(node.inner, doc, sub)
        hasher.write_u64(sub.finish() % node.modulo)


def _hash_structure(node: Any, hasher: _Hasher) -> None:
    """Salt with the expression tree (reference Hash for InnerRoutingExpr)."""
    if isinstance(node, _Field):
        hasher.write_u8(_TAG_FIELD)
        hasher.write_u64(len(node.path))
        hasher.write(".".join(node.path).encode())
    elif isinstance(node, _Composite):
        hasher.write_u8(_TAG_COMPOSITE)
        for child in node.children:
            _hash_structure(child, hasher)
    else:
        hasher.write_u8(_TAG_MODULO)
        _hash_structure(node.inner, hasher)
        hasher.write_u64(node.modulo)


class RoutingExpr:
    """Compiled partition routing expression."""

    def __init__(self, expr: str = ""):
        expr = (expr or "").strip()
        self.source = expr
        if not expr:
            self._inner = None
            self._salt = b""
            return
        self._inner = _Parser(expr).parse()
        salt_hasher = _Hasher()
        _hash_structure(self._inner, salt_hasher)
        self._salt = salt_hasher.state()

    @property
    def is_empty(self) -> bool:
        return self._inner is None

    def field_names(self) -> list[str]:
        out: list[str] = []

        def walk(node):
            if isinstance(node, _Field):
                out.append(".".join(node.path))
            elif isinstance(node, _Composite):
                for child in node.children:
                    walk(child)
            elif isinstance(node, _Modulo):
                walk(node.inner)

        if self._inner is not None:
            walk(self._inner)
        return out

    def eval_hash(self, doc: dict) -> int:
        """Stable u64 partition id for a JSON document (0 when empty)."""
        if self._inner is None:
            return 0
        hasher = _Hasher(self._salt)
        _eval(self._inner, doc, hasher)
        return hasher.finish()
