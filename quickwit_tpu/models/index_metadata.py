"""Index metadata: config + sources + checkpoints.

Role of the reference's `quickwit-metastore/src/metastore/index_metadata.rs`:
the per-index record held by the metastore — the index config (doc mapping,
settings, retention), registered sources, and per-source checkpoints.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..common.clock import wall_time
from .doc_mapper import DocMapper


@dataclass
class RetentionPolicy:
    period_seconds: int
    schedule: str = "hourly"

    def to_dict(self) -> dict[str, Any]:
        return {"period_seconds": self.period_seconds, "schedule": self.schedule}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "RetentionPolicy":
        return RetentionPolicy(d["period_seconds"], d.get("schedule", "hourly"))


@dataclass
class IndexConfig:
    """Reference: `quickwit-config/src/index_config/mod.rs`."""
    index_id: str
    index_uri: str
    doc_mapper: DocMapper
    commit_timeout_secs: int = 60
    split_num_docs_target: int = 10_000_000
    merge_policy: dict[str, Any] = field(default_factory=lambda: {"type": "stable_log"})
    retention: Optional[RetentionPolicy] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "index_id": self.index_id,
            "index_uri": self.index_uri,
            "doc_mapping": self.doc_mapper.to_dict(),
            "commit_timeout_secs": self.commit_timeout_secs,
            "split_num_docs_target": self.split_num_docs_target,
            "merge_policy": self.merge_policy,
            "retention": self.retention.to_dict() if self.retention else None,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "IndexConfig":
        return IndexConfig(
            index_id=d["index_id"],
            index_uri=d["index_uri"],
            doc_mapper=DocMapper.from_dict(d["doc_mapping"]),
            commit_timeout_secs=d.get("commit_timeout_secs", 60),
            split_num_docs_target=d.get("split_num_docs_target", 10_000_000),
            merge_policy=d.get("merge_policy", {"type": "stable_log"}),
            retention=RetentionPolicy.from_dict(d["retention"]) if d.get("retention") else None,
        )


@dataclass
class SourceConfig:
    """Reference: `quickwit-config/src/source_config/mod.rs`."""
    source_id: str
    source_type: str  # "file" | "vec" | "void" | "ingest" | "kafka-stub" | ...
    params: dict[str, Any] = field(default_factory=dict)
    enabled: bool = True
    num_pipelines: int = 1

    def to_dict(self) -> dict[str, Any]:
        return {
            "source_id": self.source_id, "source_type": self.source_type,
            "params": self.params, "enabled": self.enabled,
            "num_pipelines": self.num_pipelines,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "SourceConfig":
        return SourceConfig(
            source_id=d["source_id"], source_type=d["source_type"],
            params=d.get("params", {}), enabled=d.get("enabled", True),
            num_pipelines=d.get("num_pipelines", 1),
        )


@dataclass
class IndexMetadata:
    index_uid: str  # "{index_id}:{incarnation}"
    index_config: IndexConfig
    sources: dict[str, SourceConfig] = field(default_factory=dict)
    # source_id -> partition_id -> position (exactly-once checkpoints,
    # reference: quickwit-metastore/src/checkpoint.rs)
    checkpoints: dict[str, dict[str, str]] = field(default_factory=dict)
    create_timestamp: int = field(default_factory=lambda: int(wall_time()))

    @property
    def index_id(self) -> str:
        return self.index_uid.split(":", 1)[0]

    def to_dict(self) -> dict[str, Any]:
        return {
            "index_uid": self.index_uid,
            "index_config": self.index_config.to_dict(),
            "sources": {sid: s.to_dict() for sid, s in self.sources.items()},
            "checkpoints": self.checkpoints,
            "create_timestamp": self.create_timestamp,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "IndexMetadata":
        return IndexMetadata(
            index_uid=d["index_uid"],
            index_config=IndexConfig.from_dict(d["index_config"]),
            sources={sid: SourceConfig.from_dict(s) for sid, s in d.get("sources", {}).items()},
            checkpoints=d.get("checkpoints", {}),
            create_timestamp=d.get("create_timestamp", 0),
        )
