"""Split metadata + lifecycle.

Role of the reference's `quickwit-metastore/src/split_metadata.rs`: the
metastore-side record of one immutable split — id, doc count, size, time
range, tags, delete opstamp, maturity — plus the Staged → Published →
MarkedForDeletion lifecycle enforced by the metastore.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional

from ..common.clock import get_rng, wall_time


class SplitState(str, Enum):
    STAGED = "Staged"
    PUBLISHED = "Published"
    MARKED_FOR_DELETION = "MarkedForDeletion"


def new_split_id() -> str:
    # ULID-like: time-ordered prefix + random suffix (reference uses ULIDs).
    # Both components come from the process clock/rng seams: under the DST
    # harness split ids are then a pure function of the scenario seed, which
    # keeps rendezvous placement (hashed over split ids) replayable.
    return (f"{int(wall_time() * 1000):013d}-"
            f"{get_rng().getrandbits(48):012x}")


@dataclass
class SplitMetadata:
    split_id: str
    index_uid: str
    source_id: str = "_unknown"
    node_id: str = "_unknown"
    num_docs: int = 0
    uncompressed_docs_size_bytes: int = 0
    footprint_bytes: int = 0  # size of the .split file
    time_range_start: Optional[int] = None  # micros since epoch, inclusive
    time_range_end: Optional[int] = None    # inclusive
    tags: frozenset[str] = field(default_factory=frozenset)
    create_timestamp: int = 0
    maturity_timestamp: int = 0  # 0 == mature immediately
    delete_opstamp: int = 0
    num_merge_ops: int = 0
    doc_mapping_uid: str = "default"
    partition_id: int = 0
    # per-column min/max of the split's numeric fast columns — the
    # split-granular zonemap (reference: quickwit-parquet-engine
    # src/zonemap/): the root prunes splits whose bounds preclude a
    # required numeric predicate before any byte of them is fetched
    column_bounds: dict[str, tuple[Any, Any]] = field(default_factory=dict)

    def is_mature(self, now_ts: Optional[float] = None) -> bool:
        if self.maturity_timestamp == 0:
            return True
        return (now_ts if now_ts is not None else wall_time()) >= self.maturity_timestamp

    def overlaps_time_range(self, start_micros: Optional[int], end_micros: Optional[int]) -> bool:
        """Time pruning predicate (reference: ListSplitsQuery time filter)."""
        if self.time_range_start is None or self.time_range_end is None:
            return True  # splits without a time range can never be pruned
        if start_micros is not None and self.time_range_end < start_micros:
            return False
        if end_micros is not None and self.time_range_start > end_micros:
            return False
        return True

    def matches_tags(self, required_tags: Optional[set[str]]) -> bool:
        """Tag pruning: the split may contain a match only if every required
        tag is present (reference: `tag_pruning.rs` conservative predicate)."""
        if not required_tags:
            return True
        return required_tags.issubset(self.tags)

    def to_dict(self) -> dict[str, Any]:
        return {
            "split_id": self.split_id, "index_uid": self.index_uid,
            "source_id": self.source_id, "node_id": self.node_id,
            "num_docs": self.num_docs,
            "uncompressed_docs_size_bytes": self.uncompressed_docs_size_bytes,
            "footprint_bytes": self.footprint_bytes,
            "time_range_start": self.time_range_start,
            "time_range_end": self.time_range_end,
            "tags": sorted(self.tags),
            "create_timestamp": self.create_timestamp,
            "maturity_timestamp": self.maturity_timestamp,
            "delete_opstamp": self.delete_opstamp,
            "num_merge_ops": self.num_merge_ops,
            "doc_mapping_uid": self.doc_mapping_uid,
            "partition_id": self.partition_id,
            "column_bounds": {name: list(bounds) for name, bounds
                              in self.column_bounds.items()},
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "SplitMetadata":
        return SplitMetadata(
            split_id=d["split_id"], index_uid=d["index_uid"],
            source_id=d.get("source_id", "_unknown"), node_id=d.get("node_id", "_unknown"),
            num_docs=d.get("num_docs", 0),
            uncompressed_docs_size_bytes=d.get("uncompressed_docs_size_bytes", 0),
            footprint_bytes=d.get("footprint_bytes", 0),
            time_range_start=d.get("time_range_start"),
            time_range_end=d.get("time_range_end"),
            tags=frozenset(d.get("tags", ())),
            create_timestamp=d.get("create_timestamp", 0),
            maturity_timestamp=d.get("maturity_timestamp", 0),
            delete_opstamp=d.get("delete_opstamp", 0),
            num_merge_ops=d.get("num_merge_ops", 0),
            doc_mapping_uid=d.get("doc_mapping_uid", "default"),
            partition_id=d.get("partition_id", 0),
            column_bounds={name: tuple(bounds) for name, bounds
                           in d.get("column_bounds", {}).items()},
        )


@dataclass
class Split:
    """A split + its lifecycle state, as stored by the metastore."""
    metadata: SplitMetadata
    state: SplitState = SplitState.STAGED
    update_timestamp: int = 0
    publish_timestamp: Optional[int] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "metadata": self.metadata.to_dict(),
            "state": self.state.value,
            "update_timestamp": self.update_timestamp,
            "publish_timestamp": self.publish_timestamp,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "Split":
        return Split(
            metadata=SplitMetadata.from_dict(d["metadata"]),
            state=SplitState(d["state"]),
            update_timestamp=d.get("update_timestamp", 0),
            publish_timestamp=d.get("publish_timestamp"),
        )
