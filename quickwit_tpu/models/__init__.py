from .doc_mapper import DocMapper, FieldMapping, FieldType, DocParsingError
from .split_metadata import SplitMetadata, SplitState
from .index_metadata import IndexMetadata

__all__ = [
    "DocMapper", "FieldMapping", "FieldType", "DocParsingError",
    "SplitMetadata", "SplitState", "IndexMetadata",
]
