"""Doc mapping: JSON documents → typed docs, and the schema they obey.

Role of the reference's `quickwit-doc-mapper` (`doc_mapper_impl.rs`,
`mapping_tree.rs`, `field_mapping_entry.rs`): the per-index schema that
 - validates and types incoming JSON documents,
 - declares which fields are indexed (inverted), fast (columnar), stored,
 - names the timestamp field used for split pruning,
 - declares tag fields and default search fields,
 - is the context against which a QueryAst is lowered.

TPU-first divergence: fields are a *flat* list of dot-separated paths (the
reference flattens its mapping tree the same way at tantivy-schema build
time), and fast fields are laid out as dense HBM-friendly columns
(see `index/columns.py`).

Dynamic mode (`mode: dynamic` + `dynamic_mapping`, reference:
`field_mapping_entry.rs:613` QuickwitJsonOptions::default_dynamic): every
unmapped leaf path materializes per split as a raw-tokenized text field
whose terms carry the canonical string form of the JSON value — the
analogue of tantivy's path-prefixed JSON terms, on this engine's padded
posting arrays. Term/full-text/phrase queries on unmapped paths resolve
against these per-split fields at plan time. Fast columns for dynamic
paths are not materialized yet (range/sort/agg on a dynamic path needs a
concrete mapping; the config's `fast` flag is accepted for compatibility).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from enum import Enum
from typing import Any, Iterator, Optional, Sequence

from ..query.tokenizers import get_tokenizer
from ..utils.datetime_utils import parse_datetime_to_micros


class DocParsingError(ValueError):
    pass


class FieldType(str, Enum):
    TEXT = "text"
    I64 = "i64"
    U64 = "u64"
    F64 = "f64"
    BOOL = "bool"
    DATETIME = "datetime"
    IP = "ip"
    BYTES = "bytes"
    JSON = "json"


@dataclass(frozen=True)
class FieldMapping:
    """One field of the schema (reference: `FieldMappingEntry`)."""
    name: str  # dot-separated path, e.g. "resource.service"
    type: FieldType
    tokenizer: str = "default"      # for TEXT
    record: str = "basic"           # "basic" (doc,tf) | "position" (phrase-capable)
    indexed: bool = True
    fast: bool = False
    stored: bool = True
    input_formats: tuple[str, ...] = ("rfc3339", "unix_timestamp")  # DATETIME
    output_format: str = "rfc3339"
    # normalizer applied to TEXT fast-column values (reference:
    # `fast: {normalizer: lowercase}` — terms aggs and fast-field reads
    # observe the normalized form)
    normalizer: Optional[str] = None
    # DATETIME fast-column precision (reference `fast_precision`):
    # "seconds" | "milliseconds" | None (microseconds). Stored values AND
    # range bounds truncate to it, so sub-precision bounds behave like ES.
    fast_precision: Optional[str] = None
    # `type: concatenate` (reference: field_mapping_entry.rs concatenate
    # fields): a synthetic TEXT field indexing the canonical leaf values
    # of the listed source fields (and, optionally, of every dynamic
    # leaf) under ITS OWN tokenizer. Internally typed TEXT; non-empty
    # concatenate_fields marks it.
    concatenate_fields: tuple[str, ...] = ()
    include_dynamic_fields: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "type": ("concatenate" if self.concatenate_fields
                     else self.type.value),
            "tokenizer": self.tokenizer,
            "record": self.record, "indexed": self.indexed, "fast": self.fast,
            "stored": self.stored, "input_formats": list(self.input_formats),
            "output_format": self.output_format, "normalizer": self.normalizer,
            "fast_precision": self.fast_precision,
            "concatenate_fields": list(self.concatenate_fields),
            "include_dynamic_fields": self.include_dynamic_fields,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "FieldMapping":
        fast = d.get("fast", False)
        normalizer = d.get("normalizer")
        if isinstance(fast, dict):
            # reference shape: `fast: {normalizer: lowercase}`
            normalizer = fast.get("normalizer", normalizer)
            fast = True
        type_name = d["type"]
        concatenate_fields = tuple(d.get("concatenate_fields", ()))
        if type_name == "concatenate":
            type_name = "text"
            if not concatenate_fields:
                raise ValueError(
                    f"concatenate field {d['name']!r} needs concatenate_fields")
        return FieldMapping(
            name=d["name"], type=FieldType(type_name),
            tokenizer=d.get("tokenizer", "default"), record=d.get("record", "basic"),
            indexed=d.get("indexed", True), fast=fast,
            stored=d.get("stored", True),
            input_formats=tuple(d.get("input_formats", ("rfc3339", "unix_timestamp"))),
            output_format=d.get("output_format", "rfc3339"),
            normalizer=normalizer,
            fast_precision=d.get("fast_precision"),
            concatenate_fields=concatenate_fields,
            include_dynamic_fields=d.get("include_dynamic_fields", False),
        )


@dataclass(frozen=True)
class DynamicMapping:
    """Indexing options applied to unmapped fields under `mode: dynamic`
    (reference: QuickwitJsonOptions, `field_mapping_entry.rs:621`)."""
    indexed: bool = True
    tokenizer: str = "raw"     # reference default_json: raw, no fieldnorms
    record: str = "basic"
    stored: bool = True
    fast: bool = True          # per-split typed dynamic columns
    expand_dots: bool = True

    def to_dict(self) -> dict[str, Any]:
        return {"indexed": self.indexed, "tokenizer": self.tokenizer,
                "record": self.record, "stored": self.stored,
                "fast": self.fast, "expand_dots": self.expand_dots}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "DynamicMapping":
        fast = d.get("fast", True)
        if isinstance(fast, dict):
            fast = True
        return DynamicMapping(
            indexed=d.get("indexed", True),
            tokenizer=d.get("tokenizer", "raw"),
            record=d.get("record", "basic"),
            stored=d.get("stored", True), fast=fast,
            expand_dots=d.get("expand_dots", True))


def _iter_path(doc: Any, path: Sequence[str]) -> Iterator[Any]:
    """Yield all values at `path` in a (possibly nested/array) JSON doc."""
    if not path:
        if isinstance(doc, list):
            yield from doc
        elif doc is not None:
            yield doc
        return
    if isinstance(doc, list):
        for item in doc:
            yield from _iter_path(item, path)
    elif isinstance(doc, dict):
        key = path[0]
        if key in doc:
            yield from _iter_path(doc[key], path[1:])


@dataclass
class TypedDoc:
    """A validated document: per-field typed values + the raw source."""
    fields: dict[str, list[Any]]
    source: dict[str, Any]

    def timestamp_micros(self, timestamp_field: Optional[str]) -> Optional[int]:
        if timestamp_field is None:
            return None
        values = self.fields.get(timestamp_field)
        return values[0] if values else None


@dataclass
class DocMapper:
    """Schema + conversion + (via search/plan.py) query lowering context.

    Reference parity: `DocMapper::doc_from_json` → `validate/convert`;
    `DocMapper::query` is implemented in `search/plan.py::lower_ast` against
    this object.
    """
    doc_mapping_uid: str = "default"
    field_mappings: list[FieldMapping] = dc_field(default_factory=list)
    timestamp_field: Optional[str] = None
    tag_fields: tuple[str, ...] = ()
    default_search_fields: tuple[str, ...] = ()
    store_source: bool = True
    # "lenient" (unknown fields ignored) | "strict" (rejected) |
    # "dynamic" (materialized per dynamic_mapping)
    mode: str = "lenient"
    dynamic_mapping: Optional[DynamicMapping] = None
    # doc-level partition routing (reference: `routing_expression/mod.rs`,
    # doc_mapping.partition_key + max_num_partitions): docs hash to
    # partitions, each split holds one partition, only same-partition
    # splits merge
    partition_key: str = ""
    max_num_partitions: int = 200
    # reference `store_document_size`: a synthetic `_doc_length` fast
    # column holding each doc's serialized byte size (aggregatable,
    # never part of _source)
    store_document_size: bool = False

    def __post_init__(self) -> None:
        self._by_name = {fm.name: fm for fm in self.field_mappings}
        self._concat_fields = [fm for fm in self.field_mappings
                               if fm.concatenate_fields]
        # interior dotted prefixes of mapped names ("a.b.c" → {"a","a.b"}):
        # O(1) membership test on the per-doc dynamic walk
        self._interior_prefixes = set()
        for fm in self.field_mappings:
            parts = fm.name.split(".")
            for i in range(1, len(parts)):
                self._interior_prefixes.add(".".join(parts[:i]))
        if self.mode == "dynamic" and self.dynamic_mapping is None:
            self.dynamic_mapping = DynamicMapping()
        from .routing_expression import RoutingExpr
        self._routing_expr = RoutingExpr(self.partition_key)
        if self.timestamp_field is not None:
            ts = self._by_name.get(self.timestamp_field)
            if ts is None or ts.type is not FieldType.DATETIME or not ts.fast:
                raise ValueError(
                    f"timestamp_field {self.timestamp_field!r} must be a fast datetime field")

    def field(self, name: str) -> Optional[FieldMapping]:
        return self._by_name.get(name)

    def dynamic_field(self, name: str) -> FieldMapping:
        """The synthesized mapping an unmapped path gets under
        `mode: dynamic` — raw-tokenized text over canonical value strings
        (both the writer and the query lowering use this, so index- and
        query-side terms always agree). `fast` carries the dynamic
        mapping's flag: the writer materializes a per-split typed column
        (string→ordinal, int→i64, float→f64, bool→bool) behind it."""
        dm = self.dynamic_mapping or DynamicMapping()
        return FieldMapping(name, FieldType.TEXT, tokenizer=dm.tokenizer,
                            record=dm.record, indexed=dm.indexed,
                            stored=dm.stored, fast=dm.fast)

    def shadows_concrete_field(self, name: str) -> bool:
        """True when a dotted path descends through a mapped NON-JSON
        field (`text.inner` under a concrete text field): such paths are
        never dynamic — they are simply invalid."""
        parts = name.split(".")
        for i in range(1, len(parts)):
            parent = self._by_name.get(".".join(parts[:i]))
            if parent is not None:
                return parent.type is not FieldType.JSON
        return False

    @property
    def fast_fields(self) -> list[FieldMapping]:
        return [fm for fm in self.field_mappings if fm.fast]

    @property
    def indexed_fields(self) -> list[FieldMapping]:
        return [fm for fm in self.field_mappings if fm.indexed]

    # ------------------------------------------------------------------
    def doc_from_json(self, doc: dict[str, Any]) -> TypedDoc:
        if not isinstance(doc, dict):
            raise DocParsingError(f"document must be a JSON object, got {type(doc).__name__}")
        fields: dict[str, list[Any]] = {}
        for fm in self.field_mappings:
            if fm.concatenate_fields:
                continue  # synthesized below from the source fields
            raw_values = list(_iter_path(doc, fm.name.split(".")))
            if not raw_values:
                continue
            try:
                fields[fm.name] = [self._convert(fm, v) for v in raw_values]
            except (ValueError, TypeError) as exc:
                raise DocParsingError(f"field {fm.name!r}: {exc}") from exc
        if self.mode == "strict":
            known_roots = {fm.name.split(".")[0] for fm in self.field_mappings}
            for key in doc:
                if key not in known_roots:
                    raise DocParsingError(f"unknown field {key!r} in strict mapping")
        elif self.mode == "dynamic":
            self._collect_dynamic(doc, (), fields)
        if self.timestamp_field is not None and self.timestamp_field not in fields:
            # reference parity (doc_processor.rs): every doc must carry the
            # timestamp field — split time ranges then bound ALL docs, which
            # the time-pruning and metadata-count paths rely on
            raise DocParsingError(
                f"document is missing timestamp field {self.timestamp_field!r}")
        for cf in self._concat_fields:
            values = self._concat_values(cf, fields)
            if values:
                fields[cf.name] = values
        return TypedDoc(fields=fields, source=doc if self.store_source else {})

    def _concat_values(self, cf: FieldMapping,
                       fields: dict[str, list[Any]]) -> list[str]:
        """Canonical leaf-value strings a concatenate field indexes: the
        listed source fields' values (JSON fields contribute every leaf)
        plus, with include_dynamic_fields, every dynamic leaf value."""
        out: list[str] = []

        def leaves(value: Any) -> None:
            if isinstance(value, dict):
                for v in value.values():
                    leaves(v)
            elif isinstance(value, list):
                for v in value:
                    leaves(v)
            elif value is not None:
                out.append(dynamic_canonical(value))

        for src in cf.concatenate_fields:
            for value in fields.get(src, ()):
                leaves(value)
        if cf.include_dynamic_fields:
            for name, values in fields.items():
                if name not in self._by_name:  # dynamic leaf
                    for value in values:
                        leaves(value)
        return out

    def _collect_dynamic(self, node: Any, path: tuple[str, ...],
                         fields: dict[str, list[Any]]) -> None:
        """Walk the doc's UNMAPPED parts, materializing each leaf value
        under its dotted path as a canonical string (numbers/bools index
        the same string the query lowering produces)."""
        if isinstance(node, dict):
            for key, value in node.items():
                sub = path + (key,)
                dotted = ".".join(sub)
                fm = self._by_name.get(dotted)
                if fm is not None:
                    if fm.type is FieldType.JSON:
                        # subpaths of a mapped JSON field stay searchable
                        # in dynamic mode via dynamic leaves (the whole
                        # value is separately stored under the mapping)
                        self._collect_dynamic_leaves(value, sub, fields)
                    elif "." in key and not fields.get(dotted):
                        # literal dotted key colliding with a mapped name
                        # (expand_dots): route it to the concrete mapping
                        # instead of silently dropping it
                        raw = value if isinstance(value, list) else [value]
                        try:
                            fields[dotted] = [self._convert(fm, v)
                                              for v in raw if v is not None]
                        except (ValueError, TypeError) as exc:
                            raise DocParsingError(
                                f"field {dotted!r}: {exc}") from exc
                    continue
                if dotted in self._interior_prefixes:
                    # interior node of the concrete schema: only its
                    # unmapped children are dynamic
                    self._collect_dynamic(value, sub, fields)
                else:
                    self._collect_dynamic_leaves(value, sub, fields)
        elif isinstance(node, list):
            for item in node:
                self._collect_dynamic(item, path, fields)

    def _collect_dynamic_leaves(self, node: Any, path: tuple[str, ...],
                                fields: dict[str, list[Any]]) -> None:
        """Collect RAW leaf values (bool/int/float/str) under dotted
        paths. The writer types each dynamic leaf per split from these
        (long/double/boolean/string value classes — reference: tantivy's
        typed JSON terms + dynamic columns); term lowering uses the
        canonical string form (`dynamic_canonical`)."""
        if node is None:
            return
        if isinstance(node, dict):
            for key, value in node.items():
                self._collect_dynamic_leaves(value, path + (key,), fields)
            return
        if isinstance(node, list):
            for item in node:
                self._collect_dynamic_leaves(item, path, fields)
            return
        fields.setdefault(".".join(path), []).append(node)

    def _convert(self, fm: FieldMapping, value: Any) -> Any:
        t = fm.type
        if t is FieldType.TEXT:
            if not isinstance(value, str):
                value = str(value)
            return value
        if t is FieldType.I64:
            if isinstance(value, bool) or not isinstance(value, (int, float, str)):
                raise ValueError(f"expected i64, got {value!r}")
            return int(value)
        if t is FieldType.U64:
            if isinstance(value, bool):
                raise ValueError(f"expected u64, got {value!r}")
            iv = int(value)
            if iv < 0:
                raise ValueError(f"expected u64, got {value!r}")
            return iv
        if t is FieldType.F64:
            if isinstance(value, bool):
                raise ValueError(f"expected f64, got {value!r}")
            return float(value)
        if t is FieldType.BOOL:
            if isinstance(value, bool):
                return value
            if isinstance(value, str) and value.lower() in ("true", "false"):
                return value.lower() == "true"
            raise ValueError(f"expected bool, got {value!r}")
        if t is FieldType.DATETIME:
            return parse_datetime_to_micros(value, fm.input_formats)
        if t is FieldType.IP:
            import ipaddress
            return int(ipaddress.ip_address(value))
        if t is FieldType.BYTES:
            import base64
            if isinstance(value, str):
                return base64.b64decode(value)
            raise ValueError(f"expected base64 string, got {value!r}")
        if t is FieldType.JSON:
            return value
        raise ValueError(f"unhandled field type {t}")

    # ------------------------------------------------------------------
    def tokens_for_field(self, fm: FieldMapping, value: Any) -> list:
        """Index tokens for one value of one field."""
        if fm.type is FieldType.TEXT:
            return get_tokenizer(fm.tokenizer)(value)
        # non-text indexed fields index their canonical string form as a raw term
        from ..query.tokenizers import Token
        return [Token(canonical_term(fm, value), 0)]

    def partition_id(self, doc: dict[str, Any]) -> int:
        """Stable u64 partition for a raw JSON doc (0 = unpartitioned)."""
        return self._routing_expr.eval_hash(doc)

    def tags(self, tdoc: TypedDoc) -> set[str]:
        """`tag_field:value` strings recorded in split metadata for pruning
        (reference: `tag_pruning.rs`)."""
        out: set[str] = set()
        for tag_field in self.tag_fields:
            for v in tdoc.fields.get(tag_field, []):
                out.add(f"{tag_field}:{v}")
        return out

    # ------------------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return {
            "doc_mapping_uid": self.doc_mapping_uid,
            "field_mappings": [fm.to_dict() for fm in self.field_mappings],
            "timestamp_field": self.timestamp_field,
            "tag_fields": list(self.tag_fields),
            "default_search_fields": list(self.default_search_fields),
            "store_source": self.store_source,
            "mode": self.mode,
            "dynamic_mapping": (self.dynamic_mapping.to_dict()
                                if self.dynamic_mapping else None),
            "partition_key": self.partition_key,
            "max_num_partitions": self.max_num_partitions,
            "store_document_size": self.store_document_size,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "DocMapper":
        if not isinstance(d, dict):
            raise ValueError(
                f"doc_mapping must be a JSON object, "
                f"got {type(d).__name__}")
        if not isinstance(d.get("field_mappings", []), list):
            raise ValueError("field_mappings must be a list")
        for key in ("tag_fields", "default_search_fields"):
            value = d.get(key, [])
            if not isinstance(value, (list, tuple)) or not all(
                    isinstance(f, str) for f in value):
                raise ValueError(f"{key} must be a list of strings")
        if d.get("dynamic_mapping") is not None \
                and not isinstance(d["dynamic_mapping"], dict):
            raise ValueError("dynamic_mapping must be a JSON object")
        return DocMapper(
            doc_mapping_uid=d.get("doc_mapping_uid", "default"),
            field_mappings=_expand_field_mappings(d.get("field_mappings", [])),
            timestamp_field=d.get("timestamp_field"),
            tag_fields=tuple(d.get("tag_fields", ())),
            default_search_fields=tuple(d.get("default_search_fields", ())),
            store_source=d.get("store_source", True),
            mode=d.get("mode", "lenient"),
            dynamic_mapping=(DynamicMapping.from_dict(d["dynamic_mapping"])
                             if d.get("dynamic_mapping") else None),
            partition_key=d.get("partition_key", ""),
            max_num_partitions=d.get("max_num_partitions", 200),
            store_document_size=d.get("store_document_size", False),
        )


def _expand_field_mappings(entries: Sequence[dict],
                           prefix: str = "") -> list[FieldMapping]:
    """Parse field-mapping entries, flattening `type: object` groups into
    dotted paths (reference: `mapping_tree.rs` builds the same flat
    tantivy schema from its nested tree) and accepting the `array<T>`
    aliases (every field is multivalued in this engine, so array<T> ≡ T)."""
    out: list[FieldMapping] = []
    for d in entries:
        if not isinstance(d, dict):
            raise ValueError(
                f"field mapping entry must be an object, got {d!r}")
        if not isinstance(d.get("name"), str) or not d["name"]:
            raise ValueError(
                f"field mapping entry requires a string name "
                f"(got {d.get('name')!r})")
        typ = str(d.get("type", "text"))
        if typ.startswith("array<") and typ.endswith(">"):
            d = {**d, "type": typ[len("array<"):-1]}
            typ = d["type"]
        name = prefix + d["name"]
        if typ == "object":
            out.extend(_expand_field_mappings(
                d.get("field_mappings", []), name + "."))
        else:
            out.append(FieldMapping.from_dict({**d, "name": name}))
    return out


def dynamic_canonical(value: Any) -> str:
    """Canonical string form of a dynamic leaf value — shared by the
    writer (index terms, ordinal column entries) and the query lowering,
    so both sides always agree."""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float):
        return repr(value)
    return str(value)


def canonical_term(fm: FieldMapping, value: Any) -> str:
    """Canonical index-term string for a non-text value.

    Numeric/datetime/bool/ip values are indexed under a canonical string so
    query-side Term("field","42") matches; mirrors tantivy's typed terms.
    """
    if fm.type is FieldType.BOOL:
        return "true" if value else "false"
    if fm.type in (FieldType.I64, FieldType.U64, FieldType.DATETIME, FieldType.IP):
        return str(int(value))
    if fm.type is FieldType.F64:
        return repr(float(value))
    if fm.type is FieldType.BYTES:
        import base64
        return base64.b64encode(value).decode()
    return str(value)
