"""Doc mapping: JSON documents → typed docs, and the schema they obey.

Role of the reference's `quickwit-doc-mapper` (`doc_mapper_impl.rs`,
`mapping_tree.rs`, `field_mapping_entry.rs`): the per-index schema that
 - validates and types incoming JSON documents,
 - declares which fields are indexed (inverted), fast (columnar), stored,
 - names the timestamp field used for split pruning,
 - declares tag fields and default search fields,
 - is the context against which a QueryAst is lowered.

TPU-first divergence: fields are a *flat* list of dot-separated paths (the
reference flattens its mapping tree the same way at tantivy-schema build
time), and fast fields are laid out as dense HBM-friendly columns
(see `index/columns.py`). Dynamic (schemaless) JSON fields are handled by a
catch-all `_dynamic` text field (tokenized `path.segments:value` pairs),
a simplification of the reference's dynamic mapping.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from enum import Enum
from typing import Any, Iterator, Optional, Sequence

from ..query.tokenizers import get_tokenizer
from ..utils.datetime_utils import parse_datetime_to_micros


class DocParsingError(ValueError):
    pass


class FieldType(str, Enum):
    TEXT = "text"
    I64 = "i64"
    U64 = "u64"
    F64 = "f64"
    BOOL = "bool"
    DATETIME = "datetime"
    IP = "ip"
    BYTES = "bytes"
    JSON = "json"


@dataclass(frozen=True)
class FieldMapping:
    """One field of the schema (reference: `FieldMappingEntry`)."""
    name: str  # dot-separated path, e.g. "resource.service"
    type: FieldType
    tokenizer: str = "default"      # for TEXT
    record: str = "basic"           # "basic" (doc,tf) | "position" (phrase-capable)
    indexed: bool = True
    fast: bool = False
    stored: bool = True
    input_formats: tuple[str, ...] = ("rfc3339", "unix_timestamp")  # DATETIME
    output_format: str = "rfc3339"
    # normalizer applied to TEXT fast-column values (reference:
    # `fast: {normalizer: lowercase}` — terms aggs and fast-field reads
    # observe the normalized form)
    normalizer: Optional[str] = None
    # DATETIME fast-column precision (reference `fast_precision`):
    # "seconds" | "milliseconds" | None (microseconds). Stored values AND
    # range bounds truncate to it, so sub-precision bounds behave like ES.
    fast_precision: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "type": self.type.value, "tokenizer": self.tokenizer,
            "record": self.record, "indexed": self.indexed, "fast": self.fast,
            "stored": self.stored, "input_formats": list(self.input_formats),
            "output_format": self.output_format, "normalizer": self.normalizer,
            "fast_precision": self.fast_precision,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "FieldMapping":
        fast = d.get("fast", False)
        normalizer = d.get("normalizer")
        if isinstance(fast, dict):
            # reference shape: `fast: {normalizer: lowercase}`
            normalizer = fast.get("normalizer", normalizer)
            fast = True
        return FieldMapping(
            name=d["name"], type=FieldType(d["type"]),
            tokenizer=d.get("tokenizer", "default"), record=d.get("record", "basic"),
            indexed=d.get("indexed", True), fast=fast,
            stored=d.get("stored", True),
            input_formats=tuple(d.get("input_formats", ("rfc3339", "unix_timestamp"))),
            output_format=d.get("output_format", "rfc3339"),
            normalizer=normalizer,
            fast_precision=d.get("fast_precision"),
        )


def _iter_path(doc: Any, path: Sequence[str]) -> Iterator[Any]:
    """Yield all values at `path` in a (possibly nested/array) JSON doc."""
    if not path:
        if isinstance(doc, list):
            yield from doc
        elif doc is not None:
            yield doc
        return
    if isinstance(doc, list):
        for item in doc:
            yield from _iter_path(item, path)
    elif isinstance(doc, dict):
        key = path[0]
        if key in doc:
            yield from _iter_path(doc[key], path[1:])


@dataclass
class TypedDoc:
    """A validated document: per-field typed values + the raw source."""
    fields: dict[str, list[Any]]
    source: dict[str, Any]

    def timestamp_micros(self, timestamp_field: Optional[str]) -> Optional[int]:
        if timestamp_field is None:
            return None
        values = self.fields.get(timestamp_field)
        return values[0] if values else None


@dataclass
class DocMapper:
    """Schema + conversion + (via search/plan.py) query lowering context.

    Reference parity: `DocMapper::doc_from_json` → `validate/convert`;
    `DocMapper::query` is implemented in `search/plan.py::lower_ast` against
    this object.
    """
    doc_mapping_uid: str = "default"
    field_mappings: list[FieldMapping] = dc_field(default_factory=list)
    timestamp_field: Optional[str] = None
    tag_fields: tuple[str, ...] = ()
    default_search_fields: tuple[str, ...] = ()
    store_source: bool = True
    mode: str = "lenient"  # "lenient" | "strict": unknown fields ignored/rejected
    # reference `store_document_size`: a synthetic `_doc_length` fast
    # column holding each doc's serialized byte size (aggregatable,
    # never part of _source)
    store_document_size: bool = False

    def __post_init__(self) -> None:
        self._by_name = {fm.name: fm for fm in self.field_mappings}
        if self.timestamp_field is not None:
            ts = self._by_name.get(self.timestamp_field)
            if ts is None or ts.type is not FieldType.DATETIME or not ts.fast:
                raise ValueError(
                    f"timestamp_field {self.timestamp_field!r} must be a fast datetime field")

    def field(self, name: str) -> Optional[FieldMapping]:
        return self._by_name.get(name)

    @property
    def fast_fields(self) -> list[FieldMapping]:
        return [fm for fm in self.field_mappings if fm.fast]

    @property
    def indexed_fields(self) -> list[FieldMapping]:
        return [fm for fm in self.field_mappings if fm.indexed]

    # ------------------------------------------------------------------
    def doc_from_json(self, doc: dict[str, Any]) -> TypedDoc:
        if not isinstance(doc, dict):
            raise DocParsingError(f"document must be a JSON object, got {type(doc).__name__}")
        fields: dict[str, list[Any]] = {}
        for fm in self.field_mappings:
            raw_values = list(_iter_path(doc, fm.name.split(".")))
            if not raw_values:
                continue
            try:
                fields[fm.name] = [self._convert(fm, v) for v in raw_values]
            except (ValueError, TypeError) as exc:
                raise DocParsingError(f"field {fm.name!r}: {exc}") from exc
        if self.mode == "strict":
            known_roots = {fm.name.split(".")[0] for fm in self.field_mappings}
            for key in doc:
                if key not in known_roots:
                    raise DocParsingError(f"unknown field {key!r} in strict mapping")
        if self.timestamp_field is not None and self.timestamp_field not in fields:
            # reference parity (doc_processor.rs): every doc must carry the
            # timestamp field — split time ranges then bound ALL docs, which
            # the time-pruning and metadata-count paths rely on
            raise DocParsingError(
                f"document is missing timestamp field {self.timestamp_field!r}")
        return TypedDoc(fields=fields, source=doc if self.store_source else {})

    def _convert(self, fm: FieldMapping, value: Any) -> Any:
        t = fm.type
        if t is FieldType.TEXT:
            if not isinstance(value, str):
                value = str(value)
            return value
        if t is FieldType.I64:
            if isinstance(value, bool) or not isinstance(value, (int, float, str)):
                raise ValueError(f"expected i64, got {value!r}")
            return int(value)
        if t is FieldType.U64:
            if isinstance(value, bool):
                raise ValueError(f"expected u64, got {value!r}")
            iv = int(value)
            if iv < 0:
                raise ValueError(f"expected u64, got {value!r}")
            return iv
        if t is FieldType.F64:
            if isinstance(value, bool):
                raise ValueError(f"expected f64, got {value!r}")
            return float(value)
        if t is FieldType.BOOL:
            if isinstance(value, bool):
                return value
            if isinstance(value, str) and value.lower() in ("true", "false"):
                return value.lower() == "true"
            raise ValueError(f"expected bool, got {value!r}")
        if t is FieldType.DATETIME:
            return parse_datetime_to_micros(value, fm.input_formats)
        if t is FieldType.IP:
            import ipaddress
            return int(ipaddress.ip_address(value))
        if t is FieldType.BYTES:
            import base64
            if isinstance(value, str):
                return base64.b64decode(value)
            raise ValueError(f"expected base64 string, got {value!r}")
        if t is FieldType.JSON:
            return value
        raise ValueError(f"unhandled field type {t}")

    # ------------------------------------------------------------------
    def tokens_for_field(self, fm: FieldMapping, value: Any) -> list:
        """Index tokens for one value of one field."""
        if fm.type is FieldType.TEXT:
            return get_tokenizer(fm.tokenizer)(value)
        # non-text indexed fields index their canonical string form as a raw term
        from ..query.tokenizers import Token
        return [Token(canonical_term(fm, value), 0)]

    def tags(self, tdoc: TypedDoc) -> set[str]:
        """`tag_field:value` strings recorded in split metadata for pruning
        (reference: `tag_pruning.rs`)."""
        out: set[str] = set()
        for tag_field in self.tag_fields:
            for v in tdoc.fields.get(tag_field, []):
                out.add(f"{tag_field}:{v}")
        return out

    # ------------------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return {
            "doc_mapping_uid": self.doc_mapping_uid,
            "field_mappings": [fm.to_dict() for fm in self.field_mappings],
            "timestamp_field": self.timestamp_field,
            "tag_fields": list(self.tag_fields),
            "default_search_fields": list(self.default_search_fields),
            "store_source": self.store_source,
            "mode": self.mode,
            "store_document_size": self.store_document_size,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "DocMapper":
        return DocMapper(
            doc_mapping_uid=d.get("doc_mapping_uid", "default"),
            field_mappings=[FieldMapping.from_dict(f) for f in d.get("field_mappings", [])],
            timestamp_field=d.get("timestamp_field"),
            tag_fields=tuple(d.get("tag_fields", ())),
            default_search_fields=tuple(d.get("default_search_fields", ())),
            store_source=d.get("store_source", True),
            mode=d.get("mode", "lenient"),
            store_document_size=d.get("store_document_size", False),
        )


def canonical_term(fm: FieldMapping, value: Any) -> str:
    """Canonical index-term string for a non-text value.

    Numeric/datetime/bool/ip values are indexed under a canonical string so
    query-side Term("field","42") matches; mirrors tantivy's typed terms.
    """
    if fm.type is FieldType.BOOL:
        return "true" if value else "false"
    if fm.type in (FieldType.I64, FieldType.U64, FieldType.DATETIME, FieldType.IP):
        return str(int(value))
    if fm.type is FieldType.F64:
        return repr(float(value))
    if fm.type is FieldType.BYTES:
        import base64
        return base64.b64encode(value).decode()
    return str(value)
