"""Elastic offload worker pool: dynamic registry + passive health.

Role of the reference's lambda worker fleet bookkeeping
(`quickwit-lambda-client`): the set of leaf-search workers is *elastic* —
workers are added and removed at runtime (static config endpoints, an
autoscaler's launches, operator action) — and *unreliable* — a worker that
times out or errors must stop receiving work without any active health
checker. Health here is purely passive, derived from dispatch outcomes:

    healthy --failure--> suspect --more failures--> ejected
       ^                    |                          |
       +----- success ------+        backoff elapses   |
       +------------- (half-open probe) <--------------+

An ejected worker is excluded from placement until an exponential
re-admission backoff elapses; it then re-enters as SUSPECT (half-open):
one success restores HEALTHY and resets the backoff, one more failure
re-ejects with the backoff doubled.

The pool also keeps the per-worker inflight/cost accounting the dispatcher's
stealing + hedging decisions read, and a pool-wide rolling latency window
whose p95 sets the hedge delay.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Callable, Optional

from ..common import sync
from ..common.clock import monotonic
from ..observability.metrics import OFFLOAD_POOL_WORKERS

HEALTHY = "healthy"
SUSPECT = "suspect"
EJECTED = "ejected"

_STATES = (HEALTHY, SUSPECT, EJECTED)


class _Worker:
    __slots__ = ("worker_id", "client", "state", "consecutive_failures",
                 "eject_count", "ejected_until", "inflight", "dispatches",
                 "failures", "busy_secs")

    def __init__(self, worker_id: str, client):
        self.worker_id = worker_id
        self.client = client
        self.state = HEALTHY
        self.consecutive_failures = 0
        # how many times this worker has been ejected without an
        # intervening success — the exponent of the re-admission backoff
        self.eject_count = 0
        self.ejected_until = 0.0
        self.inflight = 0
        self.dispatches = 0
        self.failures = 0
        self.busy_secs = 0.0


class WorkerPool:
    """Thread-safe worker registry with passive health tracking.

    `clock` is injectable so the health/backoff state machine is testable
    without sleeping.
    """

    def __init__(self, suspect_after: int = 1, eject_after: int = 3,
                 readmit_backoff_secs: float = 0.5,
                 readmit_backoff_max_secs: float = 30.0,
                 latency_window: int = 128,
                 clock: Callable[[], float] = monotonic):
        if suspect_after < 1 or eject_after < suspect_after:
            raise ValueError("need 1 <= suspect_after <= eject_after")
        self.suspect_after = suspect_after
        self.eject_after = eject_after
        self.readmit_backoff_secs = float(readmit_backoff_secs)
        self.readmit_backoff_max_secs = float(readmit_backoff_max_secs)
        self._clock = clock
        self._lock = sync.lock("WorkerPool._lock")
        sync.register_shared(self, "WorkerPool")
        # qwrace planted race (mandatory self-test): with
        # QW_RACE_BREAK_POOL set, note_result mutates health state WITHOUT
        # the pool lock — racing begin_dispatch/candidates on any schedule
        # where the accesses are unordered
        self._break_unlocked = os.environ.get(
            "QW_RACE_BREAK_POOL", "").strip().lower() in ("1", "true", "yes")
        self._workers: dict[str, _Worker] = {}
        # pool-wide rolling window of successful-dispatch latencies; its
        # p95 is the hedge trigger ("this attempt is slower than 95% of
        # recent ones → launch a backup")
        self._latencies: deque[float] = deque(maxlen=latency_window)

    # --- membership -------------------------------------------------------
    def add_worker(self, worker_id: str, client) -> None:
        with self._lock:
            if worker_id in self._workers:
                raise ValueError(f"worker {worker_id!r} already registered")
            self._workers[worker_id] = _Worker(worker_id, client)
            self._refresh_gauges_locked()

    def remove_worker(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)
            self._refresh_gauges_locked()

    def __contains__(self, worker_id: str) -> bool:
        with self._lock:
            return worker_id in self._workers

    def size(self) -> int:
        with self._lock:
            return len(self._workers)

    def worker_ids(self) -> list[str]:
        """Every registered worker, any state (sorted for determinism)."""
        with self._lock:
            return sorted(self._workers)

    def client(self, worker_id: str):
        with self._lock:
            return self._workers[worker_id].client

    # --- placement candidates --------------------------------------------
    def candidates(self) -> list[str]:
        """Workers eligible for new dispatches, sorted for deterministic
        placement: healthy + suspect, plus ejected workers whose backoff
        has elapsed — those transition to SUSPECT here (half-open probe:
        the next dispatch outcome decides re-eject vs recovery)."""
        now = self._clock()
        with self._lock:
            sync.note_write(self, "workers")
            eligible = []
            for worker in self._workers.values():
                if worker.state == EJECTED:
                    if now < worker.ejected_until:
                        continue
                    worker.state = SUSPECT
                    # one more failure re-ejects immediately
                    worker.consecutive_failures = self.eject_after - 1
                eligible.append(worker.worker_id)
            self._refresh_gauges_locked()
            return sorted(eligible)

    # --- dispatch accounting ---------------------------------------------
    def begin_dispatch(self, worker_id: str) -> None:
        with self._lock:
            sync.note_write(self, "workers")
            worker = self._workers.get(worker_id)
            if worker is None:
                return
            worker.inflight += 1
            worker.dispatches += 1

    def note_result(self, worker_id: str, ok: bool,
                    latency_secs: Optional[float] = None) -> None:
        """End-of-attempt accounting: inflight release + the passive
        health transition this outcome implies."""
        if self._break_unlocked:
            self._note_result_locked(worker_id, ok, latency_secs)
            return
        with self._lock:
            self._note_result_locked(worker_id, ok, latency_secs)

    def _note_result_locked(self, worker_id: str, ok: bool,
                            latency_secs: Optional[float]) -> None:
        sync.note_write(self, "workers")
        worker = self._workers.get(worker_id)
        if worker is None:
            return  # removed while the attempt was in flight
        worker.inflight = max(worker.inflight - 1, 0)
        if latency_secs is not None:
            worker.busy_secs += latency_secs
        if ok:
            worker.consecutive_failures = 0
            worker.eject_count = 0
            worker.state = HEALTHY
            if latency_secs is not None:
                self._latencies.append(latency_secs)
        else:
            worker.failures += 1
            worker.consecutive_failures += 1
            if worker.consecutive_failures >= self.eject_after:
                worker.state = EJECTED
                backoff = min(
                    self.readmit_backoff_secs * (2 ** worker.eject_count),
                    self.readmit_backoff_max_secs)
                worker.ejected_until = self._clock() + backoff
                worker.eject_count += 1
            elif worker.consecutive_failures >= self.suspect_after:
                worker.state = SUSPECT
        self._refresh_gauges_locked()

    def inflight(self, worker_id: str) -> int:
        with self._lock:
            sync.note_read(self, "workers")
            worker = self._workers.get(worker_id)
            return worker.inflight if worker is not None else 0

    def p95_latency(self) -> Optional[float]:
        """p95 of the rolling successful-latency window; None until enough
        samples exist to make the percentile meaningful."""
        with self._lock:
            if len(self._latencies) < 5:
                return None
            ordered = sorted(self._latencies)
            return ordered[min(int(0.95 * len(ordered)),
                               len(ordered) - 1)]

    # --- introspection ----------------------------------------------------
    def state_of(self, worker_id: str) -> Optional[str]:
        with self._lock:
            sync.note_read(self, "workers")
            worker = self._workers.get(worker_id)
            return worker.state if worker is not None else None

    def snapshot(self) -> dict:
        """Full pool state for tests / developer endpoints."""
        with self._lock:
            return {
                worker_id: {
                    "state": w.state,
                    "inflight": w.inflight,
                    "dispatches": w.dispatches,
                    "failures": w.failures,
                    "consecutive_failures": w.consecutive_failures,
                    "eject_count": w.eject_count,
                    "busy_secs": round(w.busy_secs, 6),
                }
                for worker_id, w in self._workers.items()
            }

    def _refresh_gauges_locked(self) -> None:
        counts = {state: 0 for state in _STATES}
        for worker in self._workers.values():
            counts[worker.state] += 1
        for state, count in counts.items():
            OFFLOAD_POOL_WORKERS.set(count, state=state)
