"""Elastic leaf-search offload pool.

The reference fork runs leaf search on an elastic fleet of serverless
workers (`quickwit-lambda-*`); this package is that shape for a pod-scale
deployment: a dynamic `WorkerPool` with passive health tracking, an
`OffloadDispatcher` doing rendezvous-affine placement with deadline-
budgeted retry, hedging and work stealing, and an `Autoscaler` deriving
pool size from the tenancy overload signal plus queue depth.

`search/service.py` routes the cold-split tail of oversized leaf requests
through here; with no pool configured the subsystem is never imported.
"""

from .autoscaler import Autoscaler, InProcessWorkerLauncher, WorkerLauncher
from .dispatcher import (
    OffloadDispatcher, OffloadOutcome, typed_backpressure_of,
)
from .pool import EJECTED, HEALTHY, SUSPECT, WorkerPool

__all__ = [
    "Autoscaler", "EJECTED", "HEALTHY", "InProcessWorkerLauncher",
    "OffloadDispatcher", "OffloadOutcome", "SUSPECT", "WorkerLauncher",
    "WorkerPool", "typed_backpressure_of",
]
