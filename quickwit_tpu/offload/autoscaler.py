"""Overload-driven pool autoscaling.

Role of the serverless substrate the reference leans on (AWS scales the
lambda fleet for it): derive a desired worker count from the signals this
stack already measures — the tenancy overload controller's EWMA queue-wait
severity (`tenancy/overload.py`) plus the offload queue depth — and drive a
pluggable `WorkerLauncher` to converge the pool toward it.

Scaling is asymmetric on purpose: up immediately (an overloaded pool sheds
real queries *now*), down only after a cooldown with calm signals (workers
carry warm split caches; churning them re-pays every warmup). The
autoscaler only ever terminates workers it launched itself — statically
configured endpoints are membership, not capacity.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from ..common.clock import monotonic
from ..observability.metrics import OFFLOAD_AUTOSCALE_TOTAL
from .pool import WorkerPool
from ..common import sync


class WorkerLauncher:
    """Pluggable worker substrate. `launch` returns a leaf-search client
    (anything with `.leaf_search(LeafSearchRequest)`); `terminate` releases
    whatever `launch` created. Real deployments back this with their pod /
    FaaS control plane; tests and bench use `InProcessWorkerLauncher`."""

    def launch(self, worker_id: str):  # pragma: no cover - interface
        raise NotImplementedError

    def terminate(self, worker_id: str) -> None:  # pragma: no cover
        raise NotImplementedError


class InProcessWorkerLauncher(WorkerLauncher):
    """Fake workers for tests/bench: each launch builds a full
    `SearchService` over a shared storage resolver and hands back its
    in-process client — real leaf execution, zero network."""

    def __init__(self, storage_resolver=None, service_factory=None):
        # service_factory(worker_id) -> object with .leaf_search, for tests
        # that want perturbed/instrumented workers
        self._storage_resolver = storage_resolver
        self._service_factory = service_factory
        self._services: dict[str, object] = {}

    def launch(self, worker_id: str):
        if self._service_factory is not None:
            client = self._service_factory(worker_id)
        else:
            from ..search.service import (
                LocalSearchClient, SearcherContext, SearchService,
            )
            client = LocalSearchClient(SearchService(
                SearcherContext(self._storage_resolver, prefetch=False),
                node_id=worker_id))
        self._services[worker_id] = client
        return client

    def terminate(self, worker_id: str) -> None:
        self._services.pop(worker_id, None)

    def live_workers(self) -> list[str]:
        return sorted(self._services)


class Autoscaler:
    """Converges pool size toward the overload/queue-depth demand signal.

    `tick(queue_depth)` is called by the dispatcher at dispatch entry (and
    by tests/bench directly); it is cheap and idempotent when the pool is
    already at the desired size.
    """

    def __init__(self, pool: WorkerPool, launcher: WorkerLauncher,
                 min_workers: int = 1, max_workers: int = 8,
                 queue_per_worker: int = 16,
                 scale_down_cooldown_secs: float = 10.0,
                 overload=None,
                 clock: Callable[[], float] = monotonic):
        if min_workers < 0 or max_workers < max(min_workers, 1):
            raise ValueError("need 0 <= min_workers <= max_workers, "
                             "max_workers >= 1")
        self.pool = pool
        self.launcher = launcher
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.queue_per_worker = max(int(queue_per_worker), 1)
        self.scale_down_cooldown_secs = float(scale_down_cooldown_secs)
        if overload is None:
            from ..tenancy.overload import OVERLOAD
            overload = OVERLOAD
        self.overload = overload
        self._clock = clock
        self._lock = sync.lock("Autoscaler._lock")
        self._counter = 0
        self._managed: set[str] = set()
        self._last_scale_up = 0.0

    def desired_size(self, queue_depth: int) -> int:
        """Demand = workers needed to keep per-worker queues at
        `queue_per_worker`, pushed further up by overload severity: when
        the node is shedding (severity > 1), queue depth alone understates
        demand — rejected queries never reach the queue."""
        current = self.pool.size()
        demand = math.ceil(max(queue_depth, 0) / self.queue_per_worker)
        severity = self.overload.severity()
        if severity > 1.0:
            demand = max(demand, current + math.ceil(severity - 1.0))
        return min(self.max_workers, max(self.min_workers, demand))

    def tick(self, queue_depth: int) -> int:
        """One reconcile step; returns the pool size after it."""
        with self._lock:
            desired = self.desired_size(queue_depth)
            current = self.pool.size()
            if desired > current:
                for _ in range(desired - current):
                    self._counter += 1
                    worker_id = f"auto-{self._counter}"
                    self.pool.add_worker(worker_id,
                                         self.launcher.launch(worker_id))
                    self._managed.add(worker_id)
                self._last_scale_up = self._clock()
                OFFLOAD_AUTOSCALE_TOTAL.inc(desired - current,
                                            direction="up")
            elif desired < current:
                calm = (self.overload.severity() <= 1.0
                        and (self._clock() - self._last_scale_up
                             >= self.scale_down_cooldown_secs))
                if calm:
                    removed = self._pick_removals(current - desired)
                    for worker_id in removed:
                        self.pool.remove_worker(worker_id)
                        self.launcher.terminate(worker_id)
                        self._managed.discard(worker_id)
                    if removed:
                        OFFLOAD_AUTOSCALE_TOTAL.inc(len(removed),
                                                    direction="down")
            return self.pool.size()

    def _pick_removals(self, count: int) -> list[str]:
        """Shrink managed workers only, sickest first (ejected, then
        suspect, then idle healthy) — never a worker with inflight work."""
        rank = {"ejected": 0, "suspect": 1, "healthy": 2}
        snapshot = self.pool.snapshot()
        candidates = sorted(
            (worker_id for worker_id in self._managed
             if worker_id in snapshot
             and snapshot[worker_id]["inflight"] == 0),
            key=lambda w: (rank.get(snapshot[w]["state"], 3),
                           -snapshot[w]["failures"], w))
        return candidates[:count]
