"""Offload dispatcher: rendezvous-affine placement with deadline-budgeted
retry, hedged dispatch, and work stealing.

Role of the reference's lambda invoker + `ClusterClient` retry policy
(`quickwit-lambda-client/src/invoker.rs`, `cluster_client.rs`): fan a batch
of offloaded splits across the worker pool and get every split answered
exactly once, inside the query deadline, despite slow and dying workers.

Placement is the existing rendezvous placer (`search/placer.py`,
`nodes_for_split`): each split's task goes to its top-ranked *candidate*
worker, so the same split lands on the same worker across queries (device/
reader cache affinity) and one membership change moves only ~1/n of the
splits. Placement is deliberately pure affinity — no static cost spill —
because load balance is done *dynamically* here instead: an idle worker
steals queued tasks from the longest queue, which rebalances exactly when
imbalance is real rather than predicted.

Recovery ladder, all deadline-budgeted:

- retry: a failed task re-enqueues on the next rendezvous-ranked worker
  that has not tried it yet;
- hedge: a task in flight longer than the pool's rolling p95 latency gets
  a duplicate attempt on another worker — first response wins, the loser
  is discarded (first-writer-wins at the result board);
- steal: tasks still *queued* on a busy worker move to an idle one.

Typed backpressure (`OverloadShed` / `TenantRateLimited`, or a remote
HTTP 429 carrying the same semantics) is never retried and never falls
back to local execution: it re-raises out of `dispatch` so the query fails
as a whole-query 429 — a worker's rate limits must bind the root too.
"""

from __future__ import annotations

import json
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..common import sync
from ..common.clock import monotonic
from ..common.ctx import run_with_context
from ..common.deadline import Deadline, current_deadline
from ..observability import flight
from ..observability.metrics import (
    OFFLOAD_DISPATCHES_TOTAL, OFFLOAD_DISPATCH_SECONDS, OFFLOAD_HEDGES_TOTAL,
    OFFLOAD_QUEUE_DEPTH, OFFLOAD_RETRIES_TOTAL, OFFLOAD_SPLITS_TOTAL,
    OFFLOAD_STEALS_TOTAL,
)
from ..observability.tracing import TRACER
from ..search.models import (
    LeafSearchRequest, LeafSearchResponse, SplitIdAndFooter,
)
from ..search.placer import nodes_for_split
from ..tenancy.overload import OverloadShed
from ..tenancy.registry import TenantRateLimited


def typed_backpressure_of(exc: BaseException) -> Optional[Exception]:
    """Classify a worker failure as typed backpressure (to re-raise) or
    None (a generic failure: retry / steal / fall back locally).

    In-process workers raise the real `OverloadShed`/`TenantRateLimited`;
    HTTP workers answer 429 with the ES-style body `serve/rest.py`'s
    `_throttle_error` writes — reconstruct the typed exception from it so
    the root's 429 + Retry-After contract survives the extra hop."""
    if isinstance(exc, (OverloadShed, TenantRateLimited)):
        return exc
    status = getattr(exc, "status", None)
    if status != 429:
        return None
    retry_after = 1.0
    kind = "overloaded"
    try:
        payload = json.loads(getattr(exc, "body", b"") or b"{}")
        kind = payload.get("error", {}).get("type", kind)
    except (ValueError, AttributeError):
        pass
    if kind == "rate_limit_exceeded":
        return TenantRateLimited(tenant_id="offload-worker", limit="remote",
                                 retry_after_secs=retry_after)
    return OverloadShed("offload_worker", retry_after_secs=retry_after)


@dataclass
class OffloadOutcome:
    """What `dispatch` could and could not get served remotely.

    `responses` are per-task worker responses (already deduplicated:
    exactly one per completed task). `unserved` splits belong to the
    caller again — the service runs them on the local path."""
    responses: list[LeafSearchResponse] = field(default_factory=list)
    unserved: list[SplitIdAndFooter] = field(default_factory=list)
    stats: dict[str, int] = field(default_factory=dict)


class _Task:
    """One dispatch unit: a chunk of splits bound for one worker, with the
    rendezvous preference order its retries walk."""

    __slots__ = ("splits", "preference", "tried", "attempts_inflight",
                 "first_dispatch_at", "hedged", "done", "response",
                 "winner_kind", "failed")

    def __init__(self, splits: list[SplitIdAndFooter],
                 preference: list[str]):
        self.splits = splits
        self.preference = preference
        self.tried: set[str] = set()
        self.attempts_inflight = 0
        self.first_dispatch_at: Optional[float] = None
        self.hedged = False
        self.done = False
        self.response: Optional[LeafSearchResponse] = None
        self.winner_kind: Optional[str] = None
        self.failed = False


class OffloadDispatcher:
    """Schedules one query's offloaded splits over the worker pool.

    The dispatcher is long-lived (per SearcherContext) and stateless
    across calls except for the pool it reads; each `dispatch` call runs
    its own little scheduler loop over per-worker FIFO queues.
    """

    def __init__(self, pool, task_splits: int = 8,
                 max_inflight_per_worker: int = 1,
                 hedge_min_delay_secs: float = 0.05,
                 hedge_max_delay_secs: float = 5.0,
                 min_attempt_budget_secs: float = 0.02,
                 injector=None, autoscaler=None,
                 clock: Callable[[], float] = monotonic):
        self.pool = pool
        self.task_splits = max(int(task_splits), 1)
        self.max_inflight_per_worker = max(int(max_inflight_per_worker), 1)
        self.hedge_min_delay_secs = float(hedge_min_delay_secs)
        self.hedge_max_delay_secs = float(hedge_max_delay_secs)
        self.min_attempt_budget_secs = float(min_attempt_budget_secs)
        # chaos hook: FaultInjector perturbing `offload.dispatch@<worker>`
        # before every worker RPC (common/faults.py determinism contract)
        self.injector = injector
        self.autoscaler = autoscaler
        self._clock = clock

    # --- placement --------------------------------------------------------
    def plan_tasks(self, splits: list[SplitIdAndFooter],
                   workers: list[str]) -> dict[str, list[_Task]]:
        """Rendezvous-affine assignment: each split's primary worker is its
        top-ranked candidate; each worker's run is chunked into tasks of
        `task_splits` so stealing/hedging operate on bounded units.
        Deterministic given (splits, workers) — the property test pins
        both determinism and the ~1/n reassignment bound."""
        by_worker: dict[str, list[SplitIdAndFooter]] = {}
        for split in splits:
            primary = nodes_for_split(split.split_id, workers)[0]
            by_worker.setdefault(primary, []).append(split)
        queues: dict[str, list[_Task]] = {}
        for worker_id, run in by_worker.items():
            for start in range(0, len(run), self.task_splits):
                chunk = run[start:start + self.task_splits]
                preference = nodes_for_split(chunk[0].split_id, workers)
                queues.setdefault(worker_id, []).append(
                    _Task(chunk, preference))
        return queues

    # --- the scheduler ----------------------------------------------------
    def dispatch(self, request: LeafSearchRequest,
                 deadline: Optional[Deadline] = None,
                 traceparent: Optional[str] = None) -> OffloadOutcome:
        """Run `request.splits` over the pool; returns served responses +
        the splits the caller must run locally. Raises typed backpressure
        (`OverloadShed` / `TenantRateLimited`) without retrying it."""
        deadline = deadline or current_deadline() or Deadline.never()
        if self.autoscaler is not None:
            self.autoscaler.tick(queue_depth=len(request.splits))
        workers = self.pool.candidates()
        if not workers:
            OFFLOAD_SPLITS_TOTAL.inc(len(request.splits),
                                     outcome="fallback_local")
            return OffloadOutcome(unserved=list(request.splits),
                                  stats={"no_workers": 1})

        # per-call condition: the result board's only synchronization.
        # Deliberately NOT a `*_lock`-named lock: the bridge reports it as
        # anonymous (QW007's static graph never claims to see per-call
        # primitives)
        cv = sync.condition(name="offload_cv")
        queues: dict[str, deque[_Task]] = {
            worker_id: deque(tasks) for worker_id, tasks
            in self.plan_tasks(request.splits, workers).items()}
        tasks: list[_Task] = [t for q in queues.values() for t in q]
        state: dict[str, Any] = {
            "backpressure": None, "sealed": False,
            "stats": {"retries": 0, "hedges": 0, "hedges_won": 0,
                      "steals": 0, "tasks_failed": 0}}
        OFFLOAD_QUEUE_DEPTH.set(len(request.splits))

        def _sub_request(task: _Task) -> LeafSearchRequest:
            # remaining budget re-serialized at ATTEMPT time: queue time on
            # this node is not silently re-granted to the worker
            return LeafSearchRequest(
                search_request=request.search_request,
                index_uid=request.index_uid,
                doc_mapping=request.doc_mapping,
                splits=task.splits,
                deadline_millis=deadline.timeout_millis(),
                tenant=request.tenant,
                sort_value_threshold=request.sort_value_threshold)

        def _attempt(task: _Task, worker_id: str, kind: str) -> None:
            t0 = self._clock()
            error: Optional[BaseException] = None
            response = None
            try:
                if self.injector is not None:
                    self.injector.perturb(f"offload.dispatch@{worker_id}")
                with TRACER.span("offload_dispatch",
                                 {"worker": worker_id, "kind": kind,
                                  "num_splits": len(task.splits)},
                                 remote_parent=traceparent):
                    response = self.pool.client(worker_id).leaf_search(
                        _sub_request(task))
            # qwlint: disable-next-line=QW004 - every failure is classified
            # below: typed backpressure re-raises out of dispatch(), the
            # rest drive the retry/steal/fallback ladder — nothing is
            # swallowed
            except Exception as exc:  # noqa: BLE001 - classified below
                error = exc
            latency = self._clock() - t0
            self.pool.note_result(worker_id, ok=error is None,
                                  latency_secs=latency)
            if flight.recording():
                flight.emit("offload.dispatch",
                            attrs={"worker": worker_id, "kind": kind,
                                   "ok": int(error is None),
                                   "splits": len(task.splits),
                                   "dur_ms": round(latency * 1000.0, 3)})
            with cv:
                task.attempts_inflight -= 1
                if error is None:
                    if task.done or state["sealed"]:
                        # hedge/steal race lost (or the query moved on):
                        # first writer already owns this task's splits
                        OFFLOAD_DISPATCHES_TOTAL.inc(outcome="discarded")
                        if kind == "hedge":
                            OFFLOAD_HEDGES_TOTAL.inc(outcome="lost")
                    else:
                        task.done = True
                        task.response = response
                        task.winner_kind = kind
                        OFFLOAD_DISPATCHES_TOTAL.inc(outcome="ok")
                        OFFLOAD_DISPATCH_SECONDS.observe(latency)
                        if kind == "hedge":
                            state["stats"]["hedges_won"] += 1
                            OFFLOAD_HEDGES_TOTAL.inc(outcome="won")
                    cv.notify_all()
                    return
                typed = typed_backpressure_of(error)
                if typed is not None:
                    OFFLOAD_DISPATCHES_TOTAL.inc(outcome="backpressure")
                    if state["backpressure"] is None:
                        state["backpressure"] = typed
                    cv.notify_all()
                    return
                OFFLOAD_DISPATCHES_TOTAL.inc(outcome="error")
                if task.done or state["sealed"]:
                    cv.notify_all()
                    return
                # deadline-budgeted retry on the next-ranked worker that
                # has not seen this task (and is still placeable)
                live = set(self.pool.candidates())
                next_worker = next(
                    (w for w in task.preference
                     if w not in task.tried and w in live), None)
                if (next_worker is not None and not deadline.expired
                        and (deadline.remaining()
                             > self.min_attempt_budget_secs)):
                    state["stats"]["retries"] += 1
                    OFFLOAD_RETRIES_TOTAL.inc()
                    queues.setdefault(next_worker,
                                      deque()).append(task)
                elif task.attempts_inflight == 0:
                    task.failed = True
                    state["stats"]["tasks_failed"] += 1
                cv.notify_all()

        def _launch(task: _Task, worker_id: str, kind: str) -> None:
            # cv is held here; pool + thread start are safe under it (the
            # pool never takes cv, lock order is always cv -> pool)
            task.tried.add(worker_id)
            task.attempts_inflight += 1
            if task.first_dispatch_at is None:
                task.first_dispatch_at = self._clock()
            self.pool.begin_dispatch(worker_id)
            sync.thread(
                target=run_with_context(_attempt),
                args=(task, worker_id, kind),
                name=f"offload-{worker_id}", daemon=True).start()

        def _hedge_delay() -> float:
            p95 = self.pool.p95_latency()
            if p95 is None:
                return self.hedge_min_delay_secs
            return min(max(p95, self.hedge_min_delay_secs),
                       self.hedge_max_delay_secs)

        with cv:
            while True:
                if state["backpressure"] is not None:
                    break
                if all(t.done or t.failed for t in tasks):
                    break
                if deadline.expired:
                    break
                live = self.pool.candidates()
                # 1) start queued work, FIFO per worker, bounded inflight
                for worker_id in live:
                    queue = queues.get(worker_id)
                    while (queue
                           and (self.pool.inflight(worker_id)
                                < self.max_inflight_per_worker)):
                        task = queue.popleft()
                        if task.done or task.failed:
                            continue
                        _launch(task, worker_id,
                                "retry" if task.tried else "primary")
                # 2) work stealing: an idle worker drains the tail of the
                # longest queue — affinity yields to liveness only when a
                # queue actually lags
                if (not deadline.expired and deadline.remaining()
                        > self.min_attempt_budget_secs):
                    for worker_id in live:
                        if (self.pool.inflight(worker_id) > 0
                                or queues.get(worker_id)):
                            continue
                        donor = max(
                            (w for w in queues
                             if w != worker_id and queues[w]),
                            key=lambda w: len(queues[w]), default=None)
                        if donor is None:
                            continue
                        task = queues[donor].pop()
                        if task.done or task.failed:
                            continue
                        state["stats"]["steals"] += 1
                        OFFLOAD_STEALS_TOTAL.inc()
                        _launch(task, worker_id, "steal")
                # 3) hedging: duplicate in-flight stragglers once
                hedge_delay = _hedge_delay()
                now = self._clock()
                for task in tasks:
                    if (task.done or task.failed or task.hedged
                            or task.attempts_inflight == 0
                            or task.first_dispatch_at is None
                            or now - task.first_dispatch_at < hedge_delay):
                        continue
                    if deadline.remaining() <= self.min_attempt_budget_secs:
                        continue
                    backup = next(
                        (w for w in task.preference
                         if w not in task.tried and w in live
                         and self.pool.inflight(w)
                         < self.max_inflight_per_worker), None)
                    if backup is None:
                        continue
                    task.hedged = True
                    state["stats"]["hedges"] += 1
                    OFFLOAD_HEDGES_TOTAL.inc(outcome="launched")
                    _launch(task, backup, "hedge")
                cv.wait(timeout=0.01)
            state["sealed"] = True
            backpressure = state["backpressure"]
            responses = [t.response for t in tasks
                         if t.done and t.response is not None]
            unserved = [s for t in tasks if not t.done for s in t.splits]
            stats = dict(state["stats"])
        OFFLOAD_QUEUE_DEPTH.set(0)
        served = sum(len(t.splits) for t in tasks if t.done)
        if served:
            OFFLOAD_SPLITS_TOTAL.inc(served, outcome="remote")
        if backpressure is not None:
            raise backpressure
        if unserved:
            OFFLOAD_SPLITS_TOTAL.inc(len(unserved), outcome="fallback_local")
        return OffloadOutcome(responses=responses, unserved=unserved,
                              stats=stats)
