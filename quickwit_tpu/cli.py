"""Command-line interface.

Role of the reference's `quickwit-cli` (`cli.rs:56`):

  quickwit-tpu run [--config FILE]                      start a node
  quickwit-tpu index create --index-config FILE
  quickwit-tpu index list | describe | delete --index ID
  quickwit-tpu index ingest --index ID [--input-path F] [ndjson on stdin]
  quickwit-tpu index search --index ID --query Q [--max-hits N] [--aggs JSON]
  quickwit-tpu index merge --index ID                   one merge pass
  quickwit-tpu source create --index ID --source-config FILE
  quickwit-tpu source list | delete | enable | disable --index ID [--source S]
  quickwit-tpu split list | describe | mark-for-deletion --index ID
  quickwit-tpu tool gc | retention                      janitor passes
  quickwit-tpu tool extract-split --index ID --split ID --output-dir D

Commands other than `run` execute against a running node's REST API when
`--endpoint` is given, or an embedded node otherwise (reference: CLI's
local/remote duality).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Optional

from .common.uri import Protocol
from .config import load_index_config, load_node_config
from .serve.node import Node, NodeConfig
from .storage.base import StorageResolver
from .storage.local import LocalFileStorage
from .storage.ram import RamStorage


def _resolver() -> StorageResolver:
    # file + ram + env-configured S3 (hedged), one shared registry
    return StorageResolver.default()


def _embedded_node(args) -> Node:
    config = load_node_config(getattr(args, "config", None))
    return Node(config, storage_resolver=_resolver())


def cmd_run(args) -> int:
    from .serve.rest import RestServer
    config = load_node_config(args.config)
    node = Node(config, storage_resolver=_resolver())
    server = RestServer(node)
    server.start()
    node.start_background_services()
    print(f"node {config.node_id} (roles: {','.join(config.roles)}) "
          f"listening on "
          f"{'https' if config.tls_enabled else 'http'}://{server.endpoint}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop_background_services()
        server.stop()
    return 0


def cmd_index_create(args) -> int:
    node = _embedded_node(args)
    index_config = load_index_config(args.index_config)
    metadata = node.index_service.create_index(index_config)
    print(json.dumps(metadata.to_dict(), indent=2))
    return 0


def cmd_index_list(args) -> int:
    node = _embedded_node(args)
    for metadata in node.metastore.list_indexes():
        print(metadata.index_id)
    return 0


def cmd_index_describe(args) -> int:
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    from .metastore.base import ListSplitsQuery
    splits = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[metadata.index_uid]))
    from .models.split_metadata import SplitState
    published = [s for s in splits if s.state is SplitState.PUBLISHED]
    print(json.dumps({
        "index": metadata.to_dict(),
        "num_splits": len(published),
        "num_docs": sum(s.metadata.num_docs for s in published),
        "splits_by_state": {
            state.value: sum(1 for s in splits if s.state is state)
            for state in SplitState
            if any(s.state is state for s in splits)
        },
    }, indent=2))
    return 0


def cmd_index_delete(args) -> int:
    node = _embedded_node(args)
    removed = node.index_service.delete_index(args.index)
    print(f"deleted index {args.index} ({len(removed)} split files removed)")
    return 0


def cmd_index_ingest(args) -> int:
    node = _embedded_node(args)
    if args.input_path:
        stream = open(args.input_path, "rb")
    else:
        stream = sys.stdin.buffer
    docs = []
    total = {"num_docs_for_processing": 0, "num_ingested_docs": 0,
             "num_invalid_docs": 0}
    def flush():
        if not docs:
            return
        result = node.ingest(args.index, docs, commit="force")
        for key in total:
            total[key] += result[key]
        docs.clear()
    for line in stream:
        line = line.strip()
        if line:
            docs.append(json.loads(line))
        if len(docs) >= args.batch_size:
            flush()
    flush()
    if args.input_path:
        stream.close()
    print(json.dumps(total))
    return 0


def cmd_index_search(args) -> int:
    from .query.parser import parse_query_string
    from .search.models import SearchRequest, SortField
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    default_fields = metadata.index_config.doc_mapper.default_search_fields
    sort_fields: tuple[SortField, ...] = (SortField(),)
    if args.sort_by:
        field_name = args.sort_by.lstrip("-+")
        if args.sort_order is not None:
            order = args.sort_order
        else:
            order = "desc" if args.sort_by.startswith("-") else "asc"
        sort_fields = (SortField(field_name, order),)
    request = SearchRequest(
        index_ids=[args.index],
        query_ast=parse_query_string(args.query, default_fields),
        max_hits=args.max_hits,
        start_offset=args.start_offset,
        sort_fields=sort_fields,
        aggs=json.loads(args.aggs) if args.aggs else None,
        start_timestamp=args.start_timestamp,
        end_timestamp=args.end_timestamp,
    )
    response = node.root_searcher.search(request)
    print(json.dumps(response.to_dict(), indent=2, default=str))
    return 0


def cmd_index_merge(args) -> int:
    node = _embedded_node(args)
    num_ops = node.run_merges(args.index)
    print(f"executed {num_ops} merge operations")
    return 0


def cmd_source_create(args) -> int:
    from .config import load_source_config
    from .indexing.sources import parse_source_config
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    # same parse/validation path as the REST POST /sources route
    source = parse_source_config(load_source_config(args.source_config))
    node.metastore.add_source(metadata.index_uid, source)
    print(json.dumps(source.to_dict(), indent=2))
    return 0


def cmd_source_list(args) -> int:
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    print(json.dumps({"sources": [s.to_dict()
                                  for s in metadata.sources.values()]},
                     indent=2))
    return 0


def cmd_source_delete(args) -> int:
    from .ingest.router import INTERNAL_SOURCE_IDS
    if args.source in INTERNAL_SOURCE_IDS:
        print(f"error: {args.source} is a built-in source",
              file=sys.stderr)
        return 1
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    node.metastore.delete_source(metadata.index_uid, args.source)
    print(f"deleted source {args.source}")
    return 0


def cmd_source_reset_checkpoint(args) -> int:
    from .ingest.router import INTERNAL_SOURCE_IDS
    if args.source in INTERNAL_SOURCE_IDS:
        print(f"error: {args.source} is a built-in source; its "
              "checkpoint guards the ingest WAL against replay",
              file=sys.stderr)
        return 1
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    node.metastore.reset_source_checkpoint(metadata.index_uid,
                                           args.source)
    print(f"reset checkpoint of source {args.source} "
          "(the source replays from the beginning)")
    return 0


def cmd_source_toggle(args) -> int:
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    enable = args.subcommand == "enable"
    node.metastore.toggle_source(metadata.index_uid, args.source, enable)
    print(f"{'enabled' if enable else 'disabled'} source {args.source}")
    return 0


def cmd_split_describe(args) -> int:
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    from .metastore.base import ListSplitsQuery
    splits = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[metadata.index_uid]))
    for split in splits:
        if split.metadata.split_id == args.split:
            print(json.dumps(split.to_dict(), indent=2))
            return 0
    print(f"error: split {args.split} not found in {args.index}",
          file=sys.stderr)
    return 1


def cmd_split_mark_for_deletion(args) -> int:
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    split_ids = [s.strip() for s in args.splits.split(",") if s.strip()]
    if not split_ids:
        print("error: --splits parsed to no split ids", file=sys.stderr)
        return 1
    from .metastore.base import ListSplitsQuery
    known = {s.metadata.split_id for s in node.metastore.list_splits(
        ListSplitsQuery(index_uids=[metadata.index_uid]))}
    unknown = [s for s in split_ids if s not in known]
    if unknown:
        # the metastore skips unknown ids silently; the CLI must not
        # report success for splits that were never marked
        print(f"error: unknown split id(s): {', '.join(unknown)}",
              file=sys.stderr)
        return 1
    node.metastore.mark_splits_for_deletion(metadata.index_uid, split_ids)
    print(f"marked {len(split_ids)} split(s) for deletion "
          "(the janitor GC pass removes the files)")
    return 0


def cmd_split_list(args) -> int:
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    from .metastore.base import ListSplitsQuery
    splits = node.metastore.list_splits(
        ListSplitsQuery(index_uids=[metadata.index_uid]))
    print(json.dumps({"splits": [s.to_dict() for s in splits]}, indent=2))
    return 0


def cmd_tool_gc(args) -> int:
    node = _embedded_node(args)
    print(json.dumps(node.run_janitor()))
    return 0


def cmd_tool_extract_split(args) -> int:
    import os
    node = _embedded_node(args)
    metadata = node.metastore.index_metadata(args.index)
    storage = node.storage_resolver.resolve(metadata.index_config.index_uri)
    os.makedirs(args.output_dir, exist_ok=True)
    dest = os.path.join(args.output_dir, f"{args.split}.split")
    storage.copy_to_file(f"{args.split}.split", dest)
    print(f"extracted to {dest}")
    return 0


def cmd_trace_export(args) -> int:
    """Export the flight recorder as Chrome trace-event / Perfetto JSON —
    from a running node's `/api/v1/developer/trace` endpoint when
    `--endpoint` is given, else from this process's own recorder (useful
    after an in-process repro or bench run)."""
    if args.endpoint:
        import urllib.request
        base = (args.endpoint if "://" in args.endpoint
                else f"http://{args.endpoint}")
        url = base.rstrip("/") + "/api/v1/developer/trace"
        if args.limit:
            url += f"?limit={int(args.limit)}"
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            trace = json.loads(resp.read().decode("utf-8"))
    else:
        from .observability.flight import FLIGHT
        trace = FLIGHT.to_chrome_trace(limit=args.limit or None)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    events = len(trace.get("traceEvents", []))
    print(f"wrote {events} trace events to {args.out} "
          f"(load in Perfetto / chrome://tracing)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="quickwit-tpu",
        description="TPU-native distributed search engine")
    parser.add_argument("--config", help="node config yaml", default=None)
    sub = parser.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="start a node")
    run.set_defaults(func=cmd_run)

    index = sub.add_parser("index", help="index management")
    index_sub = index.add_subparsers(dest="subcommand", required=True)
    create = index_sub.add_parser("create")
    create.add_argument("--index-config", required=True)
    create.set_defaults(func=cmd_index_create)
    lst = index_sub.add_parser("list")
    lst.set_defaults(func=cmd_index_list)
    describe = index_sub.add_parser("describe")
    describe.add_argument("--index", required=True)
    describe.set_defaults(func=cmd_index_describe)
    delete = index_sub.add_parser("delete")
    delete.add_argument("--index", required=True)
    delete.set_defaults(func=cmd_index_delete)
    ingest = index_sub.add_parser("ingest")
    ingest.add_argument("--index", required=True)
    ingest.add_argument("--input-path", default=None)
    ingest.add_argument("--batch-size", type=int, default=100_000)
    ingest.set_defaults(func=cmd_index_ingest)
    search = index_sub.add_parser("search")
    search.add_argument("--index", required=True)
    search.add_argument("--query", required=True)
    search.add_argument("--max-hits", type=int, default=20)
    search.add_argument("--start-offset", type=int, default=0)
    # `--sort-by=-field` for descending (leading dash needs the `=` form,
    # or use --sort-order)
    search.add_argument("--sort-by", default=None)
    search.add_argument("--sort-order", choices=("asc", "desc"), default=None)
    search.add_argument("--aggs", default=None)
    search.add_argument("--start-timestamp", type=int, default=None)
    search.add_argument("--end-timestamp", type=int, default=None)
    search.set_defaults(func=cmd_index_search)
    merge = index_sub.add_parser("merge")
    merge.add_argument("--index", required=True)
    merge.set_defaults(func=cmd_index_merge)

    source = sub.add_parser("source", help="source management")
    source_sub = source.add_subparsers(dest="subcommand", required=True)
    source_create = source_sub.add_parser("create")
    source_create.add_argument("--index", required=True)
    source_create.add_argument("--source-config", required=True)
    source_create.set_defaults(func=cmd_source_create)
    source_list = source_sub.add_parser("list")
    source_list.add_argument("--index", required=True)
    source_list.set_defaults(func=cmd_source_list)
    source_delete = source_sub.add_parser("delete")
    source_delete.add_argument("--index", required=True)
    source_delete.add_argument("--source", required=True)
    source_delete.set_defaults(func=cmd_source_delete)
    for toggle_name in ("enable", "disable"):
        toggle = source_sub.add_parser(toggle_name)
        toggle.add_argument("--index", required=True)
        toggle.add_argument("--source", required=True)
        toggle.set_defaults(func=cmd_source_toggle)
    reset = source_sub.add_parser("reset-checkpoint")
    reset.add_argument("--index", required=True)
    reset.add_argument("--source", required=True)
    reset.set_defaults(func=cmd_source_reset_checkpoint)

    split = sub.add_parser("split", help="split management")
    split_sub = split.add_subparsers(dest="subcommand", required=True)
    split_list = split_sub.add_parser("list")
    split_list.add_argument("--index", required=True)
    split_list.set_defaults(func=cmd_split_list)
    split_desc = split_sub.add_parser("describe")
    split_desc.add_argument("--index", required=True)
    split_desc.add_argument("--split", required=True)
    split_desc.set_defaults(func=cmd_split_describe)
    split_mark = split_sub.add_parser("mark-for-deletion")
    split_mark.add_argument("--index", required=True)
    split_mark.add_argument("--splits", required=True,
                            help="comma-separated split ids")
    split_mark.set_defaults(func=cmd_split_mark_for_deletion)

    trace = sub.add_parser("trace", help="flight-recorder trace export")
    trace_sub = trace.add_subparsers(dest="subcommand", required=True)
    trace_export = trace_sub.add_parser(
        "export", help="write the device timeline as Perfetto JSON")
    trace_export.add_argument("--out", required=True,
                              help="output path (e.g. trace.json)")
    trace_export.add_argument("--endpoint", default=None,
                              help="running node's REST host:port "
                                   "(default: this process's recorder)")
    trace_export.add_argument("--limit", type=int, default=0,
                              help="max events (0 = everything buffered)")
    trace_export.set_defaults(func=cmd_trace_export)

    tool = sub.add_parser("tool", help="maintenance tools")
    tool_sub = tool.add_subparsers(dest="subcommand", required=True)
    gc = tool_sub.add_parser("gc")
    gc.set_defaults(func=cmd_tool_gc)
    extract = tool_sub.add_parser("extract-split")
    extract.add_argument("--index", required=True)
    extract.add_argument("--split", required=True)
    extract.add_argument("--output-dir", required=True)
    extract.set_defaults(func=cmd_tool_extract_split)
    return parser


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.func(args)
    except BrokenPipeError:
        # output piped into a pager/head that closed early — not an error
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        return 0
    except Exception as exc:  # noqa: BLE001 - CLI surface
        print(f"error: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
