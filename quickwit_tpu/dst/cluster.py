"""SimCluster: a full in-process cluster assembled from the real components.

Every moving part is the production implementation — `Ingester` WAL shards
with chained replication, `IngestRouter`, `IndexingPipeline` drains,
`FileBackedMetastore` instances polling one shared object store,
`MergeExecutor`, `RootSearcher` fan-out over `SearchService` leaves,
`IndexingScheduler` planning, the offload `WorkerPool` + `Autoscaler` —
only the seams are simulated: the network (`SimNetwork`), time (the
process `FakeClock` the harness installs), randomness (the seeded process
rng), and faults (the run's `FaultInjector`).

Node liveness is modeled, not threaded: a killed node keeps its WAL
directory (the machine's disk) but is partitioned and excluded from every
role; orphaned replica shards on survivors are promoted, and a restart
re-runs the real `Ingester` recovery over the old WAL plus a fresh
metastore cache — exactly the failover path the zero-loss invariant is
about.

The deliberate-bug switches (`break_publish`, `break_wal` — the
`QW_DST_BREAK_{PUBLISH,WAL}` env flags) inject the two classes of bug the
harness self-test must catch: checkpoint-less drains (duplicate publish)
and a replication link that silently truncates batches (loss after
failover).
"""

from __future__ import annotations

import itertools
import os
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Any, Optional

from ..common import sync
from ..common.ctx import run_with_context
from ..common.deadline import CancellationToken
from ..common.faults import FaultInjector, FaultyMetastore, FaultyStorageResolver
from ..control_plane.scheduler import IndexingScheduler, IndexingTask
from ..index import SplitReader
from ..indexing import IndexingPipeline, PipelineParams, VecSource
from ..indexing.merge import MergeExecutor, StableLogMergePolicy
from ..indexing.pipeline import split_file_path
from ..indexing.sources import IngestSource
from ..ingest import Ingester, IngestRouter
from ..ingest.ingester import ReplicationGap, shard_queue_id
from ..ingest.router import INGEST_V2_SOURCE_ID
from ..metastore import FileBackedMetastore, ListSplitsQuery
from ..metastore.base import MetastoreError
from ..metastore.checkpoint import BEGINNING, IncompatibleCheckpointDelta
from ..models import DocMapper, FieldMapping, FieldType
from ..models.index_metadata import IndexConfig, IndexMetadata, SourceConfig
from ..models.split_metadata import SplitState
from ..offload.autoscaler import Autoscaler, WorkerLauncher
from ..offload.pool import WorkerPool
from ..query.ast import MatchAll, Range, RangeBound
from ..search import SearchRequest, SortField, leaf_search_single_split
from ..search.cancel import CANCEL_REGISTRY
from ..search.root import RootSearcher
from ..search.service import LocalSearchClient, SearcherContext, SearchService
from ..storage import StorageResolver
from ..tenancy.overload import OverloadController
from .network import SimNetwork, SimSearchClient
from .scenario import Scenario

SIM_MAPPER = DocMapper(
    field_mappings=[
        FieldMapping("ts", FieldType.DATETIME, fast=True,
                     input_formats=("unix_timestamp",)),
        FieldMapping("n", FieldType.U64, fast=True),
        FieldMapping("body", FieldType.TEXT),
    ],
    timestamp_field="ts",
    default_search_fields=("body",),
)

METASTORE_POLL_SECS = 5.0  # < Scenario.step_secs: publishes surface next step
SPLIT_NUM_DOCS_TARGET = 50

# per-process namespace counter: the ram:// tree and WAL tempdir are unique
# per run; neither may ever appear in the trace
_NS_COUNTER = itertools.count()


class _StubWorkerLauncher(WorkerLauncher):
    """Autoscaler substrate for the sim: launch/terminate bookkeeping only
    (the pool-size invariant is about the controller, not the workers)."""

    def launch(self, worker_id: str):
        return object()

    def terminate(self, worker_id: str) -> None:
        pass


@dataclass
class SimNode:
    node_id: str
    wal_dir: str
    alive: bool = True
    ingester: Any = None
    router: Any = None
    metastore: Any = None
    service: Any = None
    client: Any = None
    extras: dict = field(default_factory=dict)


class SimCluster:
    def __init__(self, scenario: Scenario, injector: FaultInjector,
                 network: SimNetwork, clock,
                 break_publish: bool = False, break_wal: bool = False):
        self.scenario = scenario
        self.injector = injector
        self.network = network
        self.clock = clock
        self.break_publish = break_publish
        self.break_wal = break_wal
        self._ns = next(_NS_COUNTER)
        self._drain_seq = itertools.count()
        self._cancel_seq = itertools.count()
        self.resolver = StorageResolver.for_test()
        self.faulty_resolver = FaultyStorageResolver(self.resolver, injector)
        self.meta_storage = self.resolver.resolve(
            f"ram:///dst{self._ns}/meta")
        self.base_dir = tempfile.mkdtemp(prefix="qw-dst-")
        # acked ledger: doc `n`s whose ingest the cluster ACKNOWLEDGED
        # (persist + replication chain succeeded) — the zero-loss floor
        self.acked: dict[str, list[int]] = {i: [] for i in scenario.indexes}
        # skip-cache over the durable chain registry (metastore
        # shard_chains): queue_id -> (leader, follower) last recorded, so
        # the per-batch replicate hook only writes on chain changes
        self._chain_cache: dict[str, tuple[str, Optional[str]]] = {}

        bootstrap = FileBackedMetastore(self.meta_storage,
                                        polling_interval_secs=None)
        for index_id in scenario.indexes:
            bootstrap.create_index(IndexMetadata(
                index_uid=self._uid(index_id),
                index_config=IndexConfig(
                    index_id=index_id,
                    index_uri=self._index_uri(index_id),
                    doc_mapper=SIM_MAPPER,
                    split_num_docs_target=SPLIT_NUM_DOCS_TARGET),
                sources={INGEST_V2_SOURCE_ID: SourceConfig(
                    INGEST_V2_SOURCE_ID, "ingest")}))

        self.nodes: dict[str, SimNode] = {}
        for i in range(scenario.nodes):
            node_id = f"sim-{i}"
            self.nodes[node_id] = self._build_node(node_id)

        self.merge_policy = StableLogMergePolicy(
            merge_factor=2, max_merge_factor=4, min_level_num_docs=20)
        self.cp_scheduler = IndexingScheduler()
        self.worker_pool = WorkerPool()
        self.autoscaler = Autoscaler(
            self.worker_pool, _StubWorkerLauncher(),
            min_workers=1, max_workers=4, queue_per_worker=8,
            overload=OverloadController())

    # --- identifiers -------------------------------------------------------
    def _uid(self, index_id: str) -> str:
        return f"{index_id}:01"

    def _index_uri(self, index_id: str) -> str:
        return f"ram:///dst{self._ns}/{index_id}"

    # --- node lifecycle ----------------------------------------------------
    def _build_node(self, node_id: str) -> SimNode:
        wal_dir = os.path.join(self.base_dir, node_id)
        node = SimNode(node_id=node_id, wal_dir=wal_dir)
        replicate = (self._make_replicate(node_id)
                     if self.scenario.replication and self.scenario.nodes > 1
                     else None)
        node.ingester = Ingester(wal_dir, fsync=False,
                                 replicate_to=replicate,
                                 fault_injector=self.injector)
        node.ingester.on_truncate = self._make_on_truncate(node_id)
        node.router = IngestRouter(node.ingester, shards_per_source=1,
                                   shard_prefix=node_id)
        node.metastore = FileBackedMetastore(
            self.meta_storage, polling_interval_secs=METASTORE_POLL_SECS)
        context_kwargs: dict[str, Any] = {}
        if self.scenario.offload:
            # production-shaped fan-out in-process: a two-worker fleet per
            # node, `max_local_splits=1` + `task_splits=1` so ANY leaf
            # request beyond one cold split exercises the dispatcher's
            # spawn/steal/hedge threads against the shared cache tiers.
            # Workers are full SearchServices over the same faulty
            # resolver, reached through LocalSearchClient (deterministic:
            # no sockets, no real network)
            context_kwargs["offload"] = {
                "endpoints": [f"{node_id}-w0", f"{node_id}-w1"],
                "max_local_splits": 1,
                "task_splits": 1,
                "max_inflight_per_worker": 2,
            }
            context_kwargs["offload_client_factory"] = (
                lambda endpoint: LocalSearchClient(SearchService(
                    SearcherContext(self.faulty_resolver, prefetch=False),
                    node_id=endpoint)))
        node.service = SearchService(
            SearcherContext(self.faulty_resolver, prefetch=False,
                            **context_kwargs),
            node_id=node_id)
        node.client = LocalSearchClient(node.service)
        return node

    def _make_replicate(self, leader_id: str):
        def replicate(index_uid: str, source_id: str, shard_id: str,
                      first: int, payloads: list[bytes]) -> None:
            follower_id = self._follower_for(leader_id)
            if follower_id is None:
                # the replication chain cannot be completed: NACK rather
                # than ack a leader-only write a later kill would lose —
                # reference semantics: persist fails when no follower is
                # available, clients retry against a healthy chain
                raise ConnectionError("simnet: no replica available")
            if self.network.is_partitioned(follower_id):
                raise ConnectionError(
                    f"simnet: replica {follower_id} unreachable")
            queue_id = shard_queue_id(index_uid, source_id, shard_id)
            if self._chain_cache.get(queue_id) != (leader_id, follower_id):
                # durable registration BEFORE the first batch reaches a
                # new follower: failover may only promote the REGISTERED
                # follower, so the record must exist before that follower
                # can hold acked data (qwmc's stale-replica-promotion
                # counterexample is exactly an unregistered-copy takeover)
                self.nodes[leader_id].metastore.record_shard_chain(
                    index_uid, source_id, shard_id,
                    leader=leader_id, follower=follower_id)
                self._chain_cache[queue_id] = (leader_id, follower_id)
            if self.break_wal:
                # QW_DST_BREAK_WAL: the link silently truncates each batch
                # — the acked tail exists only on the leader, so a leader
                # kill + replica promotion loses it (zero-loss violation)
                payloads = payloads[:-1]
            follower = self.nodes[follower_id].ingester
            try:
                follower.replica_persist(index_uid, source_id, shard_id,
                                         first, payloads)
            except ReplicationGap as gap:
                if self.break_wal:
                    return  # the buggy link also swallows gap reports
                leader_shard = self.nodes[leader_id].ingester.shard(
                    index_uid, source_id, shard_id)
                records = leader_shard.log.read_from(gap.have, 1_000_000)
                if not records:
                    return
                start = records[0][0]
                if start > gap.have:
                    # the leader's retained WAL starts past the follower's
                    # position (truncated behind the published checkpoint):
                    # restart the replica log at what the leader still
                    # holds — the checkpoint covers everything below
                    # (serve/node.py's reset= backfill path)
                    follower.replica_reset(index_uid, source_id, shard_id,
                                           start)
                follower.replica_persist(index_uid, source_id, shard_id,
                                         start,
                                         [payload for _, payload in records])
        return replicate

    def _make_on_truncate(self, leader_id: str):
        def on_truncate(index_uid: str, source_id: str, shard_id: str,
                        position: int) -> None:
            for node_id in self.alive_nodes():
                if node_id != leader_id:
                    self.nodes[node_id].ingester.replica_truncate(
                        index_uid, source_id, shard_id, position)
        return on_truncate

    def _follower_for(self, leader_id: str) -> Optional[str]:
        for node_id in self.alive_nodes():
            if node_id != leader_id:
                return node_id
        return None

    def alive_nodes(self) -> list[str]:
        return sorted(n for n, node in self.nodes.items() if node.alive)

    def kill(self, node_id: str) -> dict[str, Any]:
        """Crash the node: partitioned and excluded from every role, but its
        WAL directory (the machine's disk) survives — a later restart runs
        real recovery over it. Kills are crashes, not machine loss, so the
        zero-loss ledger invariant is checkable under any kill sequence."""
        node = self.nodes[node_id]
        if not node.alive:
            return {"skipped": "already-dead"}
        node.alive = False
        self.network.partition(node_id)
        if self.break_wal:
            self._drop_unfsynced_tail(node)
        return {"killed": node_id,
                "promoted": self.promote_orphans()}

    def _drop_unfsynced_tail(self, node: SimNode) -> None:
        """QW_DST_BREAK_WAL, crash half: the last acked record of each
        leader shard was never durably fsynced, so the crash loses it —
        rewrite the on-disk WAL without its tail record (positions
        preserved). Combined with the truncating replication link, the
        acked tail then exists on no surviving copy."""
        for shard in node.ingester.list_shards(include_replicas=False):
            records = shard.log.read_from(0, 1_000_000)
            if not records:
                continue
            first = records[0][0]
            shard.log.reset_to(first)
            if len(records) > 1:
                shard.log.append_batch(
                    [payload for _, payload in records[:-1]])

    def restart(self, node_id: str) -> dict[str, Any]:
        node = self.nodes[node_id]
        if node.alive:
            return {"skipped": "already-alive"}
        # real recovery: a fresh Ingester re-reads the old WAL directory,
        # a fresh metastore instance starts cold (must re-poll state)
        self.nodes[node_id] = self._build_node(node_id)
        self.network.heal(node_id)
        demoted = self._reconcile_rejoined(node_id)
        shards = sorted(
            s.shard_id
            for s in self.nodes[node_id].ingester.list_shards(
                include_replicas=True))
        result = {"restarted": node_id, "recovered_shards": shards}
        if demoted:
            result["demoted"] = demoted
        return result

    def _reconcile_rejoined(self, node_id: str) -> list[str]:
        """A rejoined node recovers its shards with the role they had when
        it crashed — a stale LEADER role when another copy was promoted
        meanwhile (qwmc's stale-leader-rejoin counterexample: the
        split-brain re-uses published positions and loses an acked
        record). The durable chain registry is the truth: demote the local
        copy, resetting its WAL at the published checkpoint — the
        registered chain holds every acked record, so nothing is lost."""
        node = self.nodes[node_id]
        node.metastore.refresh()  # cold start must not serve a stale view
        demoted = []
        for shard in node.ingester.list_shards(include_replicas=False):
            chain = node.metastore.shard_chain(
                shard.index_uid, shard.source_id, shard.shard_id)
            if chain is None or chain.get("leader") == node_id:
                continue
            queue_id = shard_queue_id(shard.index_uid, shard.source_id,
                                      shard.shard_id)
            if node.ingester.demote_to_replica(
                    queue_id, self._published_floor(node, shard)):
                demoted.append(queue_id)
        return sorted(demoted)

    def _published_floor(self, node: SimNode, shard) -> int:
        """Published checkpoint for the shard (exclusive end position):
        everything below it is in published splits."""
        checkpoint = node.metastore.source_checkpoint(shard.index_uid,
                                                      shard.source_id)
        position = checkpoint.position_for(shard.shard_id)
        return 0 if position == BEGINNING else int(position)

    def _checkpoint_total(self, node: SimNode, uid: str) -> int:
        """Sum of the source checkpoint's partition positions (each one an
        EXCLUSIVE end = records published from that shard) — the concrete
        image of the qwmc checkpoint model's `ckpt` counter, recorded in
        drain summaries so `tools.qwmc.conformance` can replay the trace
        against the abstract transition relation."""
        checkpoint = node.metastore.source_checkpoint(uid,
                                                      INGEST_V2_SOURCE_ID)
        return sum(int(p) for p in checkpoint.positions.values()
                   if p != BEGINNING)

    def promote_orphans(self) -> list[str]:
        """Promote replica shards whose leader node is dead (the reference's
        AdviseResetShards failover) on every surviving node.

        The durable chain registry gates the takeover: the current leader
        is whoever the registry records (falling back to the shard-id
        prefix for never-replicated shards), and only the REGISTERED
        follower is eligible — a copy that merely looks healthy may have
        crashed out of the chain and be missing acked batches (qwmc's
        stale-replica-promotion counterexample). A promoted log behind the
        published checkpoint forward-resets to it, or fresh appends would
        collide with already-consumed positions (behind-checkpoint
        counterexample)."""
        alive = set(self.alive_nodes())
        promoted = []
        for node_id in self.alive_nodes():
            node = self.nodes[node_id]
            refreshed = False
            for queue_id, shard in node.ingester.replica_shards():
                if not refreshed:
                    # promotion decisions must read the registry and the
                    # checkpoint fresh, not from the polling cache
                    node.metastore.refresh()
                    refreshed = True
                chain = node.metastore.shard_chain(
                    shard.index_uid, shard.source_id, shard.shard_id)
                if chain is not None and chain.get("leader") == node_id:
                    # a crash between the registry write and the role flip
                    # left the record already naming us: finish the
                    # promotion (idempotent — the registry is the truth)
                    if node.ingester.promote_replica(
                            queue_id,
                            min_position=self._published_floor(node, shard)):
                        promoted.append(queue_id)
                    continue
                leader = (chain["leader"] if chain is not None
                          else shard.shard_id.rsplit("-shard-", 1)[0])
                if leader in alive:
                    continue
                if chain is not None and chain.get("follower") != node_id:
                    continue
                # registry BEFORE the role flip: if we crash in between,
                # the next failover round finds the record naming us and
                # finishes the flip (branch above) instead of demoting a
                # copy that holds acked data back to the checkpoint
                node.metastore.record_shard_chain(
                    shard.index_uid, shard.source_id, shard.shard_id,
                    leader=node_id, follower=None)
                self._chain_cache[queue_id] = (node_id, None)
                if node.ingester.promote_replica(
                        queue_id,
                        min_position=self._published_floor(node, shard)):
                    promoted.append(queue_id)
        return sorted(promoted)

    # --- ops ---------------------------------------------------------------
    def ingest(self, node_id: str, index_id: str,
               docs: list[dict[str, Any]]) -> dict[str, Any]:
        node = self.nodes[node_id]
        if not node.alive:
            return {"skipped": "dead"}
        try:
            result = node.router.ingest(self._uid(index_id), docs)
        except Exception as exc:  # noqa: BLE001 - any failure means NACK
            # chained replication rolled the leader WAL back: the batch is
            # durable on both or neither, so nothing joins the acked ledger
            return {"error": type(exc).__name__}
        self.acked[index_id].extend(int(d["n"]) for d in docs)
        return {"acked": result["num_docs"]}

    def drain(self, node_id: str) -> dict[str, Any]:
        node = self.nodes[node_id]
        if not node.alive:
            return {"skipped": "dead"}
        summary: dict[str, Any] = {}
        for index_id in self.scenario.indexes:
            if self.break_publish:
                summary[index_id] = self._drain_break_publish(node, index_id)
            else:
                summary[index_id] = self._drain_index(node, index_id)
        return summary

    def _drain_index(self, node: SimNode, index_id: str) -> dict[str, Any]:
        uid = self._uid(index_id)
        storage = self.resolver.resolve(self._index_uri(index_id))
        params = PipelineParams(
            index_uid=uid, source_id=INGEST_V2_SOURCE_ID,
            node_id=node.node_id,
            split_num_docs_target=SPLIT_NUM_DOCS_TARGET, batch_num_docs=25)
        counters = None
        for attempt in (0, 1):
            source = IngestSource(node.ingester, uid, INGEST_V2_SOURCE_ID)
            pipeline = IndexingPipeline(params, SIM_MAPPER, source,
                                        node.metastore, storage)
            try:
                counters = pipeline.run_to_completion()
                break
            except IncompatibleCheckpointDelta:
                # another node already published these positions (post-
                # failover double drain): exactly-once enforcement worked
                return {"skipped": "checkpoint",
                        "checkpoint": self._checkpoint_total(node, uid)}
            except MetastoreError as exc:
                if attempt or getattr(exc, "kind", "") != "failed_precondition":
                    return {"error": "metastore"}
                # stale cache lost the CAS: age it past the polling TTL so
                # the retry reloads, exactly like a node would on its next
                # poll tick
                self.clock.advance(METASTORE_POLL_SECS + 1.0)
        if counters is None:
            return {"error": "metastore"}
        checkpoint = node.metastore.source_checkpoint(uid,
                                                      INGEST_V2_SOURCE_ID)
        for shard in node.ingester.list_shards(uid):
            position = checkpoint.position_for(shard.shard_id)
            if position != BEGINNING:
                node.ingester.truncate(uid, INGEST_V2_SOURCE_ID,
                                       shard.shard_id, int(position))
        return {"indexed": counters.num_docs_processed,
                "splits": counters.num_splits_published,
                "checkpoint": self._checkpoint_total(node, uid)}

    def _drain_break_publish(self, node: SimNode,
                             index_id: str) -> dict[str, Any]:
        """QW_DST_BREAK_PUBLISH: drain the WAL from position zero with a
        fresh checkpoint partition each pass and never truncate — the
        'lost the checkpoint linkage' bug class. Every re-drain republishes
        the same records (exactly-once violation)."""
        uid = self._uid(index_id)
        storage = self.resolver.resolve(self._index_uri(index_id))
        docs: list[dict[str, Any]] = []
        for shard in node.ingester.list_shards(uid):
            for _, doc in node.ingester.fetch(uid, INGEST_V2_SOURCE_ID,
                                              shard.shard_id,
                                              from_position=0,
                                              max_records=1_000_000):
                docs.append(doc)
        if not docs:
            return {"indexed": 0, "splits": 0,
                    "checkpoint": self._checkpoint_total(node, uid)}
        params = PipelineParams(
            index_uid=uid, source_id=INGEST_V2_SOURCE_ID,
            node_id=node.node_id,
            split_num_docs_target=SPLIT_NUM_DOCS_TARGET, batch_num_docs=25)
        source = VecSource(
            docs, partition_id=f"bp-{node.node_id}-{next(self._drain_seq)}")
        pipeline = IndexingPipeline(params, SIM_MAPPER, source,
                                    node.metastore, storage)
        counters = pipeline.run_to_completion()
        # the checkpoint never advances here (fresh partition each pass):
        # exactly the divergence the conformance check is built to catch
        return {"indexed": counters.num_docs_processed,
                "splits": counters.num_splits_published,
                "checkpoint": self._checkpoint_total(node, uid)}

    def _make_root(self, alive: list[str]) -> RootSearcher:
        searcher = self.nodes[alive[0]]
        clients = {
            node_id: SimSearchClient(self.network, node_id,
                                     self.nodes[node_id].client)
            for node_id in alive
        }
        return RootSearcher(
            FaultyMetastore(searcher.metastore, self.injector), clients,
            nodes_provider=lambda: self.alive_nodes(),
            default_timeout_secs=self.scenario.search_timeout_secs)

    def search(self, index_id: str, max_hits: int,
               sort: Optional[str] = None,
               repeat: int = 2) -> list[dict[str, Any]]:
        """Run the query `repeat` times through the full root fan-out —
        the second pass hits the warm cache tiers, which is exactly what
        the cache≡cold invariant compares."""
        alive = self.alive_nodes()
        if not alive:
            return [{"error": "NoAliveNodes"}]
        root = self._make_root(alive)
        # a fast-field sort arms threshold pruning: the leaf's shared
        # ThresholdBox is then written by the local execute loop and read
        # by the offload dispatch thread — the interleaving the qwrace
        # schedule exploration targets
        request = SearchRequest(
            index_ids=[index_id], query_ast=MatchAll(), max_hits=max_hits,
            sort_fields=([SortField(sort, "desc")] if sort else []))
        outs: list[dict[str, Any]] = []
        for _ in range(repeat):
            try:
                resp = root.search(request)
            except Exception as exc:  # noqa: BLE001 - typed outcome per run
                outs.append({"error": type(exc).__name__})
                continue
            complete = (not resp.timed_out and not resp.errors
                        and not resp.failed_splits)
            outs.append({
                "ns": sorted(int(h.doc["n"]) for h in resp.hits),
                "num_hits": int(resp.num_hits),
                "complete": bool(complete),
            })
        return outs

    def cancel_search(self, index_id: str, max_hits: int) -> dict[str, Any]:
        """Execute a search whose handle was cancelled BEFORE the query
        started — the REST DELETE racing ahead of the query it targets.
        The root adopts the pre-cancelled token from the registry, so the
        cancel deterministically lands before any split executes: the
        response is typed-cancelled (when splits existed to cut short),
        carries zero hits, and the registry entry is gone afterwards —
        exactly what the cancel_responsiveness invariant audits."""
        alive = self.alive_nodes()
        if not alive:
            return {"error": "NoAliveNodes"}
        # same staleness as the root's own view: whether the query HAD
        # splits to cancel is judged through the node's polling metastore
        uid = self._uid(index_id)
        had_splits = bool(self.nodes[alive[0]].metastore.list_splits(
            ListSplitsQuery(index_uids=[uid],
                            states=[SplitState.PUBLISHED])))
        root = self._make_root(alive)
        qid = f"dst-cancel-{next(self._cancel_seq)}"
        token = CancellationToken()
        CANCEL_REGISTRY.register(qid, token)
        accepted = CANCEL_REGISTRY.cancel(qid, reason="dst cancel op")
        request = SearchRequest(index_ids=[index_id], query_ast=MatchAll(),
                                max_hits=max_hits, query_id=qid)
        try:
            resp = root.search(request)
        except Exception as exc:  # noqa: BLE001 - typed outcome per op
            return {"error": type(exc).__name__,
                    "registry_drained": CANCEL_REGISTRY.get(qid) is None}
        return {"accepted": accepted,
                "cancelled": bool(resp.cancelled),
                "num_hits": int(resp.num_hits),
                "had_splits": had_splits,
                "registry_drained": CANCEL_REGISTRY.get(qid) is None}

    def dashboard(self, index_id: str, max_hits: int, panels: int,
                  cancel_panel: bool = False) -> dict[str, Any]:
        """N concurrent shape-compatible panel searches through ONE root —
        the workload the query batcher (search/batcher.py) stacks into a
        single device dispatch. Panels share structure (Range on the "ts"
        fast field, same sort + max_hits) but carry distinct window bounds,
        so they are distinct queries under one group key. Each panel runs
        cold+warm like `search` (the cache≡cold invariant audits every
        lane); with `cancel_panel` one extra panel's handle is cancelled
        up front, so the batcher sheds it AFTER group formation — the
        masked-rider path, audited by cancel_responsiveness."""
        alive = self.alive_nodes()
        if not alive:
            return {"error": "NoAliveNodes"}
        root = self._make_root(alive)
        t0_us = 1_600_000_000 * 1_000_000

        def request_for(i: int, qid: Optional[str] = None) -> SearchRequest:
            # distinct upper bound per panel: distinct query, same shape
            window = Range(
                "ts", lower=RangeBound(t0_us, True),
                upper=RangeBound(t0_us + (i + 1) * 1_000 * 1_000_000, False))
            return SearchRequest(
                index_ids=[index_id], query_ast=window, max_hits=max_hits,
                sort_fields=[SortField("ts", "desc")], query_id=qid)

        panel_outs: list[Any] = [None] * panels

        def run_panel(i: int) -> None:
            outs: list[dict[str, Any]] = []
            for _ in range(2):
                try:
                    resp = root.search(request_for(i))
                except Exception as exc:  # noqa: BLE001 - typed outcome
                    outs.append({"error": type(exc).__name__})
                    continue
                complete = (not resp.timed_out and not resp.errors
                            and not resp.failed_splits)
                outs.append({
                    "ns": sorted(int(h.doc["n"]) for h in resp.hits),
                    "num_hits": int(resp.num_hits),
                    "complete": bool(complete),
                })
            panel_outs[i] = outs

        cancelled_out: dict[str, Any] = {}
        # same staleness as the root's own view (read before the threads
        # start, so the result is independent of panel interleaving)
        uid = self._uid(index_id)
        had_splits = bool(self.nodes[alive[0]].metastore.list_splits(
            ListSplitsQuery(index_uids=[uid],
                            states=[SplitState.PUBLISHED])))

        def run_cancelled(i: int) -> None:
            qid = f"dst-dashboard-{next(self._cancel_seq)}"
            token = CancellationToken()
            CANCEL_REGISTRY.register(qid, token)
            accepted = CANCEL_REGISTRY.cancel(qid, reason="dst dashboard shed")
            try:
                resp = root.search(request_for(i, qid=qid))
            except Exception as exc:  # noqa: BLE001 - typed outcome
                cancelled_out.update(
                    error=type(exc).__name__,
                    registry_drained=CANCEL_REGISTRY.get(qid) is None)
                return
            cancelled_out.update(
                accepted=accepted, cancelled=bool(resp.cancelled),
                num_hits=int(resp.num_hits), had_splits=had_splits,
                registry_drained=CANCEL_REGISTRY.get(qid) is None)

        threads = [sync.thread(target=run_with_context(run_panel),
                               args=(i,), name=f"dashboard-panel-{i}")
                   for i in range(panels)]
        if cancel_panel:
            threads.append(sync.thread(target=run_with_context(run_cancelled),
                                       args=(panels,),
                                       name="dashboard-shed"))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        result: dict[str, Any] = {"panels": panel_outs}
        if cancel_panel:
            result["cancelled_panel"] = cancelled_out
        return result

    def merge(self, node_id: str, index_id: str) -> dict[str, Any]:
        node = self.nodes[node_id]
        if not node.alive:
            return {"skipped": "dead"}
        uid = self._uid(index_id)
        storage = self.resolver.resolve(self._index_uri(index_id))
        query = ListSplitsQuery(index_uids=[uid],
                                states=[SplitState.PUBLISHED])
        try:
            splits = node.metastore.list_splits(query)
            docs_before = sum(s.metadata.num_docs for s in splits)
            operations = self.merge_policy.operations(splits)
            if not operations:
                return {"merged": 0}
            executor = MergeExecutor(uid, SIM_MAPPER, node.metastore, storage)
            executor.execute(operations[0])
            docs_after = sum(
                s.metadata.num_docs
                for s in node.metastore.list_splits(query))
        except Exception as exc:  # noqa: BLE001 - typed outcome per op
            return {"error": type(exc).__name__}
        return {"merged": 1, "docs_before": docs_before,
                "docs_after": docs_after}

    def autoscale(self, queue_depth: int) -> dict[str, Any]:
        size = self.autoscaler.tick(queue_depth)
        return {"pool_size": size,
                "min": self.autoscaler.min_workers,
                "max": self.autoscaler.max_workers}

    def plan(self) -> dict[str, Any]:
        tasks = [IndexingTask(self._uid(index_id), INGEST_V2_SOURCE_ID)
                 for index_id in self.scenario.indexes]
        alive = self.alive_nodes()
        physical = self.cp_scheduler.schedule(tasks, alive)
        assignment_counts: dict[str, int] = {}
        for node_id, node_tasks in sorted(physical.assignments.items()):
            for task in node_tasks:
                key = f"{task.index_uid}/{task.source_id}"
                assignment_counts[key] = assignment_counts.get(key, 0) + 1
        return {"nodes": alive, "assignments": assignment_counts,
                "num_tasks": len(tasks),
                "assigned_to_dead": sorted(
                    n for n in physical.assignments
                    if physical.assignments[n] and n not in alive)}

    # --- quiescence + oracle ------------------------------------------------
    def quiesce(self) -> dict[str, Any]:
        """Drain everything outstanding deterministically: restart every
        crashed node (disks are durable — WAL recovery is exactly what the
        zero-loss invariant audits), age past the metastore TTL, then drain
        every node (twice — a first pass may publish positions a second
        node's drain needs to observe before truncating)."""
        summary: dict[str, Any] = {
            "restarted": [node_id for node_id in sorted(self.nodes)
                          if not self.nodes[node_id].alive
                          and self.restart(node_id)],
            "promoted": self.promote_orphans()}
        for round_index in range(2):
            self.clock.advance(METASTORE_POLL_SECS * 2)
            for node_id in self.alive_nodes():
                summary[f"drain{round_index}:{node_id}"] = self.drain(node_id)
        return summary

    def searchable_ns(self, index_id: str) -> list[int]:
        """Ground truth, network-free: every doc `n` searchable across the
        index's published splits, duplicates preserved, via direct split
        reads against the shared object store."""
        uid = self._uid(index_id)
        storage = self.resolver.resolve(self._index_uri(index_id))
        metastore = FileBackedMetastore(self.meta_storage,
                                        polling_interval_secs=None)
        out: list[int] = []
        splits = metastore.list_splits(ListSplitsQuery(
            index_uids=[uid], states=[SplitState.PUBLISHED]))
        for split in splits:
            reader = SplitReader(
                storage, split_file_path(split.metadata.split_id))
            resp = leaf_search_single_split(
                SearchRequest(index_ids=[index_id], query_ast=MatchAll(),
                              max_hits=1_000_000),
                SIM_MAPPER, reader, split.metadata.split_id)
            docs = reader.fetch_docs([h.doc_id for h in resp.partial_hits])
            out.extend(int(d["n"]) for d in docs)
        return sorted(out)

    def close(self) -> None:
        shutil.rmtree(self.base_dir, ignore_errors=True)
