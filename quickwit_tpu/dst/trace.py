"""Run trace: the deterministic event log a simulation run produces.

The trace is the determinism oracle: two executions of the same
(seed, scenario, op list, fault plan) must produce byte-identical traces —
`digest()` is what the harness, the self-tests, and `dst replay` compare.
Consequently every recorded field must be a pure function of the run's
inputs: op summaries, virtual timestamps, invariant observations, fault
decisions — never wall-clock times, filesystem paths, object ids, or
anything else that varies across processes.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any


def canonical_json(value: Any) -> str:
    """Stable serialization: sorted keys, no whitespace — the byte form
    every digest in the DST layer is computed over."""
    return json.dumps(value, sort_keys=True, separators=(",", ":"))


def blake2b_digest(value: Any) -> str:
    """THE digest of the artifact/trace schema: blake2b-128 over canonical
    JSON. Every digest field in a DST replay artifact, a qwmc counterexample
    artifact, or a run trace is computed by this one function, so the two
    artifact families cannot drift apart byte-format-wise."""
    return hashlib.blake2b(canonical_json(value).encode(),
                           digest_size=16).hexdigest()


class Trace:
    def __init__(self) -> None:
        self.events: list[dict[str, Any]] = []

    def record(self, kind: str, **fields: Any) -> None:
        event = {"kind": kind}
        event.update(fields)
        # round-trip through canonical JSON now: a non-serializable or
        # non-deterministic value should fail at the recording site, not
        # at digest time three hundred events later
        self.events.append(json.loads(canonical_json(event)))

    def digest(self) -> str:
        return blake2b_digest(self.events)

    def __len__(self) -> int:
        return len(self.events)
