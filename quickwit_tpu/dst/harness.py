"""The deterministic scheduler: one run = one seeded, replayable execution.

`run_scenario` owns the whole run: it installs a `FakeClock` and a seeded
`random.Random` process-wide (every component routed through
`quickwit_tpu.common.clock` — qwlint QW006 keeps them honest), builds the
`FaultInjector` + `SimNetwork` + `SimCluster`, then executes the
materialized op list **synchronously, one op at a time** — the op order IS
the interleaving, FoundationDB-style, so a run is pinned by
(scenario, seed, op list, fault plan) and nothing else. Virtual time
advances only when the scheduler (or a latency fault) says so; scenario
hours cost milliseconds of wall clock.

`sweep` explores seeds; on a violation it `shrink`s the op list and fault
plan (greedy single-pass delta-debugging, keeping a candidate only if the
SAME invariant still fires) and persists a self-contained replay artifact.
`replay` re-executes an artifact from its own contents alone and reports
whether the trace digest matches byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import os
import random
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Any, Optional

from ..common.clock import FakeClock, use_clock, use_rng
from ..common.faults import FaultInjector
from ..observability.flight import FLIGHT
from .artifact import make_artifact, save_artifact
from .cluster import SimCluster
from .invariants import InvariantChecker, Violation
from .network import SimNetwork
from .scenario import SCENARIOS, Scenario
from .trace import Trace

# virtual start of every run: far enough from zero that monotonic deltas
# and wall timestamps are both well-behaved, and identical across runs
_VIRTUAL_START = 1000.0
_VIRTUAL_EPOCH = 1_700_000_000.0


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes")


@dataclass
class RunResult:
    scenario: Scenario
    seed: int
    ops: list[dict[str, Any]]
    violations: list[Violation]
    trace: Trace
    # the op thread's flight-recorder timeline (FLIGHT.dst_tail()): virtual
    # timestamps, no thread/span ids — byte-identical across replays of the
    # same (scenario, seed, ops, fault plan)
    flight_tail: list = dataclasses.field(default_factory=list)

    @property
    def digest(self) -> str:
        return self.trace.digest()

    @property
    def ok(self) -> bool:
        return not self.violations

    @property
    def first_violation(self) -> Optional[Violation]:
        return self.violations[0] if self.violations else None


def run_scenario(scenario: Scenario, seed: int,
                 ops: Optional[list[dict[str, Any]]] = None,
                 fault_plan: Optional[dict[str, Any]] = None,
                 break_publish: Optional[bool] = None,
                 break_wal: Optional[bool] = None,
                 race: Optional[Any] = None) -> RunResult:
    """Execute one deterministic run. `ops` / `fault_plan` default to the
    scenario's materialization and fault rules for `seed`; replay and
    shrinking pass explicit (possibly reduced) values. The break flags
    default to the `QW_DST_BREAK_{PUBLISH,WAL}` env switches; replay pins
    them from the artifact so a run reproduces from the file alone.

    `race` is a `tools.qwrace.PctRace` controller (or None): when set, the
    run executes under the gated PCT scheduler — every thread and lock the
    cluster builds goes through the `common.sync` seam, interleavings are
    explored at sync-op granularity inside each (still serial) DST op, and
    happens-before race findings become ordinary `Violation`s so shrink /
    artifact / replay apply unchanged."""
    if ops is None:
        ops = scenario.materialize(seed)
    if break_publish is None:
        break_publish = _env_flag("QW_DST_BREAK_PUBLISH")
    if break_wal is None:
        break_wal = _env_flag("QW_DST_BREAK_WAL")
    if fault_plan is not None:
        injector = FaultInjector.from_plan(fault_plan)
    else:
        injector = FaultInjector(seed, list(scenario.fault_rules))

    expected_index_of_n = {
        int(doc["n"]): op["index"]
        for op in ops if op["kind"] == "ingest" for doc in op["docs"]
    }
    checker = InvariantChecker(scenario.invariants, expected_index_of_n)
    trace = Trace()
    clock = FakeClock(start=_VIRTUAL_START, epoch=_VIRTUAL_EPOCH)
    rng = random.Random(seed)

    racer = race.begin(seed) if race is not None else None
    # () never matches in an except clause: abort_exc is only "live" when
    # a race controller is installed
    abort_exc = racer.abort_exc if racer is not None else ()

    # activate BEFORE the cluster is built: a lock constructed outside the
    # runtime would be invisible to happens-before and yield false races
    with use_clock(clock), use_rng(rng), \
            (racer.activate() if racer is not None else nullcontext()):
        # rebase the flight recorder on the just-installed FakeClock: the
        # run's timeline starts at t=0 virtual and depends on nothing but
        # the run inputs
        FLIGHT.begin_run()
        network = SimNetwork(injector, seed, duplicate_probability=0.05)
        cluster = SimCluster(scenario, injector, network, clock,
                             break_publish=break_publish,
                             break_wal=break_wal)
        try:
            start_extra = {"race": race.to_dict()} if race is not None else {}
            trace.record("start", scenario=scenario.name, seed=seed,
                         num_ops=len(ops), break_publish=break_publish,
                         break_wal=break_wal, **start_extra)
            aborted = False
            try:
                for step, op in enumerate(ops):
                    if racer is not None:
                        racer.before_op(step)
                    clock.advance(scenario.step_secs)
                    # every op marks the op thread's ring, so even an
                    # ingest/drain-only shrunk repro carries a timeline
                    FLIGHT.emit("dst.op",
                                attrs={"step": step, "kind": op["kind"]})
                    result = _execute(cluster, op)
                    trace.record("op", step=step,
                                 now=round(clock.monotonic(), 6),
                                 op=op if op["kind"] != "ingest" else {
                                     "kind": "ingest", "node": op["node"],
                                     "index": op["index"],
                                     "num_docs": len(op["docs"])},
                                 result=result)
                    checker.after_op(cluster, op, result, step)
                    if checker.violations:
                        break
                    if racer is not None and racer.detector.findings():
                        break   # stop at the first race, like any violation
            except abort_exc:
                # scheduler deadlock / budget abort: the finding is already
                # in the detector; the run ends here
                aborted = True
            if racer is not None:
                racer.finalize()
                checker.violations.extend(racer.violations())
                trace.record("race", **racer.trace_event())
            if not checker.violations and not aborted:
                summary = cluster.quiesce()
                trace.record("quiesce", now=round(clock.monotonic(), 6),
                             summary=summary)
                checker.at_quiescence(cluster, step=len(ops))
            trace.record("fault_schedule", schedule=injector.schedule())
            trace.record("end",
                         violations=[v.to_dict() for v in checker.violations])
        finally:
            if racer is not None:
                racer.finalize()
            cluster.close()
    return RunResult(scenario=scenario, seed=seed, ops=ops,
                     violations=checker.violations, trace=trace,
                     flight_tail=FLIGHT.dst_tail())


def _execute(cluster: SimCluster, op: dict[str, Any]) -> Any:
    kind = op["kind"]
    if kind == "ingest":
        return cluster.ingest(op["node"], op["index"], op["docs"])
    if kind == "drain":
        return cluster.drain(op["node"])
    if kind == "search":
        return cluster.search(op["index"], op["max_hits"],
                              sort=op.get("sort"))
    if kind == "merge":
        return cluster.merge(op["node"], op["index"])
    if kind == "kill":
        return cluster.kill(op["node"])
    if kind == "restart":
        return cluster.restart(op["node"])
    if kind == "autoscale":
        return cluster.autoscale(op["queue_depth"])
    if kind == "plan":
        return cluster.plan()
    if kind == "cancel":
        return cluster.cancel_search(op["index"], op["max_hits"])
    if kind == "dashboard":
        return cluster.dashboard(op["index"], op["max_hits"], op["panels"],
                                 cancel_panel=op.get("cancel_panel", False))
    raise ValueError(f"unknown op kind: {kind!r}")


# --- shrinking ---------------------------------------------------------------

def shrink(scenario: Scenario, seed: int, ops: list[dict[str, Any]],
           violation: Violation,
           break_publish: bool = False,
           break_wal: bool = False,
           race: Optional[Any] = None) -> tuple[Scenario, list[dict[str, Any]]]:
    """Greedy seed-local shrink: one backward elimination pass over the op
    list, then one over the fault rules — a candidate survives only if the
    SAME-NAMED invariant still fires. Single-pass keeps the cost linear in
    the op count (each probe is a full deterministic run). Race findings
    shrink exactly like any other violation: each probe re-runs under the
    same PCT controller (same seed → same schedule for the surviving op
    prefix)."""
    name = violation.invariant

    def still_fails(sc: Scenario, candidate_ops: list[dict[str, Any]]) -> bool:
        result = run_scenario(sc, seed, ops=candidate_ops,
                              break_publish=break_publish,
                              break_wal=break_wal, race=race)
        return any(v.invariant == name for v in result.violations)

    current = list(ops)
    for i in reversed(range(len(current))):
        candidate = current[:i] + current[i + 1:]
        if still_fails(scenario, candidate):
            current = candidate

    rules = list(scenario.fault_rules)
    for i in reversed(range(len(rules))):
        candidate_rules = rules[:i] + rules[i + 1:]
        candidate_sc = dataclasses.replace(scenario,
                                           fault_rules=tuple(candidate_rules))
        if still_fails(candidate_sc, current):
            rules = candidate_rules
            scenario = candidate_sc
    return scenario, current


# --- sweep -------------------------------------------------------------------

def sweep(scenario: Scenario, seeds: int, start_seed: int = 0,
          artifacts_dir: Optional[str] = None,
          break_publish: Optional[bool] = None,
          break_wal: Optional[bool] = None,
          shrink_violations: bool = True,
          stop_on_first: bool = True,
          conformance: bool = False,
          race: Optional[Any] = None) -> dict[str, Any]:
    """Run `seeds` consecutive seeds; shrink + persist an artifact for each
    violating seed. Returns a JSON-safe summary (the CLI prints it).

    With `conformance=True` every run's trace is additionally replayed
    against the qwmc checkpoint model's abstract transition relation
    (`tools.qwmc.conformance.check_trace`) — a second, independent oracle:
    the runtime invariants compare against the acked ledger, the
    conformance check against what the exhaustively-verified model permits,
    so a planted bug must fall to both."""
    if break_publish is None:
        break_publish = _env_flag("QW_DST_BREAK_PUBLISH")
    if break_wal is None:
        break_wal = _env_flag("QW_DST_BREAK_WAL")
    check_trace = None
    if conformance:
        # lazy: tools/ sits beside quickwit_tpu/ at the repo root; the
        # DST layer must stay importable without it (wheel installs)
        from tools.qwmc.conformance import check_trace
    summary: dict[str, Any] = {
        "scenario": scenario.name, "seeds": seeds, "start_seed": start_seed,
        "passed": [], "violations": [],
    }
    if conformance:
        summary["nonconforming"] = []
    if race is not None:
        summary["race"] = race.to_dict()
    for seed in range(start_seed, start_seed + seeds):
        result = run_scenario(scenario, seed,
                              break_publish=break_publish,
                              break_wal=break_wal, race=race)
        if check_trace is not None:
            report = check_trace(result.trace.events)
            if not report["conforms"]:
                summary["nonconforming"].append(
                    {"seed": seed, "report": report})
        if result.ok:
            summary["passed"].append(seed)
            continue
        violation = result.first_violation
        entry: dict[str, Any] = {"seed": seed,
                                 "invariant": violation.invariant,
                                 "violation": violation.to_dict()}
        shrunk_scenario, shrunk_ops = scenario, result.ops
        if shrink_violations:
            shrunk_scenario, shrunk_ops = shrink(
                scenario, seed, result.ops, violation,
                break_publish=break_publish, break_wal=break_wal, race=race)
            entry["ops_before_shrink"] = len(result.ops)
            entry["ops_after_shrink"] = len(shrunk_ops)
            entry["fault_rules_after_shrink"] = len(
                shrunk_scenario.fault_rules)
        # re-run the shrunk repro to capture its trace for the artifact
        repro = run_scenario(shrunk_scenario, seed, ops=shrunk_ops,
                             break_publish=break_publish,
                             break_wal=break_wal, race=race)
        repro_violation = (repro.first_violation
                           if repro.first_violation else violation)
        artifact = make_artifact(
            shrunk_scenario, seed, shrunk_ops, repro_violation, repro.trace,
            break_publish=break_publish, break_wal=break_wal, race=race,
            flight_tail=repro.flight_tail)
        if artifacts_dir:
            os.makedirs(artifacts_dir, exist_ok=True)
            path = os.path.join(
                artifacts_dir,
                f"dst-{scenario.name}-seed{seed}-"
                f"{violation.invariant}.json")
            save_artifact(artifact, path)
            entry["artifact"] = path
        else:
            entry["artifact_inline"] = artifact
        summary["violations"].append(entry)
        if stop_on_first:
            break
    summary["ok"] = not summary["violations"] \
        and not summary.get("nonconforming")
    return summary


# --- replay ------------------------------------------------------------------

def replay(artifact: dict[str, Any]) -> tuple[RunResult, bool]:
    """Re-execute a replay artifact from its contents alone. Returns the
    fresh `RunResult` and whether its trace digest matches the recorded
    one byte-for-byte."""
    scenario = Scenario.from_dict(artifact["scenario"])
    flags = artifact.get("break_flags", {})
    race = None
    if artifact.get("race"):
        # lazy for the same reason as qwmc conformance above: the DST
        # layer stays importable without the tools/ tree
        from tools.qwrace.harness import race_from_dict
        race = race_from_dict(artifact["race"])
    result = run_scenario(
        scenario, int(artifact["seed"]), ops=list(artifact["ops"]),
        fault_plan=artifact.get("fault_plan"),
        break_publish=bool(flags.get("publish", False)),
        break_wal=bool(flags.get("wal", False)),
        race=race)
    ok = result.digest == artifact["trace_digest"]
    # artifacts that embed a flight-recorder tail must re-derive it
    # byte-identically too — the runtime timeline is part of the repro
    if "flight_tail" in artifact:
        ok = ok and result.flight_tail == artifact["flight_tail"]
    return result, ok


def scenario_by_name(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; known: {sorted(SCENARIOS)}"
        ) from None
