"""Deterministic whole-cluster simulation (DST).

Role of the reference fork's `quickwit-dst` crate + TLA+ specs (PAPER.md):
run a full in-process cluster — ingest with chained replication, WAL
drain/publish, merges, a polling metastore, control-plane planning, search
fan-out, the offload autoscaler — under ONE seeded scheduler that owns
virtual time, the op interleaving, the simulated network, and the fault
schedule, then check a library of invariants continuously and at
quiescence. Any violation emits a self-contained JSON replay artifact
that `python -m quickwit_tpu.dst replay` re-executes byte-identically,
after automatic seed-local shrinking.

Entry points:

- `Scenario` / `SCENARIOS` — the workload DSL (`scenario.py`)
- `run_scenario(scenario, seed)` — one deterministic run (`harness.py`)
- `sweep(scenario, seeds)` — explore seeds, shrink + persist violations
- `replay(artifact)` — re-execute a replay artifact
- `python -m quickwit_tpu.dst sweep|replay` — the CLI (`__main__.py`)

Everything the simulation touches must read time and randomness through
`quickwit_tpu.common.clock` (enforced by qwlint QW006) — the harness
installs a `FakeClock` and a seeded `random.Random` process-wide for the
duration of a run, so scenario hours cost milliseconds of wall time and
two runs of the same seed produce bit-identical traces.
"""

from .artifact import load_artifact, save_artifact
from .harness import RunResult, replay, run_scenario, shrink, sweep
from .invariants import INVARIANTS, Violation
from .scenario import SCENARIOS, Scenario

__all__ = [
    "INVARIANTS",
    "RunResult",
    "SCENARIOS",
    "Scenario",
    "Violation",
    "load_artifact",
    "replay",
    "run_scenario",
    "save_artifact",
    "shrink",
    "sweep",
]
