"""Simulated network: the seam between the root and each node's services.

Layered over the in-process clients (`LocalSearchClient`) the way the
reference's DST wraps its RPC layer: every cross-node call goes through
`SimNetwork.call`, which models

- **partitions** — a partitioned node is unreachable (`ConnectionError`,
  which the root's retry machinery treats like any dead leaf);
- **latency and typed errors** — driven by the run's shared seeded
  `FaultInjector` under per-node op names (``net.leaf_search@sim-1``), so
  the fault schedule lives in the same replay-artifact plan as every
  other perturbation, and latency sleeps land on the virtual clock;
- **duplicate delivery** — a seeded per-(node, method) decision stream
  re-issues the call (read RPCs are idempotent by design; duplication
  exercises exactly that, plus the cache tiers);
- **deadline observation** — each leaf request's `deadline_millis` is
  recorded for the deadline-monotonicity invariant.

Reordering across calls is owned by the scheduler's op-list permutation,
not modeled per-packet: ops execute synchronously one at a time, so the
op order IS the delivery order.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Optional

from ..common.faults import FaultInjector


class SimNetwork:
    def __init__(self, injector: FaultInjector, seed: int,
                 duplicate_probability: float = 0.0):
        self.injector = injector
        self.seed = seed
        self.duplicate_probability = float(duplicate_probability)
        self._partitioned: set[str] = set()
        self._dup_occurrences: dict[str, int] = {}
        # (node_id, deadline_millis) per observed leaf_search dispatch,
        # in call order — consumed by the deadline-monotonicity invariant
        self.deadline_observations: list[tuple[str, Optional[int]]] = []

    # --- partitions --------------------------------------------------------
    def partition(self, node_id: str) -> None:
        self._partitioned.add(node_id)

    def heal(self, node_id: str) -> None:
        self._partitioned.discard(node_id)

    def is_partitioned(self, node_id: str) -> bool:
        return node_id in self._partitioned

    # --- delivery ----------------------------------------------------------
    def _should_duplicate(self, op: str) -> bool:
        if self.duplicate_probability <= 0.0:
            return False
        occurrence = self._dup_occurrences.get(op, 0) + 1
        self._dup_occurrences[op] = occurrence
        digest = hashlib.blake2b(
            f"dup:{self.seed}:{op}:{occurrence}".encode(),
            digest_size=8).digest()
        roll = int.from_bytes(digest, "big") / float(1 << 64)
        return roll < self.duplicate_probability

    def call(self, node_id: str, method: str,
             fn: Callable[[Any], Any], request: Any) -> Any:
        if node_id in self._partitioned:
            raise ConnectionError(f"simnet: {node_id} unreachable")
        if method == "leaf_search":
            self.deadline_observations.append(
                (node_id, getattr(request, "deadline_millis", None)))
        op = f"net.{method}@{node_id}"
        self.injector.perturb(op)
        result = fn(request)
        if self._should_duplicate(op):
            # deliver twice: the second response wins, as with an at-least-
            # once transport; a non-idempotent handler would diverge here
            result = fn(request)
        return result


class SimSearchClient:
    """Leaf-search client routed through the simulated network — the same
    surface as `LocalSearchClient`, so it plugs into `RootSearcher`."""

    def __init__(self, network: SimNetwork, node_id: str, inner: Any):
        self.network = network
        self.node_id = node_id
        self.inner = inner

    def leaf_search(self, request: Any) -> Any:
        return self.network.call(self.node_id, "leaf_search",
                                 self.inner.leaf_search, request)

    def fetch_docs(self, request: Any) -> Any:
        return self.network.call(self.node_id, "fetch_docs",
                                 self.inner.fetch_docs, request)
