"""CLI: ``python -m quickwit_tpu.dst {sweep,replay,list}``.

- ``sweep --scenario mixed --seeds 200 [--artifacts-dir DIR] [--json]``
  explores seeds; exit code 1 if any seed violated an invariant (its
  shrunk replay artifact is persisted / printed). ``--conformance``
  additionally replays every trace against the qwmc checkpoint model
  (``tools.qwmc.conformance``): a trace that is not a behavior of the
  exhaustively-checked model fails the sweep even if no runtime
  invariant fired. ``--pct`` layers the qwrace PCT scheduler under every
  run: thread interleavings become seed-deterministic and a FastTrack
  happens-before detector reports data races / deadlocks as regular DST
  violations (shrunk and persisted like any other).
- ``replay path/to/artifact.json [--json]`` re-executes an artifact and
  exits 1 unless the trace digest matches byte-for-byte AND the recorded
  violation fires again.
- ``list`` prints the scenario and invariant catalogs.
"""

from __future__ import annotations

import argparse
import json
import sys

from .artifact import load_artifact
from .harness import replay, scenario_by_name, sweep
from .invariants import INVARIANTS
from .scenario import SCENARIOS


def _cmd_sweep(args: argparse.Namespace) -> int:
    scenario = scenario_by_name(args.scenario)
    race = None
    if args.pct:
        # lazy: the DST layer stays importable without the tools/ tree
        from tools.qwrace.harness import PctRace
        race = PctRace(depth=args.pct_depth, horizon=args.pct_horizon)
    summary = sweep(scenario, seeds=args.seeds, start_seed=args.start_seed,
                    artifacts_dir=args.artifacts_dir,
                    shrink_violations=not args.no_shrink,
                    stop_on_first=not args.keep_going,
                    conformance=args.conformance,
                    race=race)
    if args.json:
        print(json.dumps(summary, sort_keys=True, indent=2))
    else:
        line = (f"scenario={summary['scenario']} seeds={summary['seeds']} "
                f"passed={len(summary['passed'])} "
                f"violations={len(summary['violations'])}")
        if "nonconforming" in summary:
            line += f" nonconforming={len(summary['nonconforming'])}"
        print(line)
        for entry in summary.get("nonconforming", []):
            for v in entry["report"]["violations"]:
                print(f"  seed {entry['seed']}: trace not a model "
                      f"behavior — {v['invariant']} on {v['index']}: "
                      f"{v['detail']}")
        for entry in summary["violations"]:
            line = (f"  seed {entry['seed']}: {entry['invariant']}")
            if "ops_after_shrink" in entry:
                line += (f" (shrunk {entry['ops_before_shrink']}"
                         f"→{entry['ops_after_shrink']} ops)")
            if "artifact" in entry:
                line += f" -> {entry['artifact']}"
            print(line)
    return 0 if summary["ok"] else 1


def _cmd_replay(args: argparse.Namespace) -> int:
    artifact = load_artifact(args.artifact)
    result, digest_match = replay(artifact)
    expected = artifact["violation"]["invariant"]
    reproduced = any(v.invariant == expected for v in result.violations)
    out = {
        "seed": result.seed,
        "scenario": result.scenario.name,
        "digest": result.digest,
        "expected_digest": artifact["trace_digest"],
        "digest_match": digest_match,
        "expected_violation": expected,
        "violation_reproduced": reproduced,
        "violations": [v.to_dict() for v in result.violations],
    }
    if args.json:
        print(json.dumps(out, sort_keys=True, indent=2))
    else:
        status = ("REPLAYED byte-identically" if digest_match
                  else "TRACE DIVERGED")
        print(f"seed {result.seed} ({result.scenario.name}): {status}; "
              f"violation {expected!r} "
              f"{'reproduced' if reproduced else 'NOT reproduced'}")
    return 0 if (digest_match and reproduced) else 1


def _cmd_list(args: argparse.Namespace) -> int:
    out = {
        "scenarios": {
            name: {"nodes": sc.nodes, "steps": sc.steps,
                   "invariants": list(sc.invariants),
                   "fault_rules": len(sc.fault_rules)}
            for name, sc in sorted(SCENARIOS.items())
        },
        "invariants": INVARIANTS,
    }
    if args.json:
        print(json.dumps(out, sort_keys=True, indent=2))
    else:
        print("scenarios:")
        for name, info in out["scenarios"].items():
            print(f"  {name}: nodes={info['nodes']} steps={info['steps']} "
                  f"invariants={len(info['invariants'])}")
        print("invariants:")
        for name, desc in INVARIANTS.items():
            print(f"  {name}: {desc}")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m quickwit_tpu.dst",
        description="deterministic whole-cluster simulation harness")
    sub = parser.add_subparsers(dest="command", required=True)

    p_sweep = sub.add_parser("sweep", help="run a seed sweep")
    p_sweep.add_argument("--scenario", default="mixed",
                         choices=sorted(SCENARIOS))
    p_sweep.add_argument("--seeds", type=int, default=100)
    p_sweep.add_argument("--start-seed", type=int, default=0)
    p_sweep.add_argument("--artifacts-dir", default=None)
    p_sweep.add_argument("--no-shrink", action="store_true",
                         help="persist violations without shrinking")
    p_sweep.add_argument("--keep-going", action="store_true",
                         help="continue past the first violating seed")
    p_sweep.add_argument("--conformance", action="store_true",
                         help="also replay every trace against the qwmc "
                              "checkpoint model (refinement check)")
    p_sweep.add_argument("--pct", action="store_true",
                         help="run every seed under the qwrace PCT "
                              "scheduler: randomized-but-deterministic "
                              "thread interleavings with happens-before "
                              "race detection (tools/qwrace)")
    p_sweep.add_argument("--pct-depth", type=int, default=3,
                         help="PCT bug depth d: d-1 priority change "
                              "points per schedule (default 3)")
    p_sweep.add_argument("--pct-horizon", type=int, default=4096,
                         help="PCT horizon k: change points are drawn "
                              "from the first k scheduling decisions; "
                              "match to trace length for deep "
                              "deadlock-order bugs (default 4096)")
    p_sweep.add_argument("--json", action="store_true")
    p_sweep.set_defaults(fn=_cmd_sweep)

    p_replay = sub.add_parser("replay", help="re-execute a replay artifact")
    p_replay.add_argument("artifact")
    p_replay.add_argument("--json", action="store_true")
    p_replay.set_defaults(fn=_cmd_replay)

    p_list = sub.add_parser("list", help="list scenarios and invariants")
    p_list.add_argument("--json", action="store_true")
    p_list.set_defaults(fn=_cmd_list)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
