"""Replay artifacts: self-contained canonical-JSON repro files.

One schema, two kinds. Every artifact this repo emits — a DST replay
artifact (``quickwit-dst-replay``) or a qwmc model-checker counterexample
(``quickwit-qwmc-counterexample``, `tools/qwmc/artifact.py`) — shares the
SAME envelope: a single ``version`` field (`ARTIFACT_VERSION`), a ``kind``
from `KNOWN_KINDS`, and a ``digest`` computed by the one blake2b helper in
`trace.py` over the canonical-JSON body. `save_artifact`/`load_artifact`
here are the only writers/readers for both families, so the formats cannot
drift apart: a version bump or digest change lands on every artifact kind
at once.

A DST replay artifact carries everything a fresh process needs to
re-execute a violating run byte-identically — the (shrunk) scenario, the
seed, the explicit op list, the fault plan with zeroed cursors, the
break-flag switches that were active, the violation, and the reference
trace + digest `dst replay` compares against. Nothing in it references
local filesystem state; `python -m quickwit_tpu.dst replay <file>` on any
machine reproduces the run from the file alone. A qwmc counterexample
carries the model name, config, violated property, and the minimal action
path — `python -m tools.qwmc replay <file>` re-executes it the same way.
"""

from __future__ import annotations

import json
from typing import Any

from ..common.faults import FaultInjector
from .invariants import Violation
from .scenario import Scenario
from .trace import Trace, blake2b_digest, canonical_json

# single version for EVERY artifact kind: bumping it revs the DST replay
# and the qwmc counterexample formats together (version 1 = pre-envelope
# DST artifacts without the integrity digest; still loadable)
ARTIFACT_VERSION = 2
ARTIFACT_KIND = "quickwit-dst-replay"
QWMC_KIND = "quickwit-qwmc-counterexample"
KNOWN_KINDS = frozenset({ARTIFACT_KIND, QWMC_KIND})


def finish_artifact(kind: str, body: dict[str, Any]) -> dict[str, Any]:
    """Stamp the shared envelope onto an artifact body: version, kind, and
    the integrity digest over the canonical-JSON body (digest excludes the
    envelope fields themselves so it is reproducible from the payload)."""
    if kind not in KNOWN_KINDS:
        raise ValueError(f"unknown artifact kind: {kind!r}")
    payload = {k: v for k, v in body.items()
               if k not in ("version", "kind", "digest")}
    artifact = {"version": ARTIFACT_VERSION, "kind": kind,
                "digest": blake2b_digest(payload)}
    artifact.update(payload)
    return artifact


def make_artifact(scenario: Scenario, seed: int, ops: list[dict[str, Any]],
                  violation: Violation, trace: Trace,
                  break_publish: bool = False,
                  break_wal: bool = False,
                  race: Any = None,
                  flight_tail: Any = None) -> dict[str, Any]:
    # a FRESH injector's plan (cursors at zero): replay must start the
    # fault decision streams from the beginning, not where the run ended
    fault_plan = FaultInjector(seed, list(scenario.fault_rules)).to_plan()
    body = {
        "scenario": scenario.to_dict(),
        "seed": int(seed),
        "ops": list(ops),
        "fault_plan": fault_plan,
        "break_flags": {"publish": bool(break_publish),
                        "wal": bool(break_wal)},
        "violation": violation.to_dict(),
        "trace_digest": trace.digest(),
        "trace": list(trace.events),
    }
    if race is not None:
        # the PCT controller config: with it, `dst replay` reconstructs
        # the race runtime and the schedule re-derives from the seed alone
        body["race"] = race.to_dict()
    if flight_tail is not None:
        # the repro run's flight-recorder timeline (op-thread ring, virtual
        # timestamps): the shrunk artifact carries the device/runtime
        # timeline of the failure, and replay re-derives it byte-identically
        body["flight_tail"] = list(flight_tail)
    return finish_artifact(ARTIFACT_KIND, body)


def save_artifact(artifact: dict[str, Any], path: str,
                  kind: str = ARTIFACT_KIND) -> None:
    if artifact.get("kind") != kind:
        raise ValueError(
            f"not a {kind} artifact (kind={artifact.get('kind')!r})")
    with open(path, "w", encoding="utf-8") as f:
        # canonical form on disk too: diffing two artifacts is meaningful
        f.write(canonical_json(artifact))
        f.write("\n")


def load_artifact(path: str, kind: str = ARTIFACT_KIND) -> dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        artifact = json.load(f)
    if artifact.get("kind") != kind:
        raise ValueError(f"{path}: not a {kind} artifact")
    if int(artifact.get("version", -1)) > ARTIFACT_VERSION:
        raise ValueError(
            f"{path}: artifact version {artifact['version']} is newer than "
            f"this harness ({ARTIFACT_VERSION})")
    recorded = artifact.get("digest")
    if recorded is not None:
        payload = {k: v for k, v in artifact.items()
                   if k not in ("version", "kind", "digest")}
        actual = blake2b_digest(payload)
        if actual != recorded:
            raise ValueError(
                f"{path}: artifact digest mismatch (file says {recorded}, "
                f"payload hashes to {actual}) — corrupted or hand-edited")
    return artifact
