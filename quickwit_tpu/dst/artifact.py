"""Replay artifacts: self-contained JSON repro files.

An artifact carries everything a fresh process needs to re-execute a
violating run byte-identically — the (shrunk) scenario, the seed, the
explicit op list, the fault plan with zeroed cursors, the break-flag
switches that were active, the violation, and the reference trace +
digest `dst replay` compares against. Nothing in it references local
filesystem state; `python -m quickwit_tpu.dst replay <file>` on any
machine reproduces the run from the file alone.
"""

from __future__ import annotations

import json
from typing import Any

from ..common.faults import FaultInjector
from .invariants import Violation
from .scenario import Scenario
from .trace import Trace, canonical_json

ARTIFACT_VERSION = 1
ARTIFACT_KIND = "quickwit-dst-replay"


def make_artifact(scenario: Scenario, seed: int, ops: list[dict[str, Any]],
                  violation: Violation, trace: Trace,
                  break_publish: bool = False,
                  break_wal: bool = False) -> dict[str, Any]:
    # a FRESH injector's plan (cursors at zero): replay must start the
    # fault decision streams from the beginning, not where the run ended
    fault_plan = FaultInjector(seed, list(scenario.fault_rules)).to_plan()
    return {
        "version": ARTIFACT_VERSION,
        "kind": ARTIFACT_KIND,
        "scenario": scenario.to_dict(),
        "seed": int(seed),
        "ops": list(ops),
        "fault_plan": fault_plan,
        "break_flags": {"publish": bool(break_publish),
                        "wal": bool(break_wal)},
        "violation": violation.to_dict(),
        "trace_digest": trace.digest(),
        "trace": list(trace.events),
    }


def save_artifact(artifact: dict[str, Any], path: str) -> None:
    if artifact.get("kind") != ARTIFACT_KIND:
        raise ValueError("not a DST replay artifact")
    with open(path, "w", encoding="utf-8") as f:
        # canonical form on disk too: diffing two artifacts is meaningful
        f.write(canonical_json(artifact))
        f.write("\n")


def load_artifact(path: str) -> dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        artifact = json.load(f)
    if artifact.get("kind") != ARTIFACT_KIND:
        raise ValueError(f"{path}: not a DST replay artifact")
    if int(artifact.get("version", -1)) > ARTIFACT_VERSION:
        raise ValueError(
            f"{path}: artifact version {artifact['version']} is newer than "
            f"this harness ({ARTIFACT_VERSION})")
    return artifact
