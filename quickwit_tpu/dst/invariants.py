"""Invariant library: the properties every simulated run must uphold.

Two classes, mirroring the reference's DST checks and TLA+ safety
properties:

- **per-op invariants** — checked right after the op that can violate them
  (cache≡cold on every search, conservation on every merge, bounds on
  every autoscaler tick, completeness on every plan);
- **ledger invariants** — checked at quiescence against the ground-truth
  oracle (`SimCluster.searchable_ns`): exactly-once publish (no doc
  appears in two published splits), zero-loss WAL failover (every acked
  doc is searchable), and tenant isolation over the full corpus.

A failed check appends a `Violation` — a JSON-safe record naming the
invariant, the step, and enough detail to read the shrunk artifact
without re-running it. Checks must themselves be deterministic: details
are built from sorted/aggregated values only (never thread-ordered
observations — e.g. leaf deadline checks aggregate to a boolean, because
fan-out dispatch order is not part of the simulation's determinism
contract, only its outcomes are).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# name -> one-line description; the CLI and docs render this catalog
INVARIANTS: dict[str, str] = {
    "exactly_once_publish":
        "no doc is published into more than one live split "
        "(checkpoint CAS ⇒ at-most-once drain per WAL position)",
    "zero_loss_wal_failover":
        "every acked doc is searchable after quiescence, across any "
        "sequence of kills, promotions, and restarts",
    "cache_cold_equivalence":
        "a repeated query served warm returns exactly the cold result",
    "tenant_isolation":
        "a query against one index never returns another tenant's docs",
    "merge_input_conservation":
        "a merge preserves the published doc count (inputs' docs == "
        "output's docs)",
    "deadline_monotonicity":
        "every leaf request carries a deadline no larger than the root's "
        "remaining budget (budgets shrink down the tree, never grow)",
    "autoscaler_bounds":
        "the offload pool size stays within [min_workers, max_workers] "
        "after every tick",
    "plan_completeness":
        "the physical indexing plan assigns every task exactly once, "
        "only to alive nodes",
    "cancel_responsiveness":
        "a query cancelled before it started returns a typed cancelled "
        "response with zero hits and leaves no registry entry behind",
}

# slack for deadline comparisons: serialization rounds to whole millis
_DEADLINE_SLACK_MS = 5


@dataclass
class Violation:
    invariant: str
    step: int
    details: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"invariant": self.invariant, "step": self.step,
                "details": self.details}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Violation":
        return cls(invariant=str(data["invariant"]), step=int(data["step"]),
                   details=dict(data.get("details", {})))


class InvariantChecker:
    def __init__(self, enabled: tuple[str, ...],
                 expected_index_of_n: dict[int, str]):
        unknown = sorted(set(enabled) - set(INVARIANTS))
        if unknown:
            raise ValueError(f"unknown invariants: {unknown}")
        self.enabled = set(enabled)
        self.expected_index_of_n = expected_index_of_n
        self.violations: list[Violation] = []
        self._deadline_cursor = 0

    def _on(self, name: str) -> bool:
        return name in self.enabled

    def _fail(self, name: str, step: int, **details: Any) -> None:
        self.violations.append(Violation(name, step, details))

    # --- per-op ------------------------------------------------------------
    def after_op(self, cluster, op: dict[str, Any], result: Any,
                 step: int) -> None:
        kind = op["kind"]
        if kind == "search":
            self._check_search(op, result, step, cluster)
        elif kind == "merge":
            self._check_merge(result, step)
        elif kind == "autoscale":
            self._check_autoscale(result, step)
        elif kind == "plan":
            self._check_plan(result, step)
        elif kind == "cancel":
            self._check_cancel(result, step)
        elif kind == "dashboard":
            # every concurrent panel is audited exactly like a standalone
            # search (cold≡warm, tenant isolation, deadlines); the shed
            # panel is audited like a standalone pre-cancelled query
            for outs in (result.get("panels") or ()):
                if outs is not None:
                    self._check_search(op, outs, step, cluster)
            if result.get("cancelled_panel") is not None:
                self._check_cancel(result["cancelled_panel"], step)

    def _check_search(self, op: dict[str, Any], outs: list[dict[str, Any]],
                      step: int, cluster) -> None:
        complete = [o for o in outs if o.get("complete")]
        if self._on("cache_cold_equivalence") and len(complete) >= 2:
            cold, warm = complete[0], complete[1]
            if (cold["ns"] != warm["ns"]
                    or cold["num_hits"] != warm["num_hits"]):
                self._fail("cache_cold_equivalence", step,
                           index=op["index"],
                           cold={"ns": cold["ns"],
                                 "num_hits": cold["num_hits"]},
                           warm={"ns": warm["ns"],
                                 "num_hits": warm["num_hits"]})
        if self._on("tenant_isolation"):
            for out in outs:
                leaked = sorted(
                    n for n in out.get("ns", ())
                    if self.expected_index_of_n.get(n) != op["index"])
                if leaked:
                    self._fail("tenant_isolation", step, index=op["index"],
                               leaked_ns=leaked)
                    break
        if self._on("deadline_monotonicity"):
            budget_ms = int(cluster.scenario.search_timeout_secs * 1000)
            observations = cluster.network.deadline_observations
            window = observations[self._deadline_cursor:]
            self._deadline_cursor = len(observations)
            bad = sorted({
                node_id for node_id, deadline in window
                if deadline is None
                or deadline > budget_ms + _DEADLINE_SLACK_MS})
            if bad:
                self._fail("deadline_monotonicity", step, index=op["index"],
                           budget_ms=budget_ms, nodes=bad)

    def _check_merge(self, result: dict[str, Any], step: int) -> None:
        if not self._on("merge_input_conservation"):
            return
        if result.get("merged") and result["docs_before"] != result["docs_after"]:
            self._fail("merge_input_conservation", step,
                       docs_before=result["docs_before"],
                       docs_after=result["docs_after"])

    def _check_autoscale(self, result: dict[str, Any], step: int) -> None:
        if not self._on("autoscaler_bounds"):
            return
        size = result["pool_size"]
        if not result["min"] <= size <= result["max"]:
            self._fail("autoscaler_bounds", step, pool_size=size,
                       min=result["min"], max=result["max"])

    def _check_cancel(self, result: dict[str, Any], step: int) -> None:
        """A pre-cancelled query handle must never produce hits (the
        per-split cancel check runs before any device work), and the
        registry entry must be gone once the search returns — a leaked
        token would pin the next query under the same handle. With no
        published splits yet the response is trivially complete (zero
        splits to cancel), so `cancelled` is only required once the
        query had work to cut short."""
        if not self._on("cancel_responsiveness"):
            return
        if "error" in result:
            return  # typed failure (e.g. no alive nodes): nothing to audit
        problems = {}
        if result.get("num_hits"):
            problems["num_hits"] = result["num_hits"]
        if result.get("had_splits") and not result.get("cancelled"):
            problems["uncancelled_with_splits"] = True
        if not result.get("registry_drained"):
            problems["registry_leak"] = True
        if problems:
            self._fail("cancel_responsiveness", step, **problems)

    def _check_plan(self, result: dict[str, Any], step: int) -> None:
        if not self._on("plan_completeness"):
            return
        counts = result["assignments"]
        problems = {}
        missing = result["num_tasks"] - sum(counts.values())
        duplicated = sorted(k for k, c in counts.items() if c > 1)
        if missing:
            problems["unassigned_tasks"] = missing
        if duplicated:
            problems["duplicated_tasks"] = duplicated
        if result["assigned_to_dead"]:
            problems["assigned_to_dead"] = result["assigned_to_dead"]
        if problems:
            self._fail("plan_completeness", step, **problems)

    # --- ledger (quiescence) -----------------------------------------------
    def at_quiescence(self, cluster, step: int) -> None:
        for index_id in cluster.scenario.indexes:
            searchable = cluster.searchable_ns(index_id)
            if self._on("exactly_once_publish"):
                dups = sorted({n for n in searchable
                               if searchable.count(n) > 1})
                if dups:
                    self._fail("exactly_once_publish", step, index=index_id,
                               duplicated_ns=dups[:50],
                               num_duplicated=len(dups))
            if self._on("zero_loss_wal_failover"):
                lost = sorted(set(cluster.acked[index_id]) - set(searchable))
                if lost:
                    self._fail("zero_loss_wal_failover", step,
                               index=index_id, lost_ns=lost[:50],
                               num_lost=len(lost),
                               num_acked=len(cluster.acked[index_id]),
                               num_searchable=len(searchable))
            if self._on("tenant_isolation"):
                leaked = sorted(
                    {n for n in searchable
                     if self.expected_index_of_n.get(n) != index_id})
                if leaked:
                    self._fail("tenant_isolation", step, index=index_id,
                               leaked_ns=leaked[:50])
