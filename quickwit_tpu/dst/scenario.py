"""Scenario DSL: declarative workload + invariant selection for one run.

A `Scenario` declares the cluster shape (nodes, indexes), the workload mix
(ingest / drain / search / merge / membership churn / autoscaler and
control-plane ticks as weighted op kinds), the fault plan, and which
invariants to check. `materialize(seed)` expands it into the explicit,
JSON-safe op list one run executes — the op list IS the interleaving: the
scheduler executes it in order, so storing it in a replay artifact (and
deleting entries from it during shrinking) fully pins a run.

Materialization tracks its own alive-set so churn ops are always
executable (never kill the last node, never restart a live one); the
executor mirrors the same bookkeeping, keeping op semantics identical
between generation and replay.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

from ..common.faults import FaultRule

DEFAULT_WEIGHTS: dict[str, int] = {
    "ingest": 6,
    "drain": 4,
    "search": 5,
    "merge": 1,
    "kill": 2,
    "restart": 2,
    "autoscale": 1,
    "plan": 1,
    # a search whose handle was cancelled before the query started (the
    # REST DELETE racing ahead of the query): weight 0 by default so
    # pre-existing scenarios' op streams (and replay artifacts) stay
    # byte-identical — materialize() only draws kinds with weight > 0
    "cancel": 0,
    # N concurrent shape-compatible panel searches through ONE node — the
    # workload the query batcher stacks into a single device dispatch
    # (search/batcher.py QueryGroupPlanner). Weight 0 by default for the
    # same replay-stability reason as "cancel".
    "dashboard": 0,
}

ALL_INVARIANTS = (
    "exactly_once_publish",
    "zero_loss_wal_failover",
    "cache_cold_equivalence",
    "tenant_isolation",
    "merge_input_conservation",
    "deadline_monotonicity",
    "autoscaler_bounds",
    "plan_completeness",
    "cancel_responsiveness",
)


@dataclass(frozen=True)
class Scenario:
    name: str
    nodes: int = 2
    indexes: tuple[str, ...] = ("tenant-a", "tenant-b")
    steps: int = 40
    docs_min: int = 1
    docs_max: int = 6
    # virtual seconds advanced before each op: > the metastore polling TTL
    # the cluster uses, so cross-node publishes become visible step-over-step
    step_secs: float = 7.5
    search_timeout_secs: float = 5.0
    replication: bool = True
    # elastic leaf-search offload at production fan-out: each node gets an
    # in-process worker fleet and `max_local_splits=1`, so every multi-split
    # leaf request exercises the dispatcher's thread spawn / steal / hedge
    # machinery concurrently with the cache tiers — the interleaving
    # surface the qwrace `--pct` schedule exploration randomizes
    offload: bool = False
    # mix in fast-field-sorted searches (sort by "ts"/"n" desc): they arm
    # threshold pruning, whose shared ThresholdBox the local execute loop
    # and the offload dispatch thread then touch concurrently. Opt-in so
    # pre-existing scenarios' op streams (and their replay artifacts)
    # stay byte-identical.
    sorted_searches: bool = False
    weights: dict[str, int] = field(
        default_factory=lambda: dict(DEFAULT_WEIGHTS))
    invariants: tuple[str, ...] = ALL_INVARIANTS
    fault_rules: tuple[FaultRule, ...] = ()

    # --- materialization ---------------------------------------------------
    def _rng(self, seed: int) -> random.Random:
        digest = hashlib.blake2b(f"{self.name}:{seed}".encode(),
                                 digest_size=8).digest()
        return random.Random(int.from_bytes(digest, "big"))

    def materialize(self, seed: int) -> list[dict[str, Any]]:
        """Expand into the explicit op list for `seed`. Ops are JSON-safe
        dicts; doc payloads carry globally unique sequence numbers `n`
        (disjoint across indexes by construction — the tenant-isolation
        oracle keys on them)."""
        rng = self._rng(seed)
        node_ids = [f"sim-{i}" for i in range(self.nodes)]
        alive = set(node_ids)
        kinds = [k for k, w in sorted(self.weights.items()) if w > 0]
        weights = [self.weights[k] for k in kinds]
        ops: list[dict[str, Any]] = []
        next_n = 0
        for _ in range(self.steps):
            kind = rng.choices(kinds, weights=weights, k=1)[0]
            if kind == "kill" and len(alive) <= 1:
                kind = "search"  # never kill the last node
            if kind == "restart" and len(alive) == len(node_ids):
                kind = "drain"  # nothing to restart
            if kind == "ingest":
                node = rng.choice(sorted(alive))
                index_id = rng.choice(self.indexes)
                count = rng.randint(self.docs_min, self.docs_max)
                docs = [{"n": next_n + i,
                         "ts": 1_600_000_000 + next_n + i,
                         "body": f"doc {index_id} {next_n + i}"}
                        for i in range(count)]
                next_n += count
                ops.append({"kind": "ingest", "node": node,
                            "index": index_id, "docs": docs})
            elif kind == "drain":
                ops.append({"kind": "drain",
                            "node": rng.choice(sorted(alive))})
            elif kind == "search":
                op = {"kind": "search",
                      "index": rng.choice(self.indexes),
                      "max_hits": rng.choice((10, 100, 1000))}
                if self.sorted_searches:
                    sort = rng.choice((None, "ts", "n"))
                    if sort is not None:
                        op["sort"] = sort
                ops.append(op)
            elif kind == "merge":
                ops.append({"kind": "merge", "node": rng.choice(sorted(alive)),
                            "index": rng.choice(self.indexes)})
            elif kind == "kill":
                node = rng.choice(sorted(alive))
                alive.discard(node)
                ops.append({"kind": "kill", "node": node})
            elif kind == "restart":
                node = rng.choice(sorted(set(node_ids) - alive))
                alive.add(node)
                ops.append({"kind": "restart", "node": node})
            elif kind == "autoscale":
                ops.append({"kind": "autoscale",
                            "queue_depth": rng.randint(0, 64)})
            elif kind == "plan":
                ops.append({"kind": "plan"})
            elif kind == "cancel":
                # same shape knobs as a search; the executor cancels the
                # query handle before the query starts, so the run is
                # deterministic: the cancel always lands first
                ops.append({"kind": "cancel",
                            "index": rng.choice(self.indexes),
                            "max_hits": rng.choice((10, 100, 1000))})
            elif kind == "dashboard":
                # panels share structure (same sort/max_hits, Range on the
                # timestamp fast field) but carry distinct window bounds:
                # distinct queries, one group key. cancel_panel sheds one
                # rider's handle up front — the post-formation masking path
                ops.append({"kind": "dashboard",
                            "index": rng.choice(self.indexes),
                            "max_hits": rng.choice((10, 100)),
                            "panels": rng.randint(2, 4),
                            "cancel_panel": rng.random() < 0.3})
        return ops

    # --- (de)serialization -------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        out = asdict(self)
        out["indexes"] = list(self.indexes)
        out["invariants"] = list(self.invariants)
        out["fault_rules"] = [asdict(r) for r in self.fault_rules]
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Scenario":
        return cls(
            name=data["name"],
            nodes=int(data.get("nodes", 2)),
            indexes=tuple(data.get("indexes", ("tenant-a", "tenant-b"))),
            steps=int(data.get("steps", 40)),
            docs_min=int(data.get("docs_min", 1)),
            docs_max=int(data.get("docs_max", 6)),
            step_secs=float(data.get("step_secs", 7.5)),
            search_timeout_secs=float(data.get("search_timeout_secs", 5.0)),
            replication=bool(data.get("replication", True)),
            offload=bool(data.get("offload", False)),
            sorted_searches=bool(data.get("sorted_searches", False)),
            weights={str(k): int(v)
                     for k, v in data.get("weights", DEFAULT_WEIGHTS).items()},
            invariants=tuple(data.get("invariants", ALL_INVARIANTS)),
            fault_rules=tuple(FaultRule(**r)
                              for r in data.get("fault_rules", ())),
        )


def _default_fault_rules() -> tuple[FaultRule, ...]:
    """The mixed scenario's chaos plan: occasional storage latency, rare
    retryable leaf/storage errors, rare replication failures — all survivable
    by design (retries, rollback, failover), so a 100+-seed sweep passes."""
    return (
        FaultRule(operation="storage.get_slice", kind="latency",
                  probability=0.05, latency_secs=0.2),
        FaultRule(operation="net.leaf_search@*", kind="error",
                  probability=0.04),
        FaultRule(operation="ingest.replicate", kind="error",
                  probability=0.05),
        FaultRule(operation="wal.fsync", kind="latency",
                  probability=0.05, latency_secs=0.05),
    )


SCENARIOS: dict[str, Scenario] = {
    # tier-1 smoke: small, fast, three core invariants, light faults
    "smoke": Scenario(
        name="smoke", nodes=2, steps=18, step_secs=7.5,
        indexes=("tenant-a", "tenant-b"),
        invariants=("exactly_once_publish", "zero_loss_wal_failover",
                    "tenant_isolation"),
        fault_rules=(FaultRule(operation="ingest.replicate", kind="error",
                               probability=0.05),),
    ),
    # the acceptance scenario: mixed ingest/search/failover, full invariant
    # set, the default chaos plan
    "mixed": Scenario(
        name="mixed", nodes=3, steps=40,
        indexes=("tenant-a", "tenant-b"),
        invariants=ALL_INVARIANTS,
        fault_rules=_default_fault_rules(),
    ),
    # offload dispatch + cache-tier interleavings at production fan-out
    # (ROADMAP item 5's named headroom): every node runs an in-process
    # worker fleet with max_local_splits=1, so multi-split searches drive
    # the dispatcher's spawn/steal/hedge threads against the shared cache
    # tiers. Under `dst sweep --pct` the qwrace scheduler randomizes the
    # thread interleavings; without it the run stays a concurrency smoke.
    # single node: the whole published split set lands in ONE leaf request,
    # so the offload cut (max_local_splits=1) reliably fans the cold tail
    # out over the in-process worker fleet
    # the cancel weight mixes pre-cancelled query handles into the same
    # stream: the typed-cancelled path (registry adopt, per-split cancel
    # checks, batcher bail-out) runs against the offload dispatcher and
    # cache tiers, and cancel_responsiveness audits every one of them
    "fanout": Scenario(
        name="fanout", nodes=1, steps=30,
        indexes=("tenant-a", "tenant-b"),
        offload=True, replication=False, sorted_searches=True,
        weights={"ingest": 8, "drain": 6, "search": 8, "merge": 1,
                 "kill": 0, "restart": 0, "autoscale": 2, "plan": 0,
                 "cancel": 2, "dashboard": 2},
        invariants=("exactly_once_publish", "tenant_isolation",
                    "cache_cold_equivalence", "autoscaler_bounds",
                    "cancel_responsiveness"),
    ),
}
