"""Scroll contexts: stateful pagination.

Role of the reference's `ScrollContext` + cluster KV
(`scroll_context.rs:51,146`, `docs/internals/scroll.md`): the first scroll
request caches a window of partial hits under a scroll id; subsequent
requests page through the cache and refill it with search_after when
exhausted. The KV store here is in-process with TTL (the reference
replicates it to affinity nodes via put_kv — the replication hook is the
store itself, swappable for a replicated one).
"""

from __future__ import annotations

import base64
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from .models import PartialHit, SearchRequest
from ..common import sync

DEFAULT_TTL_SECS = 300
CACHE_WINDOW = 1000


@dataclass
class ScrollContext:
    request: SearchRequest
    cached_hits: list[Any]  # fetched Hits (docs included), global rank order
    cursor: int = 0
    total_hits: int = 0
    created_at: float = field(default_factory=time.monotonic)
    ttl_secs: float = DEFAULT_TTL_SECS

    @property
    def expired(self) -> bool:
        return time.monotonic() - self.created_at > self.ttl_secs


class ScrollStore:
    def __init__(self) -> None:
        self._contexts: dict[str, ScrollContext] = {}
        self._lock = sync.lock("ScrollStore._lock")

    def put(self, context: ScrollContext) -> str:
        scroll_id = base64.urlsafe_b64encode(uuid.uuid4().bytes).decode().rstrip("=")
        with self._lock:
            self._gc()
            self._contexts[scroll_id] = context
        return scroll_id

    def put_with_id(self, scroll_id: str, context: ScrollContext) -> None:
        """Install a replicated context under its existing id (the
        affinity-replica side of put_kv)."""
        with self._lock:
            self._gc()
            self._contexts[scroll_id] = context

    def get(self, scroll_id: str) -> Optional[ScrollContext]:
        with self._lock:
            context = self._contexts.get(scroll_id)
            if context is not None and context.expired:
                del self._contexts[scroll_id]
                return None
            return context

    def delete(self, scroll_id: str) -> bool:
        with self._lock:
            return self._contexts.pop(scroll_id, None) is not None

    def _gc(self) -> None:
        dead = [k for k, c in self._contexts.items() if c.expired]
        for k in dead:
            del self._contexts[k]


# --------------------------------------------------------------------------
# serialization (cluster-KV replication of scroll contexts — reference:
# put_kv to best-affinity nodes, scroll_context.rs:146)

def context_to_dict(context: ScrollContext) -> dict:
    return {
        "request": context.request.to_dict(),
        "cached_hits": [
            {"doc": h.doc, "score": h.score, "sort_values": h.sort_values,
             "split_id": h.split_id, "doc_id": h.doc_id,
             "snippets": h.snippets}
            for h in context.cached_hits],
        "cursor": context.cursor,
        "total_hits": context.total_hits,
        "ttl_secs": context.ttl_secs,
        "age_secs": time.monotonic() - context.created_at,
    }


def context_from_dict(d: dict) -> ScrollContext:
    from .models import Hit, SearchRequest
    return ScrollContext(
        request=SearchRequest.from_dict(d["request"]),
        cached_hits=[Hit(doc=h["doc"], score=h.get("score"),
                         sort_values=h.get("sort_values") or [],
                         split_id=h.get("split_id", ""),
                         doc_id=h.get("doc_id", 0),
                         snippets=h.get("snippets"))
                     for h in d["cached_hits"]],
        cursor=d.get("cursor", 0),
        total_hits=d.get("total_hits", 0),
        created_at=time.monotonic() - d.get("age_secs", 0.0),
        ttl_secs=d.get("ttl_secs", DEFAULT_TTL_SECS),
    )
