"""Byte-accurate HBM admission for leaf search.

Role of the reference's `SearchPermitProvider`
(`quickwit-search/src/search_permit_provider.rs:43,436`): split searches
must not materialize more memory than the device has. The reference
estimates pessimistically and corrects after warmup; here the lowered
plan KNOWS every array's exact byte size before any transfer, so
admission is exact:

- a query's NEW transfer bytes are **pinned** for the duration of its
  execution; admission order is weighted deficit-round-robin across
  tenants (`tenancy/drr.py`): per-tenant FIFO sub-queues, grant order by
  deficit counter, so one flooding tenant cannot convoy everyone else's
  queue wait, while large requests still cannot be starved by a stream
  of small ones. Unlabeled traffic all lands in one implicit tenant,
  where DRR degenerates to the exact FIFO this queue used to be.
  Admission blocks while earlier pins would overflow the budget —
  over-budget work queues instead of materializing;
- after execution the pins downgrade to **resident** bytes (the device
  array cache that makes repeat queries skip H2D); residency is evicted
  LRU per split reader whenever new pins need room. Readers with
  in-flight queries are never evicted (their device arrays are in use).

A single query larger than the whole budget is admitted alone (pinned
bytes of others == 0) — refusing it would deadlock, and the reference
likewise lets one oversized split through to fail loudly on-device.

Format-v2 splits stage FOR-packed numeric columns as narrow delta lanes
(docs/device-layout.md), so the bytes admitted here are the compact
footprint — a fixed budget admits proportionally more concurrent splits.
"""

from __future__ import annotations

import logging
import os
import time
import weakref
from collections import OrderedDict

from ..common import sync
from ..common.clock import monotonic as _seam_monotonic
from ..common.deadline import DeadlineExceeded, current_deadline
from ..observability import flight
from ..observability.metrics import SEARCH_SHED_TOTAL
from ..observability.profile import PHASE_ADMISSION_WAIT, current_profile
from ..tenancy.context import effective_tenant
from ..tenancy.drr import DrrScheduler
from ..tenancy.overload import OVERLOAD, OverloadShed
from ..tenancy.registry import GLOBAL_TENANCY

logger = logging.getLogger(__name__)

DEFAULT_BUDGET_BYTES = int(os.environ.get("QW_HBM_BUDGET_BYTES", 8 << 30))


class HbmBudget:
    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES):
        self.budget = budget_bytes
        self._cond = sync.condition(name="HbmBudget._cond")
        sync.register_shared(self, "HbmBudget")
        self._pinned = 0
        self._pin_counts: dict[int, int] = {}  # id(owner) -> in-flight count
        # weighted deficit-round-robin admission order across tenants;
        # guarded by self._cond's lock (the scheduler itself is lock-free)
        self._drr = DrrScheduler()
        # id(reader) -> [resident_bytes, weakref(reader)]
        self._resident: "OrderedDict[int, list]" = OrderedDict()
        self._resident_bytes = 0

    # ------------------------------------------------------------------
    def admit(self, owner, new_bytes: int,
              timeout_secs: float = 120.0) -> int:
        """Block until `new_bytes` fit; returns the admitted (pinned) byte
        count. Grant order is weighted deficit-round-robin across the
        ambient tenant's sub-queue (FIFO within a tenant; see module
        docstring). Evicts idle readers' resident device arrays LRU to
        make room.

        Load shedding: a query whose ambient deadline has already passed —
        or passes while it queues — is rejected with `DeadlineExceeded`
        instead of occupying a ticket; its caller has no time left to use
        the admission anyway. Under sustained overload the controller
        additionally sheds low-priority tenants up front (`OverloadShed`),
        and a tenant over its staged-bytes/s bucket is rejected with
        `TenantRateLimited` before it queues."""
        query_deadline = current_deadline()
        profile = current_profile()
        tenant = effective_tenant()
        if query_deadline is not None and query_deadline.expired:
            SEARCH_SHED_TOTAL.inc(stage="admission")
            flight.emit("admission.shed", attrs={"stage": "deadline"})
            if profile is not None:
                profile.mark_partial("shed: HBM admission")
            raise DeadlineExceeded("HBM admission")
        if new_bytes <= 0:
            # zero-byte admission still PINS the owner: its cached device
            # arrays are in use and must not be evicted mid-query
            with self._cond:
                self._pin_counts[id(owner)] = \
                    self._pin_counts.get(id(owner), 0) + 1
            return 0
        if OVERLOAD.should_shed(tenant.priority):
            SEARCH_SHED_TOTAL.inc(stage="overload_admission")
            flight.emit("admission.shed",
                        attrs={"stage": "overload",
                               "priority": tenant.priority})
            GLOBAL_TENANCY.note_shed(tenant.tenant_id, stage="admission")
            if profile is not None:
                profile.mark_partial("shed: overload (admission)")
            raise OverloadShed("admission", OVERLOAD.retry_after_secs())
        # staged-bytes/s pacing: charged before queueing so a flooding
        # tenant is bounced while its bytes are still hypothetical
        GLOBAL_TENANCY.charge_staged_bytes(tenant, new_bytes)
        if query_deadline is not None:
            timeout_secs = min(timeout_secs,
                               query_deadline.clamp(timeout_secs))
        deadline = time.monotonic() + timeout_secs
        t_admit = time.monotonic()
        # seam twin of t_admit: the flight event's wait must be virtual
        # time under DST (byte-identical artifact tails), real time live
        ft_admit = _seam_monotonic()
        try:
            with self._cond:
                ticket = self._drr.enqueue(tenant.tenant_id, tenant.weight,
                                           new_bytes)
                try:
                    while not (self._drr.head() is ticket
                               and (self._pinned == 0
                                    or self._pinned + new_bytes
                                    <= self.budget)):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            if (query_deadline is not None
                                    and query_deadline.expired):
                                SEARCH_SHED_TOTAL.inc(stage="admission")
                                raise DeadlineExceeded(
                                    "HBM admission queue wait")
                            raise TimeoutError(
                                f"HBM admission timed out: need {new_bytes} "
                                f"bytes, {self._pinned} pinned of "
                                f"{self.budget}")
                        self._cond.wait(remaining)
                except BaseException:
                    self._drr.remove(ticket, served=False)
                    self._cond.notify_all()  # a new head may now be grantable
                    raise
                self._drr.remove(ticket, served=True)
                self._cond.notify_all()
                sync.note_write(self, "pinned")
                self._pinned += new_bytes
                self._pin_counts[id(owner)] = \
                    self._pin_counts.get(id(owner), 0) + 1
                self._evict_locked()
                if new_bytes > self.budget:
                    logger.warning(
                        "query needs %d bytes against a %d-byte HBM budget; "
                        "admitted alone", new_bytes, self.budget)
        except BaseException:
            wait = time.monotonic() - t_admit
            OVERLOAD.note_wait(wait)
            if profile is not None:
                # shed while queued: the partial wait is still reported
                profile.record_phase(
                    PHASE_ADMISSION_WAIT, wait,
                    start=t_admit, bytes=new_bytes, aborted=True)
                profile.mark_partial("shed: HBM admission queue wait")
            raise
        wait = time.monotonic() - t_admit
        OVERLOAD.note_wait(wait)
        GLOBAL_TENANCY.note_admission_wait(tenant.tenant_id, wait)
        GLOBAL_TENANCY.note_staged_bytes(tenant.tenant_id, new_bytes)
        if flight.recording():
            # the DRR grant: this query reached its tenant sub-queue head
            # and its bytes fit the budget
            flight.emit("admission.grant", attrs={
                "bytes": new_bytes,
                "wait_ms": round((_seam_monotonic() - ft_admit) * 1000.0, 3)})
        if profile is not None:
            profile.record_phase(PHASE_ADMISSION_WAIT,
                                 wait, start=t_admit,
                                 bytes=new_bytes)
        return new_bytes

    def release(self, owner, admitted_bytes: int,
                to_resident: bool = True) -> None:
        """Pins → residency when the owner keeps a device-array cache
        (split readers); transient owners (batches) just unpin — their
        arrays die with them and must not count as resident.
        `to_resident=False` unpins without residency (failed transfer:
        nothing actually landed in HBM). Zero-byte releases still unpin
        the owner (matching zero-byte admissions)."""
        with self._cond:
            sync.note_write(self, "pinned")
            if admitted_bytes <= 0:
                count = self._pin_counts.get(id(owner), 1) - 1
                if count <= 0:
                    self._pin_counts.pop(id(owner), None)
                else:
                    self._pin_counts[id(owner)] = count
                self._cond.notify_all()
                return
            self._pinned -= admitted_bytes
            count = self._pin_counts.get(id(owner), 1) - 1
            if count <= 0:
                self._pin_counts.pop(id(owner), None)
            else:
                self._pin_counts[id(owner)] = count
            if to_resident and getattr(owner, "_device_array_cache",
                                       None) is not None:
                oid = id(owner)
                entry = self._resident.pop(oid, None)
                if entry is None:
                    entry = [0, weakref.ref(
                        owner, lambda _ref, oid=oid: self._drop(oid))]
                entry[0] += admitted_bytes
                self._resident[oid] = entry
                self._resident_bytes += admitted_bytes
            self._cond.notify_all()

    def _drop(self, oid: int) -> None:
        """weakref callback: a reader was garbage-collected; its device
        arrays are gone, so its residency must not cause evictions."""
        with self._cond:
            entry = self._resident.pop(oid, None)
            if entry is not None:
                self._resident_bytes -= entry[0]
                self._cond.notify_all()

    def _evict_locked(self) -> None:
        while (self._resident_bytes + self._pinned > self.budget
               and self._resident):
            victim_id = next(
                (rid for rid in self._resident
                 if self._pin_counts.get(rid, 0) == 0), None)
            if victim_id is None:
                return  # every resident reader has in-flight queries
            nbytes, ref = self._resident.pop(victim_id)
            self._resident_bytes -= nbytes
            reader = ref()
            if reader is not None:
                # dropping the refs releases HBM once no kernel holds them
                cache = getattr(reader, "_device_array_cache", None)
                if cache:
                    cache.clear()
                logger.info("evicted %d resident device bytes (LRU)", nbytes)

    # --- observability ------------------------------------------------
    def stats(self) -> dict:
        with self._cond:
            return {"budget": self.budget, "pinned": self._pinned,
                    "resident": self._resident_bytes,
                    "waiting_by_tenant": self._drr.waiting_by_tenant()}
