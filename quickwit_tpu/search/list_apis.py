"""list_terms / list_fields.

Roles of the reference's `list_terms.rs` and `list_fields/mod.rs`: enumerate
index terms of a field across splits (range-bounded, limited) and describe
the queryable fields of one or more indexes.
"""

from __future__ import annotations

import heapq
from typing import Any, Optional

from ..metastore.base import ListSplitsQuery, Metastore
from ..models.split_metadata import SplitState
from .service import SearcherContext
from .models import SplitIdAndFooter


def leaf_list_terms(context: SearcherContext, splits: list[SplitIdAndFooter],
                    field: str, start_key: Optional[str] = None,
                    end_key: Optional[str] = None, max_terms: int = 100
                    ) -> list[str]:
    """Merged sorted unique terms of `field` across the given splits."""
    iterators = []
    for split in splits:
        reader = context.reader(split)
        term_dict = reader.term_dict(field)
        if term_dict is None:
            continue
        iterators.append(
            (term for term, _df in term_dict.iter_terms(start=start_key)))
    out: list[str] = []
    for term in heapq.merge(*iterators):
        if end_key is not None and term >= end_key:
            break
        if out and out[-1] == term:
            continue
        out.append(term)
        if len(out) >= max_terms:
            break
    return out


def root_list_terms(metastore: Metastore, context: SearcherContext,
                    index_id: str, field: str,
                    start_key: Optional[str] = None,
                    end_key: Optional[str] = None,
                    max_terms: int = 100) -> list[str]:
    metadata = metastore.index_metadata(index_id)
    splits = metastore.list_splits(ListSplitsQuery(
        index_uids=[metadata.index_uid], states=[SplitState.PUBLISHED]))
    offsets = [SplitIdAndFooter(split_id=s.metadata.split_id,
                                storage_uri=metadata.index_config.index_uri)
               for s in splits]
    return leaf_list_terms(context, offsets, field, start_key, end_key, max_terms)


def list_fields(metastore: Metastore, index_patterns: list[str]) -> list[dict[str, Any]]:
    """Queryable fields across matching indexes (reference list_fields)."""
    import fnmatch
    out: dict[str, dict[str, Any]] = {}
    for metadata in metastore.list_indexes():
        if not any(fnmatch.fnmatch(metadata.index_id, p) for p in index_patterns):
            continue
        for fm in metadata.index_config.doc_mapper.field_mappings:
            entry = out.setdefault(fm.name, {
                "field_name": fm.name, "field_type": fm.type.value,
                "searchable": fm.indexed, "aggregatable": fm.fast,
                "index_ids": []})
            entry["index_ids"].append(metadata.index_id)
    return sorted(out.values(), key=lambda e: e["field_name"])
