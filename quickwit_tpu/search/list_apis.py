"""list_terms / list_fields.

Roles of the reference's `list_terms.rs` and `list_fields/mod.rs`: enumerate
index terms of a field across splits (range-bounded, limited) and describe
the queryable fields of one or more indexes.
"""

from __future__ import annotations

import heapq
from typing import Any, Optional

from ..metastore.base import ListSplitsQuery, Metastore
from ..models.split_metadata import SplitState
from .service import SearcherContext
from .models import SplitIdAndFooter


def leaf_list_terms(context: SearcherContext, splits: list[SplitIdAndFooter],
                    field: str, start_key: Optional[str] = None,
                    end_key: Optional[str] = None, max_terms: int = 100
                    ) -> list[str]:
    """Merged sorted unique terms of `field` across the given splits."""
    iterators = []
    for split in splits:
        reader = context.reader(split)
        term_dict = reader.term_dict(field)
        if term_dict is None:
            continue
        iterators.append(
            (term for term, _df in term_dict.iter_terms(start=start_key)))
    out: list[str] = []
    for term in heapq.merge(*iterators):
        if end_key is not None and term >= end_key:
            break
        if out and out[-1] == term:
            continue
        out.append(term)
        if len(out) >= max_terms:
            break
    return out


def root_list_terms(metastore: Metastore, context: SearcherContext,
                    index_id: str, field: str,
                    start_key: Optional[str] = None,
                    end_key: Optional[str] = None,
                    max_terms: int = 100) -> list[str]:
    metadata = metastore.index_metadata(index_id)
    splits = metastore.list_splits(ListSplitsQuery(
        index_uids=[metadata.index_uid], states=[SplitState.PUBLISHED]))
    offsets = [SplitIdAndFooter(split_id=s.metadata.split_id,
                                storage_uri=metadata.index_config.index_uri)
               for s in splits]
    return leaf_list_terms(context, offsets, field, start_key, end_key, max_terms)


# concrete field type → list-fields type class (reference ListFieldsType;
# "str" expands to keyword+text on the ES field-caps surface)
_TYPE_CLASS = {"text": "str", "i64": "long", "u64": "long", "f64": "double",
               "bool": "boolean", "datetime": "date", "ip": "ip",
               "bytes": "binary"}
# dynamic column type → the value class it makes aggregatable
_COL_CLASS = {"i64": "long", "u64": "long", "f64": "double",
              "bool": "boolean", "text": "str"}


def list_field_entries(metastore: Metastore, context: SearcherContext,
                       index_patterns: list[str],
                       field_patterns: Optional[list[str]] = None,
                       start_timestamp: Optional[int] = None,
                       end_timestamp: Optional[int] = None,
                       filter_ast: Any = None
                       ) -> list[dict[str, Any]]:
    """Per-(field, type-class) entries aggregated over the PER-SPLIT field
    registries (reference `list_fields/mod.rs`: leaf split-fields metadata
    merged at the root). Dynamic fields carry their observed value
    classes; a class is aggregatable only where the split's coerced
    column is of that class (mixed long+double in one split ⇒ the f64
    column makes `double` aggregatable and `long` searchable-only).
    Timestamps (seconds) prune splits by time range before reading.
    `filter_ast` (ES index_filter) prunes each index's splits via the
    conjunctive terms on THAT index's own tag fields — tags extracted
    per index, never leaking one index's tag semantics onto another."""
    import fnmatch
    entries: dict[tuple[str, str], dict[str, Any]] = {}
    for metadata in metastore.list_indexes():
        if not any(fnmatch.fnmatch(metadata.index_id, p.rstrip(","))
                   for p in index_patterns):
            continue
        required_tags: Optional[set] = None
        if filter_ast is not None:
            from .root import extract_required_tags
            tag_fields = tuple(
                metadata.index_config.doc_mapper.tag_fields)
            required_tags = (extract_required_tags(filter_ast, tag_fields)
                             or None)
        query = ListSplitsQuery(index_uids=[metadata.index_uid],
                                states=[SplitState.PUBLISHED])
        for split in metastore.list_splits(query):
            sm = split.metadata
            if (start_timestamp is not None
                    and sm.time_range_end is not None
                    and sm.time_range_end // 1_000_000 < start_timestamp):
                continue
            if (end_timestamp is not None
                    and sm.time_range_start is not None
                    and sm.time_range_start // 1_000_000 >= end_timestamp):
                continue
            if not sm.matches_tags(required_tags):
                continue
            reader = context.reader(SplitIdAndFooter(
                split_id=sm.split_id,
                storage_uri=metadata.index_config.index_uri))
            for name, meta in reader.footer.fields.items():
                if name.startswith("_"):
                    continue  # synthetic fields (_doc_length) stay hidden
                if field_patterns and not any(
                        fnmatch.fnmatch(name, p) for p in field_patterns):
                    continue
                searchable = bool(meta.get("indexed"))
                if meta.get("dynamic"):
                    coerced = _COL_CLASS.get(meta.get("col_type", ""))
                    for cls in meta.get("value_classes", ()):
                        # a fast-only dynamic field is still queryable
                        # through its coerced column (plan.py
                        # _fast_only_term / numeric-range routing)
                        _merge_entry(entries, name, cls, metadata.index_id,
                                     searchable or cls == coerced,
                                     aggregatable=(cls == coerced))
                else:
                    cls = _TYPE_CLASS.get(meta.get("type", ""))
                    if cls is None:
                        continue
                    _merge_entry(entries, name, cls, metadata.index_id,
                                 searchable or meta.get("fast", False),
                                 aggregatable=bool(meta.get("fast")))
    return [entries[key] for key in sorted(entries)]


def _merge_entry(entries: dict, name: str, cls: str, index_id: str,
                 searchable: bool, aggregatable: bool) -> None:
    entry = entries.setdefault((name, cls), {
        "field_name": name, "type_class": cls, "searchable": False,
        "aggregatable": False, "index_ids": []})
    entry["searchable"] = entry["searchable"] or searchable
    entry["aggregatable"] = entry["aggregatable"] or aggregatable
    if index_id not in entry["index_ids"]:
        entry["index_ids"].append(index_id)


def list_fields(metastore: Metastore, index_patterns: list[str],
                context: Optional[SearcherContext] = None
                ) -> list[dict[str, Any]]:
    """Queryable fields across matching indexes (reference list_fields).

    With a searcher context, fields come from the per-split registries
    (dynamic fields included); without one, from the doc mappings."""
    import fnmatch
    if context is not None:
        out: dict[str, dict[str, Any]] = {}
        for e in list_field_entries(metastore, context, index_patterns):
            entry = out.setdefault(e["field_name"], {
                "field_name": e["field_name"], "field_type": e["type_class"],
                "searchable": False, "aggregatable": False, "index_ids": []})
            entry["searchable"] = entry["searchable"] or e["searchable"]
            entry["aggregatable"] = (entry["aggregatable"]
                                     or e["aggregatable"])
            for index_id in e["index_ids"]:
                if index_id not in entry["index_ids"]:
                    entry["index_ids"].append(index_id)
        return sorted(out.values(), key=lambda e: e["field_name"])
    out = {}
    for metadata in metastore.list_indexes():
        if not any(fnmatch.fnmatch(metadata.index_id, p) for p in index_patterns):
            continue
        for fm in metadata.index_config.doc_mapper.field_mappings:
            entry = out.setdefault(fm.name, {
                "field_name": fm.name, "field_type": fm.type.value,
                "searchable": fm.indexed, "aggregatable": fm.fast,
                "index_ids": []})
            entry["index_ids"].append(metadata.index_id)
    return sorted(out.values(), key=lambda e: e["field_name"])
