"""Tier A — predicate-mask cache.

Memoizes the evaluated filter bitmask of one split, keyed
`(split_id, canonical_filter_digest)` (search/cache.py): two dashboard
panels sharing one filter but differing in top-K / sort / agg shape reuse
the SAME mask, so the warm panel stages zero predicate columns and skips
kernel filter evaluation entirely — the lowering swaps the whole query
root for a `PMaskRef` node over the packed mask (search/plan.py), and the
executor unpacks bits instead of walking postings (search/executor.py).

The mask is stored np.packbits-packed (1 bit/doc, big-endian — the device
pack/unpack in executor.py uses the same bit order). Host residency lives
here, byte-bounded and tenant-partitioned (Tier C); DEVICE residency needs
no code of its own: the packed mask rides `plan.array_keys` under
`mask.<digest>`, so `warmup_device_arrays` + `ResidentColumnStore` keep it
in HBM for warm splits with `HbmBudget` accounting, exactly like any
column.

Soundness: splits are immutable, and the digest covers everything that
decides WHICH docs match (query AST + rebased time bounds). Fills are
gated on `plan.count_override is None` — an impact-prefix-truncated plan
(format v3) never saw the posting tail, so its mask is incomplete.

Chaos points (common/faults.py):
- `cache.mask_corrupt` fires on a hit: the entry is treated as corrupt,
  dropped, and the query degrades to recompute (a miss), never fails.
- `cache.evict` fires on a put: the calling tenant's partition is
  force-cleared first (eviction-storm simulation); the put still lands.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common.faults import InjectedFault
from ..observability.metrics import (
    MASK_CACHE_EVICTED_BYTES_TOTAL, MASK_CACHE_HITS_TOTAL,
    MASK_CACHE_MISSES_TOTAL,
)
from .tenant_cache import TenantPartitionedCache


def packed_mask_nbytes(num_docs_padded: int) -> int:
    return (num_docs_padded + 7) // 8


class PredicateMaskCache:
    def __init__(self, capacity_bytes: int = 32 << 20, fault_injector=None):
        self._cache = TenantPartitionedCache(
            capacity_bytes,
            on_evict=MASK_CACHE_EVICTED_BYTES_TOTAL.inc,
            tier="predicate_mask")
        self.fault_injector = fault_injector

    @staticmethod
    def _key(split_id: str, digest: str) -> str:
        return f"{split_id}:{digest}"

    def get(self, split_id: str, digest: str,
            expected_nbytes: int) -> Optional[np.ndarray]:
        """The packed uint8 mask, or None. `expected_nbytes` pins the entry
        to the split's padded doc space — a mismatch (impossible for an
        immutable split, conceivable after a corruption fault) degrades to
        a miss instead of feeding the kernel a wrong-shaped array."""
        key = self._key(split_id, digest)
        raw = self._cache.get(key)
        if raw is not None and self.fault_injector is not None:
            try:
                self.fault_injector.perturb("cache.mask_corrupt")
            except InjectedFault:
                # injected corruption: drop the entry and recompute — the
                # triggering query must never fail or return wrong results
                self._cache.delete(key)
                raw = None
        if raw is None or len(raw) != expected_nbytes:
            MASK_CACHE_MISSES_TOTAL.inc()
            return None
        MASK_CACHE_HITS_TOTAL.inc()
        return np.frombuffer(raw, dtype=np.uint8)

    def put(self, split_id: str, digest: str, packed: np.ndarray) -> None:
        if self.fault_injector is not None:
            try:
                self.fault_injector.perturb("cache.evict")
            except InjectedFault:
                # injected eviction storm: this tenant's partition is
                # force-cleared (counted as evicted bytes); absorbing the
                # fault here keeps the triggering query unharmed
                self._cache.clear_current_partition()
        self._cache.put(self._key(split_id, digest),
                        np.ascontiguousarray(packed, dtype=np.uint8)
                        .tobytes())

    @property
    def stats(self) -> dict:
        return self._cache.stats
