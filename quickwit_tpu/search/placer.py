"""Search job placement.

Role of the reference's `SearchJobPlacer` (`search_job_placer.rs:40,306`):
assign per-split search jobs to searcher nodes by rendezvous hashing (cache
affinity: the same split lands on the same node across queries) with cost
balancing — a node already loaded past the mean cost spills its next splits
to the next-best node.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Sequence

from ..common.rendezvous import sort_by_rendezvous_hash


@dataclass(frozen=True)
class SearchJob:
    split_id: str
    cost: int = 1  # reference: derived from split doc count


def place_jobs(jobs: Sequence[SearchJob], nodes: Sequence[str],
               max_load_factor: float = 1.2) -> dict[str, list[SearchJob]]:
    """split jobs → node assignments; deterministic given (jobs, nodes)."""
    if not nodes:
        raise ValueError("no searcher nodes available")
    total_cost = sum(job.cost for job in jobs) or 1
    capacity = (total_cost / len(nodes)) * max_load_factor
    load: dict[str, int] = {node: 0 for node in nodes}
    assignment: dict[str, list[SearchJob]] = {node: [] for node in nodes}
    # place big jobs first so spill decisions happen while there is room
    for job in sorted(jobs, key=lambda j: (-j.cost, j.split_id)):
        preference = sort_by_rendezvous_hash(job.split_id, nodes)
        chosen = None
        for node in preference:
            if load[node] + job.cost <= capacity:
                chosen = node
                break
        if chosen is None:  # everyone is "full": least-loaded wins
            chosen = min(preference, key=lambda n: load[n])
        load[chosen] += job.cost
        assignment[chosen].append(job)
    return {node: jobs_ for node, jobs_ in assignment.items() if jobs_}


def nodes_for_split(split_id: str, nodes: Sequence[str]) -> list[str]:
    """Preference-ordered nodes for one split (retry order,
    reference `ClusterClient` retry policy)."""
    return sort_by_rendezvous_hash(split_id, nodes)
