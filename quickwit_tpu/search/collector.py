"""Incremental merge of leaf responses + aggregation finalization.

Role of the reference's `IncrementalCollector` (`collector.rs:1195`) and
root-side `merge_fruits` / `finalize_aggregation` (`root.rs:841,1120`): leaf
responses merge associatively — hit lists by sort key, aggregation states by
bucket key — so the same code runs the segment→split→node→root merge tree at
any level.

Internal hit ordering convention: `PartialHit.sort_value` is float64
"higher is better"; ties break by (split_id, doc_id) ascending, matching the
reference's doc-address tie-break.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..ops.aggs import (PCTL_NUM_BUCKETS, hll_estimate, merge_stats_states,
                        sketch_quantiles)
from ..query.aggregations import DEFAULT_PERCENTS
from .hostdecode import host_array, host_float, host_int, host_list
from .models import LeafSearchResponse, PartialHit


def _hit_order_key(h: PartialHit):
    return (-h.sort_value, -h.sort_value2, h.split_id, h.doc_id)


class _StrKey:
    """Order wrapper for text-sort merging: compares the DECODED term
    strings (per-split ordinals are not cross-split comparable); missing
    values (None) sort last in both directions (ES `missing: _last`)."""

    __slots__ = ("value", "desc")

    def __init__(self, value, desc: bool):
        self.value = value
        self.desc = desc

    def __lt__(self, other: "_StrKey") -> bool:
        a, b = self.value, other.value
        if a is None:
            return False  # None never precedes anything
        if b is None:
            return True
        return a > b if self.desc else a < b

    def __eq__(self, other) -> bool:
        return self.value == other.value


class IncrementalCollector:
    def __init__(self, max_hits: int, start_offset: int = 0,
                 search_after: Optional[tuple] = None,
                 string_sort: Optional[str] = None,
                 string_search_after: Optional[tuple] = None):
        self.max_hits = max_hits
        self.start_offset = start_offset
        self.search_after = search_after  # (sort_value, split_id, doc_id) internal
        # text-sort marker: (raw_term|None, split|None, doc) — filtered on
        # the DECODED strings (per-split ordinals are not comparable)
        self.string_search_after = string_search_after
        # "asc" | "desc" when the primary sort is a text field: merge by
        # raw_sort_value (term string) instead of the split-local float key
        self.string_sort = string_sort
        self.num_hits = 0
        self.failed_splits: list = []
        self.num_attempted_splits = 0
        self.num_successful_splits = 0
        self._hits: list[PartialHit] = []
        self._agg_states: dict[str, Any] = {}
        self.resource_stats: dict[str, float] = {}

    # ------------------------------------------------------------------
    def add_leaf_response(self, leaf: LeafSearchResponse) -> None:
        self.num_hits += leaf.num_hits
        self.failed_splits.extend(leaf.failed_splits)
        self.num_attempted_splits += leaf.num_attempted_splits
        self.num_successful_splits += leaf.num_successful_splits
        for key, value in leaf.resource_stats.items():
            self.resource_stats[key] = self.resource_stats.get(key, 0) + value
        hits = leaf.partial_hits
        if self.string_search_after is not None and self.string_sort:
            raw, m_split, m_doc = self.string_search_after
            desc = self.string_sort == "desc"
            marker = (_StrKey(raw, desc), m_split or "", m_doc)
            if m_split is None:
                hits = [h for h in hits
                        if _StrKey(raw, desc) < _StrKey(h.raw_sort_value,
                                                        desc)]
            else:
                hits = [h for h in hits
                        if marker < (_StrKey(h.raw_sort_value, desc),
                                     h.split_id, h.doc_id)]
        if self.search_after is not None:
            sa_v, sa_v2, sa_split, sa_doc = self.search_after
            if sa_split is None:
                # value-only ES marker: strictly after the value; docs
                # tying the marker on every key are skipped
                hits = [h for h in hits
                        if (-h.sort_value, -h.sort_value2) > (-sa_v, -sa_v2)]
            else:
                hits = [h for h in hits
                        if (-h.sort_value, -h.sort_value2, h.split_id,
                            h.doc_id) > (-sa_v, -sa_v2, sa_split, sa_doc)]
        self._hits.extend(hits)
        keep = self.start_offset + self.max_hits
        if len(self._hits) > 4 * max(keep, 1):
            self._hits.sort(key=self._order_key)
            del self._hits[keep:]
        for name, state in leaf.intermediate_aggs.items():
            self._merge_agg(name, state)

    # ------------------------------------------------------------------
    def _merge_agg(self, name: str, state: dict[str, Any]) -> None:
        current = self._agg_states.get(name)
        if current is None:
            self._agg_states[name] = _copy_state(state)
            return
        kind = state["kind"]
        if kind in ("date_histogram", "histogram"):
            _merge_histogram(current, state)
        elif kind == "terms":
            _merge_terms(current, state)
        elif kind == "range":
            _merge_bucket_maps(current["bucket_map"], _range_to_map(state))
        elif kind == "composite":
            bucket_map = current["bucket_map"]
            for key, bucket in bucket_map.items():
                if isinstance(bucket, int):  # pre-metrics wire shape
                    bucket_map[key] = {"doc_count": bucket, "metrics": {}}
            # buckets (and their nested sub_maps) merge by key tuple with
            # the same machinery every other bucket kind uses
            _merge_bucket_maps(bucket_map, dict(_composite_pairs(state)))
        elif kind == "percentiles":
            current["sketch"] = current["sketch"] + state["sketch"]
        elif kind == "cardinality":
            # HLL registers merge by elementwise max
            current["hll"] = np.maximum(current["hll"], state["hll"])
        else:  # metric state [count,sum,sum_sq,min,max]
            current["state"] = merge_stats_states(current["state"],
                                                  state["state"])

    # ------------------------------------------------------------------
    def _order_key(self, h: PartialHit):
        if self.string_sort is not None:
            return (_StrKey(h.raw_sort_value, self.string_sort == "desc"),
                    h.split_id, h.doc_id)
        return _hit_order_key(h)

    def partial_hits(self) -> list[PartialHit]:
        self._hits.sort(key=self._order_key)
        return self._hits[self.start_offset: self.start_offset + self.max_hits]

    def sort_value_threshold(self) -> Optional[float]:
        """Current Kth internal sort value (higher-is-better), or None when
        the top-K window is not yet full — the dynamic-pruning threshold
        (reference: `CanSplitDoBetter`, leaf.rs:1279).

        A pending split whose best achievable internal key is STRICTLY below
        this value cannot displace any collected hit: an equal primary key
        could still win on the (sort_value2, split_id, doc_id) tie-break, so
        callers must prune on `best < threshold`, never `<=`. Not meaningful
        for text sorts (split-local ordinals aren't comparable to time
        ranges or score bounds) — returns None there.
        """
        if self.string_sort is not None or self.max_hits <= 0:
            return None
        keep = self.start_offset + self.max_hits
        if len(self._hits) < keep:
            return None
        self._hits.sort(key=self._order_key)
        window = self._hits[self.start_offset: keep]
        if len(window) < self.max_hits:
            return None
        return window[-1].sort_value

    def to_leaf_response(self) -> LeafSearchResponse:
        """Re-emit as a leaf response (for tree-merging at the node level)."""
        self._hits.sort(key=self._order_key)
        return LeafSearchResponse(
            num_hits=self.num_hits,
            partial_hits=self._hits[: self.start_offset + self.max_hits],
            failed_splits=self.failed_splits,
            num_attempted_splits=self.num_attempted_splits,
            num_successful_splits=self.num_successful_splits,
            intermediate_aggs=self._agg_states,
            resource_stats=self.resource_stats,
        )

    def aggregation_states(self) -> dict[str, Any]:
        return self._agg_states


# --------------------------------------------------------------------------
# merge helpers: bucket states keyed absolutely so per-split origins align

def _copy_state(state: dict[str, Any]) -> dict[str, Any]:
    kind = state["kind"]
    if kind in ("date_histogram", "histogram"):
        copy = dict(state)
        copy["bucket_map"] = _histogram_to_map(state)
        copy.pop("counts", None)
        copy.pop("metrics", None)
        _carry_sub_info(copy, state)
        return copy
    if kind == "terms":
        copy = dict(state)
        copy["bucket_map"] = _terms_to_map(state)
        copy.pop("counts", None)
        copy.pop("metrics", None)
        copy.pop("keys", None)
        _carry_sub_info(copy, state)
        return copy
    if kind == "range":
        copy = dict(state)
        copy["bucket_map"] = _range_to_map(state)
        copy.pop("counts", None)
        copy.pop("metrics", None)
        return copy
    if kind == "composite":
        copy = dict(state)
        copy["bucket_map"] = dict(_composite_pairs(state))
        copy.pop("buckets", None)
        _carry_sub_info(copy, state)
        return copy
    return dict(state)


def _composite_pairs(state: dict[str, Any]):
    """(key_tuple, bucket) pairs from a leaf state ("buckets" list) or an
    already-merged state ("bucket_map") — wire decode turns tuples into
    lists, so keys re-freeze here. Buckets carry {"doc_count", "metrics"}
    (metric accumulators keyed by name)."""
    metric_kinds = state.get("metric_kinds", {})
    if "bucket_map" in state:
        return [(tuple(k) if isinstance(k, list) else k,
                 {"doc_count": b, "metrics": {}} if isinstance(b, int)
                 else b)
                for k, b in state["bucket_map"].items()]
    out = []
    for entry in state["buckets"]:
        values, count = entry[0], entry[1]
        metrics: dict = {}
        if len(entry) > 2:
            for name, accum in entry[2].items():
                acc = _new_metric_acc(metric_kinds.get(name, "avg"))
                acc.update({k: v for k, v in accum.items()
                            if k in ("sum", "count", "min", "max",
                                     "sum_sq")})
                metrics[name] = acc
        bucket = {"doc_count": count, "metrics": metrics}
        if len(entry) > 3 and state.get("subs"):
            # entry[3] is this bucket's run index into the flattened
            # child states: decode its nested children like any other
            # parent bucket kind
            _attach_sub_maps(bucket, state, host_int(entry[3]))
        out.append((tuple(values), bucket))
    return out


def _composite_order_key(key_tuple):
    """ES composite ordering: ascending per source, null first."""
    return tuple((0, "") if v is None else (1, v) for v in key_tuple)


def _finalize_composite(state: dict[str, Any]) -> dict[str, Any]:
    bucket_map = (state["bucket_map"] if "bucket_map" in state
                  else dict(_composite_pairs(state)))
    if "sub_infos" not in state and state.get("subs"):
        # finalizing a raw (never-merged) leaf state directly
        state = {**state,
                 "sub_infos": [_sub_info_of(s) for s in state["subs"]]}
    ordered = sorted(bucket_map.items(),
                     key=lambda kv: _composite_order_key(kv[0]))
    ordered = ordered[: state["size"]]
    sources = state["sources"]
    buckets = []
    for key_tuple, bucket in ordered:
        if isinstance(bucket, int):  # pre-metrics wire shape
            bucket = {"doc_count": bucket, "metrics": {}}
        key: dict[str, Any] = {}
        for value, info in zip(key_tuple, sources):
            if info["kind"] == "date_histogram" and value is not None:
                value = host_int(value) // 1000  # micros → ES integer ms
            key[info["name"]] = value
        entry = {"key": key, "doc_count": host_int(bucket["doc_count"])}
        for mname, acc in bucket["metrics"].items():
            entry[mname] = _finalize_metric(acc)
        for child_info in (state.get("sub_infos") or ()):
            entry[child_info["name"]] = _finalize_bucket_map(
                bucket.get("sub_maps", {}).get(child_info["name"], {}),
                child_info, child_info.get("sub_infos"))
        buckets.append(entry)
    out: dict[str, Any] = {"buckets": buckets}
    if buckets:
        out["after_key"] = buckets[-1]["key"]
    return out


def _range_to_map(state: dict[str, Any]) -> dict:
    """Range buckets keyed by their static range index (all emitted)."""
    if "bucket_map" in state:  # already-merged state (tree merging at root)
        return _copy_bucket_map(state["bucket_map"])
    counts = host_array(state["counts"])
    out = {}
    for i in range(len(state["ranges"])):
        acc_metrics = {}
        for name, arrays in state.get("metrics", {}).items():
            met_kind = state["metric_kinds"][name]
            acc = _new_metric_acc(
                met_kind, state.get("metric_percents", {}).get(name),
                state.get("metric_keyed", {}).get(name, True))
            _acc_metric(acc, arrays, i)
            acc_metrics[name] = acc
        out[i] = {"doc_count": host_int(counts[i]) if i < len(counts) else 0,
                  "metrics": acc_metrics}
    return out


def _carry_sub_info(copy: dict, state: dict) -> None:
    """Finalization parameters of the nested children, all levels."""
    subs = state.get("subs")
    copy.pop("subs", None)
    if subs:
        copy["sub_infos"] = [_sub_info_of(sub) for sub in subs]


def _sub_info_of(sub: dict) -> dict:
    info = {k: sub.get(k) for k in
            ("name", "kind", "interval", "origin", "min_doc_count",
             "size", "order_desc", "order_target", "extended_bounds",
             "offset")}
    if sub.get("subs"):
        info["sub_infos"] = [_sub_info_of(s) for s in sub["subs"]]
    return info


def _new_metric_acc(kind: str, percents=None, keyed: bool = True) -> dict[str, Any]:
    return {"sum": 0.0, "count": 0, "min": np.inf, "max": -np.inf, "sum_sq": 0.0,
            "kind": kind, "sketch": None, "hll": None, "percents": percents,
            "keyed": keyed}


def _acc_metric(acc: dict[str, Any], arrays: dict[str, np.ndarray], i: int) -> None:
    if "sum" in arrays:
        acc["sum"] += host_float(arrays["sum"][i])
    if "count" in arrays:
        acc["count"] += host_int(arrays["count"][i])
    if "min" in arrays:
        acc["min"] = min(acc["min"], host_float(arrays["min"][i]))
    if "max" in arrays:
        acc["max"] = max(acc["max"], host_float(arrays["max"][i]))
    if "sum_sq" in arrays:
        acc["sum_sq"] += host_float(arrays["sum_sq"][i])
    if "sketch" in arrays:
        row = host_array(arrays["sketch"][i])
        # non-inplace add: accs are shallow-copied by _copy_bucket_map
        acc["sketch"] = row if acc["sketch"] is None else acc["sketch"] + row
    if "hll" in arrays:
        row = host_array(arrays["hll"][i])
        # HLL registers merge by elementwise max (non-inplace, as above)
        acc["hll"] = row if acc.get("hll") is None \
            else np.maximum(acc["hll"], row)


def _copy_bucket_map(bucket_map: dict) -> dict:
    return {key: {"doc_count": b["doc_count"],
                  "metrics": {m: dict(acc) for m, acc in b["metrics"].items()},
                  **({"sub_maps": {n: _copy_bucket_map(m)
                                   for n, m in b["sub_maps"].items()}}
                     if "sub_maps" in b else {})}
            for key, b in bucket_map.items()}


def _sub_key(sub: dict, j: int):
    if sub["kind"] == "terms":
        keys = sub["keys"]
        return keys[j] if j < len(keys) else None
    return sub["origin"] + j * sub["interval"]


def _attach_sub_maps(bucket: dict, state: dict, parent_flat: int) -> None:
    """Nested children of one parent bucket, decoded recursively from the
    flattened mixed-radix device states (child flat index =
    parent_flat * child_nb + child_local)."""
    subs = state.get("subs")
    if not subs:
        return
    sub_maps: dict = {}
    for sub in subs:
        nb = sub["nb"]
        base = parent_flat * nb
        counts = sub["counts"]
        metric_kinds = sub.get("metric_kinds", {})
        metric_percents = sub.get("metric_percents", {})
        metric_keyed = sub.get("metric_keyed", {})
        sub_map: dict = {}
        for j in range(nb):
            flat = base + j
            if flat >= len(counts) or counts[flat] == 0:
                continue
            key = _sub_key(sub, j)
            if key is None:
                continue
            child = {"doc_count": host_int(counts[flat]), "metrics": {}}
            for mname, arrays in sub.get("metrics", {}).items():
                acc = _new_metric_acc(metric_kinds.get(mname, "avg"),
                                      metric_percents.get(mname),
                                      metric_keyed.get(mname, True))
                _acc_metric(acc, arrays, flat)
                child["metrics"][mname] = acc
            _attach_sub_maps(child, sub, flat)
            sub_map[key] = child
        sub_maps[sub["name"]] = sub_map
    bucket["sub_maps"] = sub_maps


def _histogram_to_map(state: dict[str, Any]) -> dict[float, dict[str, Any]]:
    if "bucket_map" in state:  # already-merged state (tree merging at root)
        return _copy_bucket_map(state["bucket_map"])
    counts = state["counts"]
    origin, interval = state["origin"], state["interval"]
    out: dict[float, dict[str, Any]] = {}
    nonzero = np.nonzero(counts)[0] if not state.get("extended_bounds") \
        else np.arange(len(counts))
    metric_kinds = state.get("metric_kinds", {})
    metric_percents = state.get("metric_percents", {})
    metric_keyed = state.get("metric_keyed", {})
    for i in host_list(nonzero):
        key = origin + i * interval
        bucket = {"doc_count": host_int(counts[i]), "metrics": {}}
        for mname, arrays in state.get("metrics", {}).items():
            acc = _new_metric_acc(metric_kinds.get(mname, "avg"),
                                  metric_percents.get(mname),
                                  metric_keyed.get(mname, True))
            _acc_metric(acc, arrays, i)
            bucket["metrics"][mname] = acc
        _attach_sub_maps(bucket, state, i)
        out[key] = bucket
    return out


def _terms_to_map(state: dict[str, Any]) -> dict[Any, dict[str, Any]]:
    if "bucket_map" in state:  # already-merged state (tree merging at root)
        return _copy_bucket_map(state["bucket_map"])
    counts = state["counts"]
    keys = state["keys"]
    metric_kinds = state.get("metric_kinds", {})
    metric_percents = state.get("metric_percents", {})
    metric_keyed = state.get("metric_keyed", {})
    out: dict[Any, dict[str, Any]] = {}
    for i in host_list(np.nonzero(counts)[0]):
        if i >= len(keys):
            continue
        bucket = {"doc_count": host_int(counts[i]), "metrics": {}}
        for mname, arrays in state.get("metrics", {}).items():
            acc = _new_metric_acc(metric_kinds.get(mname, "avg"),
                                  metric_percents.get(mname),
                                  metric_keyed.get(mname, True))
            _acc_metric(acc, arrays, i)
            bucket["metrics"][mname] = acc
        _attach_sub_maps(bucket, state, i)
        out[keys[i]] = bucket
    return out


def _merge_bucket_maps(bucket_map: dict, incoming: dict) -> None:
    for key, bucket in incoming.items():
        cur = bucket_map.get(key)
        if cur is None:
            bucket_map[key] = bucket
            continue
        cur["doc_count"] += bucket["doc_count"]
        for mname, acc in bucket["metrics"].items():
            cacc = cur["metrics"].get(mname)
            if cacc is None:
                cur["metrics"][mname] = acc
            else:
                cacc["sum"] += acc["sum"]
                cacc["count"] += acc["count"]
                cacc["min"] = min(cacc["min"], acc["min"])
                cacc["max"] = max(cacc["max"], acc["max"])
                cacc["sum_sq"] += acc["sum_sq"]
                if acc.get("sketch") is not None:
                    cacc["sketch"] = acc["sketch"] \
                        if cacc.get("sketch") is None \
                        else cacc["sketch"] + acc["sketch"]
                if acc.get("hll") is not None:
                    cacc["hll"] = acc["hll"] \
                        if cacc.get("hll") is None \
                        else np.maximum(cacc["hll"], acc["hll"])
        if "sub_maps" in bucket:
            if "sub_maps" not in cur:
                cur["sub_maps"] = bucket["sub_maps"]
            else:
                for name, sub_map in bucket["sub_maps"].items():
                    if name not in cur["sub_maps"]:
                        cur["sub_maps"][name] = sub_map
                    else:
                        _merge_bucket_maps(cur["sub_maps"][name], sub_map)


def _merge_histogram(current: dict[str, Any], state: dict[str, Any]) -> None:
    _merge_bucket_maps(current["bucket_map"], _histogram_to_map(state))
    if state.get("extended_bounds") and not current.get("extended_bounds"):
        current["extended_bounds"] = state["extended_bounds"]


def _merge_terms(current: dict[str, Any], state: dict[str, Any]) -> None:
    _merge_bucket_maps(current["bucket_map"], _terms_to_map(state))
    if state.get("error_bound"):
        current["error_bound"] = (current.get("error_bound", 0)
                                  + state["error_bound"])
    if state.get("other_docs"):
        current["other_docs"] = (current.get("other_docs", 0)
                                 + state["other_docs"])


# --------------------------------------------------------------------------
# finalization → ES-shaped aggregation results

def _finalize_metric(acc: dict[str, Any]) -> dict[str, Any]:
    kind = acc["kind"]
    count = acc["count"]
    if kind == "cardinality":
        hll = acc.get("hll")
        return {"value": round(hll_estimate(hll)) if hll is not None
                else 0}
    if kind == "value_count":
        return {"value": count}
    if kind == "sum":
        return {"value": acc["sum"]}
    if kind == "avg":
        return {"value": (acc["sum"] / count) if count else None}
    if kind == "min":
        return {"value": acc["min"] if np.isfinite(acc["min"]) else None}
    if kind == "max":
        return {"value": acc["max"] if np.isfinite(acc["max"]) else None}
    if kind == "stats":
        return {
            "count": count, "sum": acc["sum"],
            "min": acc["min"] if np.isfinite(acc["min"]) else None,
            "max": acc["max"] if np.isfinite(acc["max"]) else None,
            "avg": (acc["sum"] / count) if count else None,
        }
    if kind == "extended_stats":
        avg = (acc["sum"] / count) if count else None
        # population variance: E[x^2] - E[x]^2 (ES's default)
        variance = ((acc["sum_sq"] / count - avg * avg)
                    if count else None)
        if variance is not None:
            variance = max(variance, 0.0)
        sampling = (count * variance / (count - 1)
                    if count and count > 1 and variance is not None else None)
        std = variance ** 0.5 if variance is not None else None
        out = {
            "count": count, "sum": acc["sum"],
            "min": acc["min"] if np.isfinite(acc["min"]) else None,
            "max": acc["max"] if np.isfinite(acc["max"]) else None,
            "avg": avg,
            "sum_of_squares": acc["sum_sq"],
            "variance": variance,
            "variance_population": variance,
            "variance_sampling": sampling,
            "std_deviation": std,
            "std_deviation_population": std,
            "std_deviation_sampling":
                sampling ** 0.5 if sampling is not None else None,
        }
        if avg is not None and std is not None:
            out["std_deviation_bounds"] = {
                "upper": avg + 2 * std, "lower": avg - 2 * std,
                "upper_population": avg + 2 * std,
                "lower_population": avg - 2 * std,
                "upper_sampling": (avg + 2 * out["std_deviation_sampling"]
                                   if out["std_deviation_sampling"]
                                   is not None else None),
                "lower_sampling": (avg - 2 * out["std_deviation_sampling"]
                                   if out["std_deviation_sampling"]
                                   is not None else None),
            }
        return out
    if kind == "percentiles":
        percents = acc.get("percents") or DEFAULT_PERCENTS
        sketch = acc.get("sketch")
        if sketch is None:
            sketch = np.zeros(PCTL_NUM_BUCKETS, dtype=np.int32)
        return {"values": _quantile_values(sketch, percents,
                                           acc.get("keyed", True))}
    raise ValueError(f"unknown metric kind {kind}")


def _quantile_values(sketch, percents, keyed: bool = True):
    """ES-shaped percentile values; empty sketches yield null (NaN is not
    valid JSON and ES emits null for empty percentiles). `keyed: false`
    emits the list-of-{key,value} shape."""
    quantiles = sketch_quantiles(sketch, [p / 100.0 for p in percents])
    if keyed:
        return {f"{p:g}": (None if np.isnan(v) else v)
                for p, v in zip(percents, quantiles)}
    return [{"key": host_float(p), "value": (None if np.isnan(v) else v)}
            for p, v in zip(percents, quantiles)]


class _KeyOrd:
    """Typed key ordering for terms `_key` sorts (numbers before their
    string forms never mix: a terms agg's keys share one type)."""

    def __init__(self, key):
        self.key = key

    def __lt__(self, other: "_KeyOrd") -> bool:
        a, b = self.key, other.key
        if isinstance(a, str) or isinstance(b, str):
            return str(a) < str(b)
        return a < b

    def __eq__(self, other) -> bool:
        return self.key == other.key


def _finalize_bucket_map(bucket_map: dict, info: dict[str, Any],
                         sub_infos: Optional[list] = None) -> dict[str, Any]:
    """One bucket map → ES-shaped buckets, recursing into nested children
    at any depth."""
    kind = info["kind"]

    def entry_for(key, bucket, key_scaled):
        entry: dict[str, Any] = {"key": key_scaled,
                                 "doc_count": bucket["doc_count"]}
        if kind == "date_histogram":
            from ..utils.datetime_utils import format_micros_rfc3339
            entry["key_as_string"] = format_micros_rfc3339(host_int(key))
        for mname, acc in bucket["metrics"].items():
            entry[mname] = _finalize_metric(acc)
        for child_info in (sub_infos or ()):
            entry[child_info["name"]] = _finalize_bucket_map(
                bucket.get("sub_maps", {}).get(child_info["name"], {}),
                child_info, child_info.get("sub_infos"))
        return entry

    if kind == "terms":
        min_dc = info.get("min_doc_count")
        min_dc = 1 if min_dc is None else min_dc
        items = [(k, b) for k, b in bucket_map.items()
                 if b["doc_count"] >= min_dc]
        desc = info.get("order_desc", True)
        target = info.get("order_target", "_count")
        if target == "_key":
            items.sort(key=lambda kb: _KeyOrd(kb[0]), reverse=desc)
        elif target != "_count":
            # order by a single-value sub-metric ("m" or "m.max"):
            # missing/NaN metric values sort last in either direction
            metric_name, _, sub_field = target.partition(".")

            def sort_key(kb):
                acc = kb[1]["metrics"].get(metric_name)
                value = None
                if acc is not None:
                    final = _finalize_metric(acc)
                    value = final.get(sub_field or "value")
                    if isinstance(value, float) and np.isnan(value):
                        value = None
                if value is None:
                    return (1, 0, str(kb[0]))
                return (0, -value if desc else value, str(kb[0]))

            items.sort(key=sort_key)
        elif desc:
            items.sort(key=lambda kb: (-kb[1]["doc_count"], str(kb[0])))
        else:  # ES order {"_count": "asc"}: rarest terms first
            items.sort(key=lambda kb: (kb[1]["doc_count"], str(kb[0])))
        size = info.get("size") or 10
        total_other = (sum(b["doc_count"] for _, b in items[size:])
                       + info.get("other_docs", 0))
        return {"buckets": [entry_for(k, b, k) for k, b in items[:size]],
                "sum_other_doc_count": host_int(total_other),
                # nonzero only under split_size truncation: per-split
                # largest-dropped counts summed at merge
                "doc_count_error_upper_bound": host_int(
                    info.get("error_bound", 0))}

    # histograms
    min_dc = info.get("min_doc_count") or 0
    interval = info["interval"]
    bounds = info.get("extended_bounds")
    keys = sorted(bucket_map)
    if keys and min_dc == 0:
        # ES semantics: empty buckets are materialized across the observed
        # range (and any extended_bounds) when min_doc_count=0
        lo, hi = keys[0], keys[-1]
        if bounds and kind == "date_histogram":
            offset = info.get("offset", 0) or 0
            lo = min(lo, ((bounds[0] - offset) // interval) * interval
                     + offset)
            hi = max(hi, ((bounds[1] - offset) // interval) * interval
                     + offset)
        num = host_int(round((hi - lo) / interval)) + 1
        # leaf planning caps per-split ranges, but the merged range across
        # splits/nodes with disjoint time ranges can be far wider — apply
        # the AggregationLimitsGuard cap here too, like the reference does
        # at every merge level
        from .plan import MAX_BUCKETS
        if num > MAX_BUCKETS:
            raise ValueError(
                f"aggregation would materialize {num} buckets at merge "
                f"(max {MAX_BUCKETS}); raise the interval or set "
                f"min_doc_count>=1")
        keys = [lo + i * interval for i in range(num)]
    buckets = []
    for key in keys:
        bucket = bucket_map.get(key, {"doc_count": 0, "metrics": {}})
        if bucket["doc_count"] < min_dc:
            continue
        scaled = key / 1000.0 if kind == "date_histogram" else key
        buckets.append(entry_for(key, bucket, scaled))
    return {"buckets": buckets}


def finalize_aggregations(agg_states: dict[str, Any]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for name, state in agg_states.items():
        if "bucket_map" not in state and state["kind"] in (
                "date_histogram", "histogram", "terms", "range"):
            state = _copy_state(state)
        kind = state["kind"]
        if kind in ("date_histogram", "histogram", "terms"):
            out[name] = _finalize_bucket_map(
                state["bucket_map"], state,
                sub_infos=state.get("sub_infos"))
        elif kind == "range":
            buckets = []
            for i, (key, lo, hi) in enumerate(state["ranges"]):
                bucket = state["bucket_map"].get(
                    i, {"doc_count": 0, "metrics": {}})
                entry: dict[str, Any] = {"key": key,
                                         "doc_count": bucket["doc_count"]}
                if lo is not None:
                    entry["from"] = lo
                if hi is not None:
                    entry["to"] = hi
                for mname, acc in bucket["metrics"].items():
                    entry[mname] = _finalize_metric(acc)
                buckets.append(entry)
            out[name] = {"buckets": buckets}
        elif kind == "composite":
            out[name] = _finalize_composite(state)
        elif kind == "percentiles":
            out[name] = {"values": _quantile_values(
                state["sketch"], state["percents"],
                state.get("keyed", True))}
        elif kind == "cardinality":
            from ..ops.aggs import hll_estimate
            out[name] = {"value": round(hll_estimate(state["hll"]))}
        else:
            c, s, s2, mn, mx = state["state"]
            acc = {"kind": kind, "count": host_int(c),
                   "sum": host_float(s), "sum_sq": host_float(s2),
                   "min": host_float(mn), "max": host_float(mx)}
            out[name] = _finalize_metric(acc)
    return out
