"""Search request/response contracts.

Role of the reference's proto messages (`search.proto:205` SearchRequest,
`:360` LeafSearchRequest/Response, `:616` failed_splits) — the wire-stable
seam between root and leaf searchers. JSON-serializable dataclasses here;
gRPC/REST encodings wrap these in `serve/`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..query.ast import QueryAst, ast_from_dict


@dataclass(frozen=True)
class SortField:
    """Sort spec: `field` is a fast field name, or "_score" (BM25 desc by
    default), or "_doc"."""
    field: str = "_score"
    order: str = "desc"  # "asc" | "desc"

    def to_dict(self) -> dict[str, Any]:
        return {"field": self.field, "order": self.order}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "SortField":
        return SortField(d.get("field", "_score"), d.get("order", "desc"))


def string_sort_of(request, doc_mapper) -> "Optional[str]":
    """'asc'/'desc' when the request's primary sort is a text FAST field
    (dict-ordinal column) — collectors must then merge by the decoded term
    strings — else None. Must stay in lockstep with the plan's
    `Lowering._is_text_sort` (plan.py): the leaf decides what it RETURNS
    there, this decides how collectors MERGE it."""
    if not request.sort_fields:
        return None
    primary = request.sort_fields[0]
    if primary.field in ("_score", "_doc"):
        return None
    fm = doc_mapper.field(primary.field)
    if fm is None or fm.type.value != "text" or not fm.fast:
        return None
    return primary.order


def normalize_sort_fields(sort_fields: tuple) -> tuple:
    """Drop a `_doc` secondary (doc order is the implicit final tie-break)
    and anything after a `_doc` primary, so the wire request's key count
    matches what the executor actually sorts by (search_after markers align)."""
    if not sort_fields:
        return sort_fields
    if sort_fields[0].field == "_doc":
        return sort_fields[:1]
    if len(sort_fields) > 1 and sort_fields[1].field == "_doc":
        return sort_fields[:1]
    return tuple(sort_fields[:2])


@dataclass
class SearchRequest:
    index_ids: list[str]
    query_ast: QueryAst
    max_hits: int = 20
    start_offset: int = 0
    sort_fields: tuple[SortField, ...] = (SortField(),)
    aggs: Optional[dict[str, Any]] = None          # ES aggs request dict
    start_timestamp: Optional[int] = None          # micros, inclusive
    end_timestamp: Optional[int] = None            # micros, exclusive (reference semantics)
    count_hits_exact: bool = True
    search_after: Optional[list[Any]] = None       # sort values of last hit
    snippet_fields: tuple[str, ...] = ()
    # Wall-clock budget for the whole query (None = server default). NOT part
    # of the leaf-cache key (cache.canonical_request_key): two queries that
    # differ only in budget must share results.
    timeout_millis: Optional[int] = None
    # ES-compatible `"profile": true` flag: return the per-query execution
    # profile (phase waterfall + device counters) in the response. Like
    # timeout_millis, NOT part of the leaf-cache key — profiling must not
    # fragment the cache.
    profile: bool = False
    # Caller-chosen handle for mid-flight cancellation via
    # `DELETE /api/v1/search/<query_id>` (reference role: ES task cancel).
    # Like timeout_millis, NOT part of the leaf-cache key: identity of the
    # in-flight attempt, not of the results.
    query_id: Optional[str] = None

    def __post_init__(self) -> None:
        self.sort_fields = normalize_sort_fields(tuple(self.sort_fields))
        # Count-only degradation (role of the reference's count-optimized
        # leaf path, leaf.rs QuickwitCollector w/ max_hits=0): no hits are
        # returned, so the sort is irrelevant — normalize to doc order.
        # Skips BM25 scoring and sort-column warmup in the executor, and
        # lets count-only requests with different sorts share cache entries.
        # search_after markers are keyed to the original sort, so requests
        # carrying one keep their sort spec (counts are unaffected either way).
        if (self.max_hits == 0 and self.start_offset == 0
                and not self.search_after):
            self.sort_fields = (SortField("_doc", "asc"),)

    def to_dict(self) -> dict[str, Any]:
        return {
            "index_ids": self.index_ids,
            "query_ast": self.query_ast.to_dict(),
            "max_hits": self.max_hits,
            "start_offset": self.start_offset,
            "sort_fields": [s.to_dict() for s in self.sort_fields],
            "aggs": self.aggs,
            "start_timestamp": self.start_timestamp,
            "end_timestamp": self.end_timestamp,
            "count_hits_exact": self.count_hits_exact,
            "search_after": self.search_after,
            "snippet_fields": list(self.snippet_fields),
            **({"timeout_millis": self.timeout_millis}
               if self.timeout_millis is not None else {}),
            **({"profile": True} if self.profile else {}),
            **({"query_id": self.query_id}
               if self.query_id is not None else {}),
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "SearchRequest":
        return SearchRequest(
            index_ids=d["index_ids"],
            query_ast=ast_from_dict(d["query_ast"]),
            max_hits=d.get("max_hits", 20),
            start_offset=d.get("start_offset", 0),
            sort_fields=tuple(SortField.from_dict(s) for s in d.get("sort_fields", [{}])),
            aggs=d.get("aggs"),
            start_timestamp=d.get("start_timestamp"),
            end_timestamp=d.get("end_timestamp"),
            count_hits_exact=d.get("count_hits_exact", True),
            search_after=d.get("search_after"),
            snippet_fields=tuple(d.get("snippet_fields", ())),
            timeout_millis=d.get("timeout_millis"),
            profile=d.get("profile", False),
            query_id=d.get("query_id"),
        )


@dataclass(frozen=True)
class PartialHit:
    """Phase-1 hit: address + sort values, no document body
    (reference: `search.proto` PartialHit)."""
    sort_value: float          # primary sort key, already "higher is better"
    split_id: str
    doc_id: int
    raw_sort_value: Any = None  # original-typed value for search_after/display
    sort_value2: float = 0.0   # secondary key (higher-is-better; 0 if unused)
    raw_sort_value2: Any = None

    def address(self) -> tuple[str, int]:
        return (self.split_id, self.doc_id)


@dataclass
class SplitSearchError:
    split_id: str
    error: str
    retryable: bool = True


@dataclass
class LeafSearchResponse:
    """Per-leaf mergeable result (reference: `search.proto` LeafSearchResponse)."""
    num_hits: int = 0
    partial_hits: list[PartialHit] = field(default_factory=list)
    failed_splits: list[SplitSearchError] = field(default_factory=list)
    num_attempted_splits: int = 0
    num_successful_splits: int = 0
    # agg name -> intermediate state dict (kind-specific, numpy-backed)
    intermediate_aggs: dict[str, Any] = field(default_factory=dict)
    resource_stats: dict[str, float] = field(default_factory=dict)
    # Leaf-local execution profile (QueryProfile.to_dict()) when the request
    # asked for one over a remote hop; None for embedded leaves, which write
    # into the root's ambient profile directly.
    profile: Optional[dict[str, Any]] = None


@dataclass
class Hit:
    """Final hit with document body (phase 2)."""
    doc: dict[str, Any]
    score: Optional[float]
    sort_values: list[Any]
    split_id: str
    doc_id: int
    snippets: Optional[dict[str, list[str]]] = None


@dataclass
class SearchResponse:
    num_hits: int = 0
    hits: list[Hit] = field(default_factory=list)
    elapsed_time_micros: int = 0
    errors: list[str] = field(default_factory=list)
    aggregations: Optional[dict[str, Any]] = None
    scroll_id: Optional[str] = None
    # Deadline outcome: True when the query budget expired and this is a
    # partial result. `failed_splits` carries the structured per-split errors
    # (the flat `errors` strings above stay for backward compat).
    timed_out: bool = False
    # Cancellation outcome: True when the query was cancelled mid-flight
    # (REST DELETE or programmatic token) and this is whatever the chunked
    # leaves had accumulated at their last chunk boundary — possibly empty.
    cancelled: bool = False
    failed_splits: list[SplitSearchError] = field(default_factory=list)
    num_attempted_splits: int = 0
    num_successful_splits: int = 0
    # Execution profile (QueryProfile.to_dict()) when the request carried
    # `"profile": true`; additive in to_dict so unprofiled responses keep
    # their shape.
    profile: Optional[dict[str, Any]] = None

    def to_dict(self) -> dict[str, Any]:
        """Reference REST shape (`search_response_rest.rs:43`): hits are the
        raw JSON documents, snippets ride in a parallel array."""
        snippets = ([h.snippets for h in self.hits]
                    if any(h.snippets for h in self.hits) else None)
        return {
            "num_hits": self.num_hits,
            "hits": [h.doc for h in self.hits],
            **({"snippets": snippets} if snippets is not None else {}),
            "elapsed_time_micros": self.elapsed_time_micros,
            "errors": self.errors,
            **({"aggregations": self.aggregations}
               if self.aggregations is not None else {}),
            **({"scroll_id": self.scroll_id} if self.scroll_id else {}),
            # additive keys: only emitted when set, so pre-deadline response
            # shapes stay byte-identical
            **({"timed_out": True} if self.timed_out else {}),
            **({"cancelled": True} if self.cancelled else {}),
            **({"failed_splits": [
                {"split_id": e.split_id, "error": e.error,
                 "retryable": e.retryable} for e in self.failed_splits]}
               if self.failed_splits else {}),
            **({"profile": self.profile} if self.profile is not None else {}),
        }


@dataclass(frozen=True)
class SplitIdAndFooter:
    """What a leaf needs to open a split (reference: SplitIdAndFooterOffsets)."""
    split_id: str
    storage_uri: str   # storage root holding `{split_id}.split`
    file_len: Optional[int] = None
    footer_hint: Optional[int] = None
    num_docs: int = 0
    time_range: Optional[tuple[int, int]] = None  # micros, inclusive

    def to_dict(self) -> dict[str, Any]:
        return {"split_id": self.split_id, "storage_uri": self.storage_uri,
                "file_len": self.file_len, "footer_hint": self.footer_hint,
                "num_docs": self.num_docs,
                "time_range": list(self.time_range) if self.time_range else None}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "SplitIdAndFooter":
        tr = d.get("time_range")
        return SplitIdAndFooter(
            d["split_id"], d["storage_uri"], d.get("file_len"),
            d.get("footer_hint"), d.get("num_docs", 0),
            (tr[0], tr[1]) if tr else None)


@dataclass
class LeafSearchRequest:
    """Root → leaf request: search one node's split batch of one index
    (reference: `search.proto` LeafSearchRequest)."""
    search_request: SearchRequest
    index_uid: str
    doc_mapping: dict[str, Any]          # serialized DocMapper
    splits: list[SplitIdAndFooter]
    # Remaining budget at dispatch time, in millis (None = unbounded). The
    # root serializes what is LEFT, not the original timeout, so time spent
    # queued at the root is not silently re-granted to the leaf.
    deadline_millis: Optional[int] = None
    # Resolved tenant (TenantContext.to_wire(): {"id", "class"}) so a remote
    # leaf schedules HBM admission / batching in the same class the root
    # resolved. Additive: absent for tenant-blind traffic. Like
    # deadline_millis, NOT part of the leaf-cache key.
    tenant: Optional[dict[str, Any]] = None
    # Kth sort value already collected elsewhere (INTERNAL higher-is-better
    # encoding, see collector.sort_value_threshold). Seeds the leaf's
    # dynamic-pruning threshold so a root retry's second round can skip
    # splits the first round already beat. Advisory only — a leaf that
    # ignores it returns a superset, never a wrong result.
    sort_value_threshold: Optional[float] = None

    def to_dict(self) -> dict[str, Any]:
        return {"search_request": self.search_request.to_dict(),
                "index_uid": self.index_uid,
                "doc_mapping": self.doc_mapping,
                "splits": [s.to_dict() for s in self.splits],
                **({"deadline_millis": self.deadline_millis}
                   if self.deadline_millis is not None else {}),
                **({"tenant": self.tenant}
                   if self.tenant is not None else {}),
                **({"sort_value_threshold": self.sort_value_threshold}
                   if self.sort_value_threshold is not None else {})}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "LeafSearchRequest":
        return LeafSearchRequest(
            search_request=SearchRequest.from_dict(d["search_request"]),
            index_uid=d["index_uid"],
            doc_mapping=d["doc_mapping"],
            splits=[SplitIdAndFooter.from_dict(s) for s in d["splits"]],
            deadline_millis=d.get("deadline_millis"),
            tenant=d.get("tenant"),
            sort_value_threshold=d.get("sort_value_threshold"))


@dataclass
class FetchDocsRequest:
    """Phase-2 request: fetch document bodies for global top hits
    (reference: `search.proto` FetchDocsRequest)."""
    index_uid: str
    split: SplitIdAndFooter
    doc_ids: list[int]
    snippet_fields: tuple[str, ...] = ()
    query_ast: Optional[QueryAst] = None  # for snippet highlighting

    def to_dict(self) -> dict[str, Any]:
        return {"index_uid": self.index_uid, "split": self.split.to_dict(),
                "doc_ids": self.doc_ids,
                "snippet_fields": list(self.snippet_fields),
                "query_ast": self.query_ast.to_dict() if self.query_ast else None}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "FetchDocsRequest":
        return FetchDocsRequest(
            index_uid=d["index_uid"],
            split=SplitIdAndFooter.from_dict(d["split"]),
            doc_ids=d["doc_ids"],
            snippet_fields=tuple(d.get("snippet_fields", ())),
            query_ast=ast_from_dict(d["query_ast"]) if d.get("query_ast") else None)
