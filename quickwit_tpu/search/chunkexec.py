"""Resumable chunked leaf kernels: the host loop between chunk programs.

Since PR 1 the deadline/shed machinery stopped at the XLA boundary: an
in-flight leaf computation was uninterruptible, so expired queries,
cancelled scrolls, and background-class tenants were only shed at host
checkpoints (ROADMAP item 4). This module restructures the leaf kernel as
a chunked scan over doc-block slabs with carried top-K/count/mergeable-agg
state: the staged operands are partitioned into fixed-size chunks, each
chunk executes as ONE compiled program through the existing
`executor.execute_plan` seam, and the host loop between chunks is the
robustness control point. At every chunk boundary the loop

  (a) kills an expired or explicitly cancelled query mid-kernel (the
      ambient `Deadline` / `CancellationToken` from common/deadline.py —
      a cancelled query stops within one boundary and returns either a
      `"partial": true` result or a typed `CancelledQuery`),
  (b) preempts the running query when tenancy/overload.py's ladder trips
      while a higher-class query is active — the carried state parks
      (bounded, byte-accounted against the tenant's DRR quantum in
      `ParkedStateRegistry`) and resumes after, making DRR priorities
      real at kernel granularity instead of only at admission,
  (c) early-terminates when the cross-chunk block-max bound proves the
      remaining chunks cannot beat the current Kth value (the BM25S
      block-max argument applied one level up: impact-ordered prefixes
      put the highest bounds in the earliest chunks), re-reading the
      shared `ThresholdBox` every boundary so pruning tightens DURING a
      query, not just between splits.

Two partitionings cover every chunk-eligible plan:

* posting mode — single-term plans (`_posting_space_eligible`): the
  [P] ids/tfs lanes split on POSTING_PAD boundaries, the quantized
  impact block maxima split with them (IMPACT_BLOCK == POSTING_PAD),
  and every doc-space array passes through whole (the `_GatherView`
  gathers by GLOBAL doc id). Counts sum exactly because the lane
  partition is disjoint; top-K ties merge in chunk order, which IS the
  fused kernel's lowest-lane-index order.
* dense mode — everything `plan.chunk_slot_plan` can classify: the
  padded doc dimension splits on DOC_PAD boundaries; doc columns,
  zonemaps and packed masks slice by the matching granularity; posting
  pairs are host-rebased into the chunk's window (out-of-window lanes
  get the chunk's scatter-drop sentinel); the chunk's global doc offset
  rides a traced `doc_base_slot` scalar so doc-id sort keys and
  search_after comparisons stay in global doc space.

Single-chunk execution falls back to the fused path untouched — it is
bit-identical by construction and stays the compiled-program-count-
friendly default for small splits: the adaptive `_ChunkSizer` only
splits work whose profiled per-chunk latency exceeds the target boundary
interval (~10ms class), so a split the fused kernel finishes faster than
one boundary interval never chunks at all.
"""

from __future__ import annotations

import math
import os
from contextlib import contextmanager
from dataclasses import replace as dc_replace
from typing import Any, Callable, Optional

import numpy as np

from ..common import sync
from ..common.clock import get_clock
from ..common.deadline import (
    CancelledQuery, DeadlineExceeded, current_cancel_token, current_deadline,
)
from ..common.faults import InjectedFault
from ..index.format import DOC_PAD, POSTING_PAD, ZONEMAP_BLOCK
from ..observability import flight
from ..observability.metrics import (
    CHUNK_BOUNDARY_SECONDS, CHUNK_DISPATCHES_TOTAL,
    CHUNK_EARLY_TERMINATIONS_TOTAL, CHUNK_RESTARTS_TOTAL,
    PREEMPT_PARKED_BYTES, PREEMPT_TOTAL,
)
from ..ops import topk as topk_ops
from ..tenancy.context import effective_tenant
from ..tenancy.drr import DEFAULT_QUANTUM_BYTES
from ..tenancy.overload import OVERLOAD
from . import executor
from .plan import CompositeAggExec, LoweredPlan, chunk_slot_plan


# --- configuration ---------------------------------------------------------

class ChunkConfig:
    """Process-wide chunking knobs. Explicit spans (tests, benches, the
    qwir corpus) override the adaptive sizer; `enabled=False` restores the
    fused-only seed behavior byte for byte."""

    def __init__(self):
        self.enabled = True
        # explicit chunk spans (None = adaptive): docs per dense chunk
        # (DOC_PAD multiple) / postings per posting chunk (POSTING_PAD
        # multiple)
        self.doc_span: Optional[int] = _env_int("QW_CHUNK_DOC_SPAN")
        self.posting_span: Optional[int] = _env_int("QW_CHUNK_POSTING_SPAN")
        # the boundary-interval target the sizer steers toward
        self.target_boundary_secs = 0.010
        # cancelled queries return the merged-so-far state with an honest
        # "partial": true marker instead of dropping completed work
        self.partial_on_cancel = True
        # a parked query resumes after this long even if the gate never
        # clears (starvation bound; the deadline still applies on top)
        self.max_park_secs = 2.0

    def set(self, **kwargs) -> None:
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown chunking knob {key!r}")
            setattr(self, key, value)


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else None


CHUNKING = ChunkConfig()


# --- adaptive chunk sizing -------------------------------------------------

class _ChunkSizer:
    """EWMA of per-item chunk latency per mode; suggests the span whose
    predicted chunk time matches the target boundary interval. Knows
    nothing until a chunked execution has been observed, so cold-start
    behavior is the fused path (no span -> no chunking) unless an explicit
    span is configured."""

    ALPHA = 0.3

    def __init__(self):
        # qwlint: disable-next-line=QW008 - leaf lock over two floats; no
        # instrumented ops run under it
        self._lock = sync.lock("_ChunkSizer._lock")
        self._rate: dict[str, float] = {}   # mode -> EWMA secs per item

    def observe(self, mode: str, items: int, secs: float) -> None:
        if items <= 0 or secs <= 0.0:
            return
        rate = secs / items
        with self._lock:
            prev = self._rate.get(mode)
            self._rate[mode] = (rate if prev is None
                                else prev + self.ALPHA * (rate - prev))

    def span_for(self, mode: str, align: int) -> Optional[int]:
        with self._lock:
            rate = self._rate.get(mode)
        if rate is None or rate <= 0.0:
            return None
        span = CHUNKING.target_boundary_secs / rate
        return max(align, int(math.ceil(span / align)) * align)


CHUNK_SIZER = _ChunkSizer()


# --- preemption gate -------------------------------------------------------

class PreemptGate:
    """Who is running at which priority class, for boundary-time yield
    decisions. Fused and chunked executions both register; only chunked
    ones can actually yield (the fused kernel is uninterruptible — that
    is the whole point of this module)."""

    def __init__(self):
        self._cond = sync.condition(name="PreemptGate._cond")
        self._active: dict[int, int] = {}

    @contextmanager
    def running(self, priority: int):
        with self._cond:
            self._active[priority] = self._active.get(priority, 0) + 1
        try:
            yield
        finally:
            with self._cond:
                self._active[priority] -= 1
                if self._active[priority] <= 0:
                    del self._active[priority]
                self._cond.notify_all()

    def _higher_active_locked(self, priority: int) -> bool:
        return any(count > 0 and pri > priority
                   for pri, count in self._active.items())

    def should_yield(self, priority: int) -> bool:
        """True when the overload ladder has tripped AND a strictly
        higher-class query is running right now."""
        if OVERLOAD.shed_floor() <= 0:
            return False
        with self._cond:
            return self._higher_active_locked(priority)

    def wait_until_clear(self, priority: int, max_wait_secs: float,
                         deadline=None, token=None) -> None:
        """Block (in short, cancel/deadline-aware slices) until no
        higher-class query is active, the ladder clears, the starvation
        bound elapses, or the query's own budget/cancel fires."""
        clock = get_clock()
        start = clock.monotonic()
        with self._cond:
            while (self._higher_active_locked(priority)
                   and OVERLOAD.shed_floor() > 0):
                if clock.monotonic() - start >= max_wait_secs:
                    return
                if deadline is not None and deadline.expired:
                    return
                if token is not None and token.cancelled:
                    return
                self._cond.wait(timeout=0.02)


PREEMPT_GATE = PreemptGate()


# --- parked-state accounting -----------------------------------------------

class _ParkTicket:
    __slots__ = ("tenant_id", "nbytes", "evicted", "seq")

    def __init__(self, tenant_id: str, nbytes: int, seq: int):
        self.tenant_id = tenant_id
        self.nbytes = nbytes
        self.evicted = False
        self.seq = seq


class ParkedStateRegistry:
    """Byte-accounts the carried chunk state of preempted queries.

    Parked bytes are bounded per tenant by the DRR quantum (the same unit
    admission charges in) and globally by a small multiple of it. Over
    either cap the OLDEST parked entry (same tenant first) is evicted:
    its owner discards the carried state at resume and re-executes from
    scratch, counted in qw_chunk_restarts_total. Eviction is an
    accounting decision — the owner releases the actual arrays at its
    next boundary check, which is at most one park-wait away."""

    GLOBAL_CAP_FACTOR = 4

    def __init__(self, tenant_cap_bytes: int = DEFAULT_QUANTUM_BYTES):
        self.tenant_cap = tenant_cap_bytes
        self.global_cap = tenant_cap_bytes * self.GLOBAL_CAP_FACTOR
        # qwlint: disable-next-line=QW008 - leaf lock over the accounting
        # dict; no instrumented ops run under it
        self._lock = sync.lock("ParkedStateRegistry._lock")
        self._entries: dict[int, _ParkTicket] = {}
        self._seq = 0

    def park(self, tenant_id: str, nbytes: int) -> _ParkTicket:
        with self._lock:
            self._seq += 1
            ticket = _ParkTicket(tenant_id, int(nbytes), self._seq)
            self._entries[ticket.seq] = ticket
            self._evict_over_caps(ticket.tenant_id)
            PREEMPT_PARKED_BYTES.set(self._total())
            return ticket

    def release(self, ticket: _ParkTicket) -> None:
        with self._lock:
            self._entries.pop(ticket.seq, None)
            PREEMPT_PARKED_BYTES.set(self._total())

    def parked_bytes(self) -> int:
        with self._lock:
            return self._total()

    def _total(self) -> int:
        return sum(t.nbytes for t in self._entries.values())

    def _tenant_total(self, tenant_id: str) -> int:
        return sum(t.nbytes for t in self._entries.values()
                   if t.tenant_id == tenant_id)

    def _evict_over_caps(self, tenant_id: str) -> None:
        # oldest-first within the offending tenant, then globally
        while self._tenant_total(tenant_id) > self.tenant_cap:
            self._evict_oldest(tenant_id)
        while self._total() > self.global_cap:
            self._evict_oldest(None)

    def _evict_oldest(self, tenant_id: Optional[str]) -> None:
        candidates = [t for t in self._entries.values()
                      if tenant_id is None or t.tenant_id == tenant_id]
        victim = min(candidates, key=lambda t: t.seq)
        victim.evicted = True
        del self._entries[victim.seq]


PARKED_STATES = ParkedStateRegistry()


# --- eligibility & chunk-plan construction ---------------------------------

def _has_composite(plan: LoweredPlan) -> bool:
    return any(isinstance(a, CompositeAggExec) for a in plan.aggs)


def chunk_mode(plan: LoweredPlan) -> Optional[tuple[str, int, int]]:
    """(mode, total_items, alignment) or None when the plan cannot chunk.

    Composite aggs never chunk in either mode: their device state is a
    run-compressed sort of the WHOLE doc space and two chunks' runs do
    not merge host-side."""
    if _has_composite(plan):
        return None
    if executor._posting_space_eligible(plan):
        items = int(plan.arrays[plan.root.ids_slot].shape[0])
        return ("posting", items, POSTING_PAD)
    if chunk_slot_plan(plan) is not None:
        return ("dense", int(plan.num_docs_padded), DOC_PAD)
    return None


def posting_chunk_plan(plan: LoweredPlan, lo: int, hi: int) -> LoweredPlan:
    """Sub-plan over posting lanes [lo, hi): ids/tfs (and the aligned
    impact block maxima) slice; every doc-space array passes through
    whole. Counts stay exact because the lane partition is disjoint."""
    root = plan.root
    sliced = {root.ids_slot, root.tfs_slot}
    arrays = list(plan.arrays)
    keys = list(plan.array_keys)
    for slot in sliced:
        arrays[slot] = plan.arrays[slot][lo:hi]
        keys[slot] = f"{plan.array_keys[slot]}#p{lo}:{hi}"
    if root.impact_bmax_slot >= 0:
        slot = root.impact_bmax_slot
        arrays[slot] = plan.arrays[slot][lo // POSTING_PAD: hi // POSTING_PAD]
        keys[slot] = f"{plan.array_keys[slot]}#p{lo}:{hi}"
    return dc_replace(plan, arrays=arrays, array_keys=keys,
                      scalars=list(plan.scalars))


def dense_chunk_plan(plan: LoweredPlan, base: int, span: int) -> LoweredPlan:
    """Sub-plan over padded docs [base, base + span): doc/zone/packed
    slots slice by their granularity, posting pairs are host-rebased into
    the window (out-of-window lanes get sentinel `span`, the chunk's
    scatter-drop id), and the global offset rides a new traced
    `doc_base_slot` scalar."""
    slots = chunk_slot_plan(plan)
    if slots is None:
        raise ValueError("plan is not dense-chunk eligible")
    hi = base + span
    arrays = list(plan.arrays)
    keys = list(plan.array_keys)
    tag = f"#d{base}:{hi}"
    for slot in slots.doc_slots:
        arrays[slot] = plan.arrays[slot][base:hi]
        keys[slot] = plan.array_keys[slot] + tag
    for slot in slots.zone_slots:
        arrays[slot] = plan.arrays[slot][base // ZONEMAP_BLOCK:
                                         hi // ZONEMAP_BLOCK]
        keys[slot] = plan.array_keys[slot] + tag
    for slot in slots.packed_slots:
        arrays[slot] = plan.arrays[slot][base // 8: hi // 8]
        keys[slot] = plan.array_keys[slot] + tag
    for ids_slot, _tfs_slot in slots.posting_pairs:
        ids = plan.arrays[ids_slot]
        # same lane count, window-local ids: the dense evaluator's gather
        # clamps and its scatter drops index == span, so out-of-window
        # postings contribute nothing (tfs lanes pass through unchanged)
        arrays[ids_slot] = np.where((ids >= base) & (ids < hi),
                                    ids - base, span).astype(ids.dtype)
        keys[ids_slot] = plan.array_keys[ids_slot] + tag
    scalars = list(plan.scalars) + [np.int32(base)]
    num_docs = min(max(plan.num_docs - base, 0), span)
    return dc_replace(plan, arrays=arrays, array_keys=keys, scalars=scalars,
                      num_docs=num_docs, num_docs_padded=span,
                      doc_base_slot=len(scalars) - 1)


def chunk_spans(total: int, span: int, align: int) -> list[tuple[int, int]]:
    """[lo, hi) windows covering [0, total): full spans plus one aligned
    remainder — at most two distinct chunk shapes enter the compile
    cache."""
    span = max(align, (span // align) * align)
    out = []
    lo = 0
    while lo < total:
        out.append((lo, min(lo + span, total)))
        lo += span
    return out


# --- host-side carried-state merging ---------------------------------------

def _merge_agg_leaf(name: str, a, b):
    """One mergeable device output leaf — the SAME per-name rules as the
    batch fan-out's cross-split `_merge_agg_stack` (parallel/fanout.py):
    min/max/hll envelope, stats component-wise, everything else adds."""
    if name == "min":
        return np.minimum(a, b)
    if name in ("max", "hll"):
        return np.maximum(a, b)
    if name == "stats":
        # [count, sum, sum_sq, min, max]
        return np.concatenate([np.asarray(a[:3]) + np.asarray(b[:3]),
                               np.minimum(a[3:4], b[3:4]),
                               np.maximum(a[4:5], b[4:5])])
    return np.asarray(a) + np.asarray(b)


def _merge_agg_state(name: str, a, b):
    if isinstance(a, dict):
        return {key: _merge_agg_state(key, a[key], b[key]) for key in a}
    if isinstance(a, (list, tuple)):
        return [_merge_agg_state(name, xa, xb) for xa, xb in zip(a, b)]
    return _merge_agg_leaf(name, a, b)


def merge_agg_outputs(a: list, b: list) -> list:
    """Merge two chunks' `result["aggs"]` lists leaf-wise."""
    return [_merge_agg_state("", sa, sb) for sa, sb in zip(a, b)]


class _CarriedState:
    """The mergeable cross-chunk state: merged top-K rows, match count,
    agg outputs, and how many chunks contributed."""

    __slots__ = ("topk", "count", "aggs", "chunks_done")

    def __init__(self):
        self.topk = None          # (vals, vals2|None, ids, scores)
        self.count = 0
        self.aggs: Optional[list] = None
        self.chunks_done = 0

    def absorb(self, result: dict[str, Any], k: int) -> None:
        self.count += int(result["count"])
        self.aggs = (list(result["aggs"]) if self.aggs is None
                     else merge_agg_outputs(self.aggs, result["aggs"]))
        piece = (np.asarray(result["sort_values"]),
                 None if result["sort_values2"] is None
                 else np.asarray(result["sort_values2"]),
                 np.asarray(result["doc_ids"]),
                 np.asarray(result["scores"]))
        if self.topk is None:
            self.topk = piece
        else:
            # both inputs are ordered chunk outputs and the earlier one is
            # from strictly earlier lanes — the stable merge reproduces the
            # fused kernel's lowest-lane-index tie order
            vals, vals2, ids, scores = topk_ops.merge_topk_chunks(
                [self.topk, piece], k)
            self.topk = (vals, vals2, ids, scores)
        self.chunks_done += 1

    def kth_value(self, k: int) -> Optional[float]:
        """The current Kth primary key, when K hits exist."""
        if k <= 0 or self.topk is None or self.topk[0].shape[0] < k:
            return None
        kth = float(self.topk[0][k - 1])
        return None if kth == -np.inf else kth

    def nbytes(self) -> int:
        total = 0
        if self.topk is not None:
            total += sum(p.nbytes for p in self.topk if p is not None)
        stack = [self.aggs] if self.aggs is not None else []
        while stack:
            node = stack.pop()
            if isinstance(node, dict):
                stack.extend(node.values())
            elif isinstance(node, (list, tuple)):
                stack.extend(node)
            elif hasattr(node, "nbytes"):
                total += node.nbytes
        return total

    def to_result(self, k: int, partial: bool = False) -> dict[str, Any]:
        if self.topk is None:
            vals = np.zeros((0,), np.float64)
            vals2 = None
            ids = np.zeros((0,), np.int32)
            scores = np.zeros((0,), np.float32)
        else:
            vals, vals2, ids, scores = self.topk
        out = {
            "sort_values": vals,
            "sort_values2": vals2,
            "doc_ids": ids,
            "scores": scores,
            "count": int(self.count),
            "aggs": list(self.aggs or []),
        }
        if partial:
            out["partial"] = True
        return out


# --- the chunk loop --------------------------------------------------------

class _RestartScan(Exception):
    """Carried state was lost (chunk_yield fault / parked-state eviction);
    the query re-executes from scratch."""


def _host_chunk_bounds(plan: LoweredPlan,
                       spans: list[tuple[int, int]]) -> Optional[np.ndarray]:
    """Per-chunk score upper bounds from the quantized impact block maxima
    (posting mode, format v3): the host-side mirror of the kernel's
    `dequantize_block_bounds`."""
    root = plan.root
    if root.impact_bmax_slot < 0 or root.impact_scale_slot < 0:
        return None
    bmax = np.asarray(plan.arrays[root.impact_bmax_slot], dtype=np.float64)
    scale = float(np.asarray(plan.scalars[root.impact_scale_slot]))
    bounds = np.empty(len(spans), dtype=np.float64)
    for i, (lo, hi) in enumerate(spans):
        blocks = bmax[lo // POSTING_PAD: (hi + POSTING_PAD - 1) // POSTING_PAD]
        bounds[i] = blocks.max() * scale if blocks.size else -np.inf
    return bounds


def _early_term_eligible(plan: LoweredPlan, k: int, mode: str) -> bool:
    """Cross-chunk early termination is only EXACT when nothing but the
    top-K depends on the remaining chunks: score-descending single-key
    sort, no aggs, and the exact count known host-side (the impact-prefix
    `count_override`)."""
    return (mode == "posting" and k > 0
            and plan.sort.by == "score" and plan.sort.descending
            and plan.sort.by2 == "none"
            and not plan.aggs
            and plan.count_override is not None)


def _chunk_device_arrays(plan: LoweredPlan, chunk: LoweredPlan,
                         device_arrays: list) -> list:
    """Device inputs for a chunk: pass through untouched slots, slice
    device-side where the host plan sliced, and upload host-rebased
    posting ids (dense mode) fresh."""
    out = []
    import jax
    for slot, (orig, new) in enumerate(zip(plan.arrays, chunk.arrays)):
        if new is orig:
            out.append(device_arrays[slot])
        elif (new.base is not None
              and new.shape[0] <= orig.shape[0]
              and new.ndim == orig.ndim):
            # a slice view of the original — slice the device array the
            # same way (device-side slice, no host round-trip). Doc/zone/
            # packed slots slice from the front only in posting mode;
            # dense mode carries the offset in the key tag.
            lo, hi = _slice_window(orig, new)
            out.append(device_arrays[slot][lo:hi])
        else:
            out.append(jax.device_put(new))
    return out


def _slice_window(orig: np.ndarray, view: np.ndarray) -> tuple[int, int]:
    """Recover [lo, hi) of a 1-D basic-slice view into its base array."""
    offset = (view.__array_interface__["data"][0]
              - orig.__array_interface__["data"][0]) // orig.itemsize
    return int(offset), int(offset) + view.shape[0]


def execute_plan_chunked(plan: LoweredPlan, k: int, device_arrays: list,
                         *, span: Optional[int] = None,
                         threshold_box=None, fault_injector=None
                         ) -> Optional[dict[str, Any]]:
    """Run the plan as a resumable chunked scan; returns the same result
    dict as `executor.execute_plan`, or None when the plan does not chunk
    (caller falls back to the fused path). A cancelled query returns the
    merged-so-far state with `"partial": True` (or raises
    `CancelledQuery` when nothing merged yet / partials disabled)."""
    if not CHUNKING.enabled:
        return None
    mode_info = chunk_mode(plan)
    if mode_info is None:
        return None
    mode, total, align = mode_info
    if total <= 0:
        return None
    if span is None:
        span = (CHUNKING.posting_span if mode == "posting"
                else CHUNKING.doc_span)
    if span is None:
        span = CHUNK_SIZER.span_for(mode, align)
    if span is None or span <= 0:
        return None
    spans = chunk_spans(total, span, align)
    if len(spans) < 2:
        # single chunk == the fused program: keep the seed path (and the
        # seed compile-cache closure) byte-identical
        return None

    tenant = effective_tenant()
    deadline = current_deadline()
    token = current_cancel_token()
    bounds = _host_chunk_bounds(plan, spans) if mode == "posting" else None
    early_ok = _early_term_eligible(plan, k, mode)

    with PREEMPT_GATE.running(tenant.priority):
        for _attempt in range(2):
            try:
                return _run_scan(plan, k, device_arrays, mode, spans, bounds,
                                 early_ok, tenant, deadline, token,
                                 threshold_box, fault_injector)
            except _RestartScan:
                CHUNK_RESTARTS_TOTAL.inc()
                continue
        # two carried-state losses in a row: finish fused so chaos storms
        # degrade to the seed path instead of livelocking the scan
        return executor.execute_plan(plan, k, device_arrays)


def _run_scan(plan, k, device_arrays, mode, spans, bounds, early_ok,
              tenant, deadline, token, threshold_box, fault_injector):
    clock = get_clock()
    state = _CarriedState()
    threshold = (float(np.asarray(plan.scalars[plan.threshold_slot]))
                 if plan.threshold_slot >= 0 else None)
    last_boundary = clock.monotonic()
    for index, (lo, hi) in enumerate(spans):
        if index > 0:
            now = clock.monotonic()
            CHUNK_BOUNDARY_SECONDS.observe(now - last_boundary)
            last_boundary = now
            if flight.recording():
                flight.emit("chunk.boundary",
                            attrs={"index": index, "of": len(spans)})
            # (a) kill: explicit cancel, then deadline — mid-kernel at
            # chunk granularity, the whole point of the boundary
            if token is not None and token.cancelled:
                if CHUNKING.partial_on_cancel and state.chunks_done > 0:
                    return state.to_result(k, partial=True)
                raise CancelledQuery("chunked scan boundary", token.reason)
            if deadline is not None:
                deadline.check("chunked scan boundary")
            # chaos: a fault at the yield point must never wedge the
            # carried state — it is discarded and the scan restarts clean
            if fault_injector is not None:
                try:
                    fault_injector.perturb("kernel.chunk_yield")
                except InjectedFault as exc:
                    raise _RestartScan() from exc
            # (b) preempt: park the carried state while a higher class
            # runs, byte-accounted against the tenant's DRR quantum
            if PREEMPT_GATE.should_yield(tenant.priority):
                PREEMPT_TOTAL.inc()
                ticket = PARKED_STATES.park(tenant.tenant_id, state.nbytes())
                if flight.recording():
                    flight.emit("chunk.preempt_park",
                                attrs={"bytes": state.nbytes(),
                                       "priority": tenant.priority})
                try:
                    if fault_injector is not None:
                        fault_injector.perturb("kernel.preempt_park")
                    PREEMPT_GATE.wait_until_clear(
                        tenant.priority, CHUNKING.max_park_secs,
                        deadline=deadline, token=token)
                except InjectedFault as exc:
                    ticket.evicted = True
                    raise _RestartScan() from exc
                finally:
                    PARKED_STATES.release(ticket)
                if ticket.evicted:
                    # parked-state eviction under byte pressure: the
                    # resumed query has nothing to resume FROM
                    flight.emit("chunk.preempt_evict")
                    raise _RestartScan()
                flight.emit("chunk.preempt_resume")
            # (c) early termination + boundary threshold tightening
            kth = state.kth_value(k)
            if (early_ok and kth is not None and bounds is not None
                    and index < len(bounds)
                    and float(bounds[index:].max()) <= kth):
                CHUNK_EARLY_TERMINATIONS_TOTAL.inc()
                if flight.recording():
                    flight.emit("chunk.early_term",
                                attrs={"after": index, "of": len(spans)})
                result = state.to_result(k)
                # the remaining chunks' matches never ran: the exact count
                # is the host-side impact-prefix override
                result["count"] = plan.count_override
                return result
            if threshold is not None:
                box_value = (threshold_box.get()
                             if threshold_box is not None else None)
                for candidate in (box_value, kth):
                    if candidate is not None and candidate > threshold:
                        # monotone tightening only: the threshold mask
                        # keeps >=, so no final-top-K lane is ever lost
                        threshold = candidate
        chunk = (posting_chunk_plan(plan, lo, hi) if mode == "posting"
                 else dense_chunk_plan(plan, lo, hi - lo))
        if threshold is not None:
            chunk.scalars[plan.threshold_slot] = np.float64(threshold)
        if mode == "dense" and chunk.num_docs <= 0 and state.chunks_done > 0:
            continue  # fully past num_docs: no valid lanes, no new state
        chunk_dev = _chunk_device_arrays(plan, chunk, device_arrays)
        t0 = clock.monotonic()
        result = executor.execute_plan(chunk, k, chunk_dev)
        CHUNK_DISPATCHES_TOTAL.inc()
        CHUNK_SIZER.observe(mode, hi - lo, clock.monotonic() - t0)
        if mode == "dense" and k > 0:
            # chunk doc ids are window-local; hits rebase to global doc
            # space host-side (dead -inf lanes keep id 0 — they pad past
            # the live hits and are never decoded)
            live = result["sort_values"] > -np.inf
            result["doc_ids"] = np.where(
                live, np.asarray(result["doc_ids"]) + lo,
                result["doc_ids"]).astype(np.int32)
        state.absorb(result, k)
    return state.to_result(k)


def maybe_execute_chunked(plan: LoweredPlan, k: int, device_arrays: list,
                          threshold_box=None, fault_injector=None
                          ) -> Optional[dict[str, Any]]:
    """The leaf's entry point: chunked result dict, or None for the fused
    path (ineligible plan, chunking disabled, or work too small to span
    two chunks)."""
    return execute_plan_chunked(plan, k, device_arrays,
                                threshold_box=threshold_box,
                                fault_injector=fault_injector)


# --- query-group chunked scan (ROADMAP item 2 × item 4) ---------------------
#
# A stacked query group (search/batcher.py QueryGroupPlanner) composed with
# chunked execution: the carried state grows a query dim (one _CarriedState
# per lane), each chunk executes as ONE stacked dispatch over all lanes,
# and every chunk boundary applies PER-QUERY masks — a lane cancelled or
# expired mid-scan flips to valid=False in subsequent chunk dispatches
# (same program, zeroed row) while the surviving lanes keep scanning.
# Early termination and threshold tightening are per-lane: each query's
# own ThresholdBox and carried Kth value drive its mask. Preemption is a
# GROUP decision at the maximum priority among live lanes: a group
# carrying an interactive rider never parks for interactive work
# elsewhere, and the park is byte-accounted once for the summed carried
# state.

def execute_group_chunked(plans: list, k: int, arrays_list: list, *,
                          valid=None, tboxes=None, deadlines=None,
                          cancels=None, tenants=None,
                          fault_injector=None,
                          span: Optional[int] = None) -> Optional[list]:
    """Run a shape-compatible query group as one chunked stacked scan.

    Returns a list aligned with `plans`: per lane a result dict, an
    exception instance (CancelledQuery / DeadlineExceeded — the batcher
    fans it to that rider), or None for a lane masked on entry. Returns
    None (the group does not chunk) when the shared structure is
    ineligible or too small to span two chunks — the caller falls back to
    one fused stacked dispatch."""
    if not CHUNKING.enabled:
        return None
    base = plans[0]
    mode_info = chunk_mode(base)
    if mode_info is None:
        return None
    mode, total, align = mode_info
    if total <= 0:
        return None
    if span is None:
        span = (CHUNKING.posting_span if mode == "posting"
                else CHUNKING.doc_span)
    if span is None:
        span = CHUNK_SIZER.span_for(mode, align)
    if span is None or span <= 0:
        return None
    spans = chunk_spans(total, span, align)
    if len(spans) < 2:
        return None

    q = len(plans)
    valid = list(valid) if valid is not None else [True] * q
    tboxes = list(tboxes) if tboxes is not None else [None] * q
    deadlines = list(deadlines) if deadlines is not None else [None] * q
    cancels = list(cancels) if cancels is not None else [None] * q
    if tenants is None:
        tenants = [effective_tenant()] * q
    bounds = [(_host_chunk_bounds(p, spans) if mode == "posting" else None)
              for p in plans]
    early_ok = [_early_term_eligible(p, k, mode) for p in plans]

    for _attempt in range(2):
        try:
            return _run_group_scan(plans, k, arrays_list, mode, spans,
                                   bounds, early_ok, list(valid), tboxes,
                                   deadlines, cancels, tenants,
                                   fault_injector)
        except _RestartScan:
            CHUNK_RESTARTS_TOTAL.inc()
            continue
    # two carried-state losses in a row: finish as one fused stacked
    # dispatch — the group degrades to the unchunked stacked path instead
    # of livelocking the scan
    results = executor.readback_plan_stacked(executor.dispatch_plan_stacked(
        plans, k, arrays_list, valid=valid))
    return results


def _group_park_lane(live, tenants):
    """The lane whose tenant charges (and whose priority gates) a group
    park: the highest-priority live lane."""
    lanes = [i for i, alive in enumerate(live) if alive]
    return max(lanes, key=lambda i: tenants[i].priority)


def _run_group_scan(plans, k, arrays_list, mode, spans, bounds, early_ok,
                    live, tboxes, deadlines, cancels, tenants,
                    fault_injector):
    clock = get_clock()
    q = len(plans)
    base = plans[0]
    states = [_CarriedState() for _ in range(q)]
    outcome: dict[int, Any] = {}
    thresholds = [
        (float(np.asarray(p.scalars[p.threshold_slot]))
         if p.threshold_slot >= 0 else None)
        for p in plans]
    last_boundary = clock.monotonic()
    for index, (lo, hi) in enumerate(spans):
        if index > 0:
            now = clock.monotonic()
            CHUNK_BOUNDARY_SECONDS.observe(now - last_boundary)
            last_boundary = now
            if flight.recording():
                flight.emit("chunk.boundary",
                            attrs={"index": index, "of": len(spans),
                                   "lanes": int(sum(live))})
            # (a) per-query kill masks: a cancelled/expired lane leaves
            # the dispatch via its validity lane — the group's program
            # shape never changes mid-scan
            for i in range(q):
                if not live[i]:
                    continue
                token = cancels[i]
                if token is not None and token.cancelled:
                    if CHUNKING.partial_on_cancel \
                            and states[i].chunks_done > 0:
                        outcome[i] = states[i].to_result(k, partial=True)
                    else:
                        outcome[i] = CancelledQuery(
                            "chunked group boundary", token.reason)
                    live[i] = False
                    continue
                if deadlines[i] is not None and deadlines[i].expired:
                    outcome[i] = DeadlineExceeded("chunked group boundary")
                    live[i] = False
            if not any(live):
                break
            # chaos: a yield fault discards the whole group's carried
            # state — all lanes restart together (same contract as solo)
            if fault_injector is not None:
                try:
                    fault_injector.perturb("kernel.chunk_yield")
                except InjectedFault as exc:
                    raise _RestartScan() from exc
            # (b) group preempt at the max live priority: parks only when
            # EVERY live lane is outranked by the active higher class
            park_lane = _group_park_lane(live, tenants)
            park_tenant = tenants[park_lane]
            if PREEMPT_GATE.should_yield(park_tenant.priority):
                PREEMPT_TOTAL.inc()
                ticket = PARKED_STATES.park(
                    park_tenant.tenant_id,
                    sum(states[i].nbytes() for i in range(q) if live[i]))
                if flight.recording():
                    flight.emit("chunk.preempt_park",
                                attrs={"bytes": ticket.nbytes,
                                       "priority": park_tenant.priority,
                                       "lanes": int(sum(live))})
                try:
                    if fault_injector is not None:
                        fault_injector.perturb("kernel.preempt_park")
                    PREEMPT_GATE.wait_until_clear(
                        park_tenant.priority, CHUNKING.max_park_secs,
                        deadline=deadlines[park_lane],
                        token=cancels[park_lane])
                except InjectedFault as exc:
                    ticket.evicted = True
                    raise _RestartScan() from exc
                finally:
                    PARKED_STATES.release(ticket)
                if ticket.evicted:
                    flight.emit("chunk.preempt_evict")
                    raise _RestartScan()
                flight.emit("chunk.preempt_resume")
            # (c) per-lane early termination + threshold tightening
            for i in range(q):
                if not live[i]:
                    continue
                kth = states[i].kth_value(k)
                if (early_ok[i] and kth is not None and bounds[i] is not None
                        and index < len(bounds[i])
                        and float(bounds[i][index:].max()) <= kth):
                    CHUNK_EARLY_TERMINATIONS_TOTAL.inc()
                    if flight.recording():
                        flight.emit("chunk.early_term",
                                    attrs={"after": index, "lane": i})
                    result = states[i].to_result(k)
                    result["count"] = plans[i].count_override
                    outcome[i] = result
                    live[i] = False
                    continue
                if thresholds[i] is not None:
                    box_value = (tboxes[i].get()
                                 if tboxes[i] is not None else None)
                    for candidate in (box_value, kth):
                        if candidate is not None \
                                and candidate > thresholds[i]:
                            thresholds[i] = candidate
            if not any(live):
                break
        chunks = []
        for i in range(q):
            chunk = (posting_chunk_plan(plans[i], lo, hi)
                     if mode == "posting"
                     else dense_chunk_plan(plans[i], lo, hi - lo))
            if thresholds[i] is not None:
                chunk.scalars[plans[i].threshold_slot] = \
                    np.float64(thresholds[i])
            chunks.append(chunk)
        if (mode == "dense" and chunks[0].num_docs <= 0
                and all(states[i].chunks_done > 0
                        for i in range(q) if live[i])):
            continue  # fully past num_docs for every lane: nothing to add
        chunk_devs = [_chunk_device_arrays(plans[i], chunks[i],
                                           arrays_list[i])
                      for i in range(q)]
        t0 = clock.monotonic()
        results = executor.readback_plan_stacked(
            executor.dispatch_plan_stacked(chunks, k, chunk_devs,
                                           valid=list(live)))
        CHUNK_DISPATCHES_TOTAL.inc()
        CHUNK_SIZER.observe(mode, hi - lo, clock.monotonic() - t0)
        for i in range(q):
            if not live[i] or results[i] is None:
                continue
            result = results[i]
            if mode == "dense" and k > 0:
                live_rows = result["sort_values"] > -np.inf
                result["doc_ids"] = np.where(
                    live_rows, np.asarray(result["doc_ids"]) + lo,
                    result["doc_ids"]).astype(np.int32)
            states[i].absorb(result, k)
    for i in range(q):
        if live[i]:
            outcome[i] = states[i].to_result(k)
    return [outcome.get(i) for i in range(q)]
