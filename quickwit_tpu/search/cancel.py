"""Live-query registry for mid-flight cancellation.

The REST layer exposes `DELETE /api/v1/search/<query_id>`; a search that
carried a `query_id` registers its CancellationToken here for its whole
lifetime, and the DELETE handler flips the token. The chunked leaf scan
(search/chunkexec.py) and the batcher's follower wait observe the token at
their next boundary, so a cancel lands within one chunk of device work
rather than after the full split.

Registration is last-writer-wins per query_id: a retried query under the
same handle replaces the stale token (the old attempt is already dead or
about to observe its own token). Entries are unregistered in a `finally`
on the search path, so the registry only ever holds in-flight queries.
"""

from __future__ import annotations

from typing import Optional

from ..common import sync
from ..common.deadline import CancellationToken


class QueryCancelRegistry:
    """query_id -> CancellationToken for every in-flight search that opted
    into cancellation. All methods are safe from any thread (the DELETE
    handler races the searching thread by design)."""

    def __init__(self) -> None:
        self._lock = sync.lock("QueryCancelRegistry._lock")
        self._tokens: dict[str, CancellationToken] = {}

    def register(self, query_id: str, token: CancellationToken) -> None:
        with self._lock:
            self._tokens[query_id] = token

    def unregister(self, query_id: str, token: CancellationToken) -> None:
        """Remove `query_id` only if it still maps to `token` — a retry that
        re-registered under the same handle must not be evicted by the
        first attempt's cleanup."""
        with self._lock:
            if self._tokens.get(query_id) is token:
                del self._tokens[query_id]

    def cancel(self, query_id: str, reason: str = "cancelled by request") -> bool:
        """Flip the token for `query_id`. Returns False when no such query
        is in flight (already finished, never registered, or unknown id)."""
        with self._lock:
            token = self._tokens.get(query_id)
        if token is None:
            return False
        token.cancel(reason)
        from ..observability import flight
        flight.emit("query.cancel", query_id=query_id,
                    attrs={"reason": reason})
        return True

    def get(self, query_id: str) -> Optional[CancellationToken]:
        with self._lock:
            return self._tokens.get(query_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._tokens)


# Process-wide registry: REST serves many indexes from one process, and a
# query_id names a query, not an index, so one registry is the right scope.
CANCEL_REGISTRY = QueryCancelRegistry()
