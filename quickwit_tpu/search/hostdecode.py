"""The audited host-side decode seam for post-readback data.

qwlint's QW001 (hidden-host-readback) bans bare ``int()`` / ``float()`` /
``np.asarray()`` in hot-path modules because each call is a *potential*
device→host sync. But data that has already crossed the packed readback
seam (``executor.readback_plan_result`` performs ONE batched
``device_get``) or arrived deserialized off the wire at the root merge is
host numpy by contract — converting it costs nothing and syncs nothing.

These helpers make that contract explicit: hot-path modules convert
post-readback / wire-state scalars and arrays through here instead of the
bare builtins, so every bare conversion remaining in a hot-path file is a
real finding (a hidden sync to fix or justify), not noise drowning the
signal.

Callers MUST NOT pass live ``jax.Array`` values — that would hide the very
sync QW001 exists to catch. Only post-readback results, intermediate agg
states, and wire-deserialized payloads belong here.
"""

from __future__ import annotations

import numpy as np


def host_int(value) -> int:
    """``int()`` of a post-readback / wire host scalar."""
    # qwlint: disable-next-line=QW001 - host numpy by the module contract
    return int(value)


def host_float(value) -> float:
    """``float()`` of a post-readback / wire host scalar."""
    # qwlint: disable-next-line=QW001 - host numpy by the module contract
    return float(value)


def host_array(value) -> np.ndarray:
    """``np.asarray()`` of post-readback / wire host data."""
    # qwlint: disable-next-line=QW001 - host numpy by the module contract
    return np.asarray(value)


def host_list(value) -> list:
    """Bulk-decode a post-readback host array to Python scalars in one
    call — per-element ``int()``/``float()`` loops over readback arrays
    become plain list indexing (the ``.tolist()`` pre-decode pattern)."""
    return value.tolist() if hasattr(value, "tolist") else list(value)
