"""QueryAst → tensor plan lowering.

Role of the reference's `DocMapper::query` + `query_builder.rs` (QueryAst →
tantivy Query + WarmupInfo): against a concrete split, resolve every AST node
into a **static-structure plan** over named device arrays:

- terms resolve to padded posting arrays (ids/tfs) + per-term idf scalars,
- ranges resolve to column slots + traced bound scalars,
- phrases are pre-matched host-side (`ops/phrase.py`) into precomputed
  posting arrays,
- wildcard/regex expand against the term dictionary into term sets,
- aggregations resolve to column slots + static bucket counts.

The plan's `signature` captures only structure + shapes + static params, so
the jitted executor (executor.py) is cached across queries that differ only
in term values/bounds — term data and idf/bounds travel as traced inputs.

Everything here is host code doing exact-byte-range IO through SplitReader
(the warmup role, `leaf.rs:304`): after lowering, the arrays list is the
complete set of buffers the kernel needs in HBM.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

import numpy as np

from ..models.doc_mapper import DocMapper, FieldMapping, FieldType, canonical_term
from ..ops.bm25 import idf as bm25_idf
from ..ops.phrase import phrase_match
from ..query import ast as Q
from ..query.aggregations import (
    AggSpec, CompositeAgg, CompositeSource, DateHistogramAgg, HistogramAgg,
    MetricAgg, RangeAgg, TermsAgg,
)
from ..query.tokenizers import get_tokenizer
from ..index.impact import IMPACT_BLOCK
from ..index.reader import SplitReader, TermInfo
from ..utils.datetime_utils import parse_datetime_to_micros

import logging

logger = logging.getLogger(__name__)

from ..observability.tracing import RateLimitedLog  # noqa: E402

_ANALYZER_WARN = RateLimitedLog(limit=3, period_secs=300.0)

MAX_EXPANSIONS = 1024
MAX_BUCKETS = 65536  # reference: AggregationLimitsGuard default bucket limit


class PlanError(ValueError):
    pass


# --------------------------------------------------------------------------
# plan node types (static structure; data lives in slots)

@dataclass(frozen=True)
class PMatchAll:
    def sig(self) -> str:
        return "all"


@dataclass(frozen=True)
class PMatchNone:
    def sig(self) -> str:
        return "none"


@dataclass(frozen=True)
class PPostings:
    """A (possibly precomputed) posting list; scoring via BM25 if requested."""
    ids_slot: int
    tfs_slot: int
    scoring: bool
    norm_slot: int = -1     # dense fieldnorm column (scoring only)
    idf_slot: int = -1      # traced scalar: idf * boost
    avg_len_slot: int = -1  # traced scalar
    # format v3 impact-ordered postings (index/impact.py). The flag is
    # ground truth about the STORAGE order of this term's postings: the
    # executor must not take the posting-space path for field-primary
    # sorts over impact order (posting index no longer equals doc order,
    # so lowest-index-wins ties would diverge from the doc-ordered seed).
    # The slots carry the per-block quantized score bounds + dequant scale
    # for the kernel's block-max early exit; -1 when not armed.
    impact_bmax_slot: int = -1
    impact_scale_slot: int = -1
    impact_ordered: bool = False

    def sig(self) -> str:
        return (f"post({self.ids_slot},{self.tfs_slot},{self.scoring},"
                f"{self.norm_slot},{self.impact_bmax_slot},"
                f"{self.impact_ordered})")


@dataclass(frozen=True)
class PRange:
    values_slot: int
    present_slot: int
    lo_slot: int = -1
    hi_slot: int = -1
    lo_incl: bool = True
    hi_incl: bool = True
    # block-sparse evaluation: per-512-doc-block min/max zonemap arrays in
    # the same domain as values_slot (scaled deltas for packed columns,
    # raw values otherwise); -1 = no zonemaps (v1 splits, derived columns)
    zmin_slot: int = -1
    zmax_slot: int = -1

    def sig(self) -> str:
        return (f"range({self.values_slot},{self.present_slot},{self.lo_slot},"
                f"{self.hi_slot},{self.lo_incl},{self.hi_incl},"
                f"{self.zmin_slot},{self.zmax_slot})")


@dataclass(frozen=True)
class PPresence:
    present_slot: int  # uint8 present column OR int32 ordinals (>= 0 test)
    is_ordinal: bool = False

    def sig(self) -> str:
        return f"pres({self.present_slot},{self.is_ordinal})"


@dataclass(frozen=True)
class PNormPresence:
    norm_slot: int  # fieldnorm > 0 == field had tokens

    def sig(self) -> str:
        return f"npres({self.norm_slot})"


@dataclass(frozen=True)
class PBool:
    must: tuple = ()
    must_not: tuple = ()
    should: tuple = ()
    filter: tuple = ()
    minimum_should_match: Optional[int] = None

    def sig(self) -> str:
        return ("bool(m[" + ",".join(c.sig() for c in self.must) +
                "]n[" + ",".join(c.sig() for c in self.must_not) +
                "]s[" + ",".join(c.sig() for c in self.should) +
                "]f[" + ",".join(c.sig() for c in self.filter) +
                f"]{self.minimum_should_match})")


@dataclass(frozen=True)
class PMaskRef:
    """Query root replaced wholesale by a cached predicate mask
    (search/mask_cache.py): the slot holds the np.packbits-packed uint8
    bitmask (big-endian, 1 bit per padded doc) and the executor unpacks it
    instead of evaluating the query tree. Its sig() forks every compiled-
    executable cache via `LoweredPlan.signature`, like any other root.
    Scoring requests are ineligible (a mask carries no BM25 scores) — the
    lowering rejects the combination."""
    packed_slot: int

    def sig(self) -> str:
        return f"maskref({self.packed_slot})"


# --------------------------------------------------------------------------
# aggregation executables

@dataclass(frozen=True)
class MetricSlots:
    name: str
    kind: str  # avg|min|max|sum|stats|extended_stats|value_count|percentiles|cardinality
    values_slot: int
    present_slot: int
    percents: tuple[float, ...] = ()
    keyed: bool = True  # percentiles output shape
    # cardinality on text columns: per-ordinal 64-bit term hashes
    # (host-precomputed so cross-split merges hash the TERM, not the
    # split-local ordinal); -1 = hash the numeric value in-kernel
    hash_slot: int = -1

    def sig(self) -> str:
        return (f"met({self.kind},{self.values_slot},{self.present_slot},"
                f"{self.hash_slot})")


@dataclass(frozen=True)
class BucketAggExec:
    """date_histogram / histogram / terms lowered onto one bucket-index map."""
    name: str
    kind: str                    # "date_histogram" | "histogram" | "terms"
    values_slot: int             # i64/f64 column or int32 ordinals
    present_slot: int            # -1 for ordinal columns (ordinal >= 0 is presence)
    num_buckets: int             # static
    origin_slot: int = -1        # traced (histograms)
    interval_slot: int = -1      # traced (histograms)
    froms_slot: int = -1         # range agg: [nb] f64 lower bounds
    tos_slot: int = -1           # range agg: [nb] f64 upper bounds
    metrics: tuple[MetricSlots, ...] = ()
    # host-side info for finalization (not part of jit signature)
    host_info: Any = None
    # nested bucket children, arbitrary depth and siblings; each chain
    # computes over a mixed-radix flattened bucket space on device
    subs: tuple["BucketAggExec", ...] = ()

    def sig(self) -> str:
        subs_sig = ";".join(s.sig() for s in self.subs)
        return (f"bagg({self.kind},{self.values_slot},{self.present_slot},"
                f"{self.num_buckets},{self.origin_slot},{self.interval_slot},"
                f"{self.froms_slot},{self.tos_slot},"
                + ",".join(m.sig() for m in self.metrics)
                + f",subs[{subs_sig}])")


@dataclass(frozen=True)
class MetricAggExec:
    name: str
    metric: MetricSlots

    def sig(self) -> str:
        return f"magg({self.metric.sig()})"


@dataclass(frozen=True)
class CompositeSourceExec:
    """One composite-agg key source lowered onto a per-doc i32 key.

    Key encoding (order-preserving): missing → 0, value with
    ordinal/bucket-index `idx` → (idx+1)*2. The odd gap values encode
    `after` positions that fall BETWEEN this split's keys (a term absent
    from the split's dictionary lowers to insertion_point*2+1), so the
    device-side strict `key > after` comparison is exact in every split."""
    kind: str                 # "terms_ord" | "histogram" | "date_histogram"
    values_slot: int
    present_slot: int = -1    # terms_ord derives presence from ordinal >= 0
    origin_slot: int = -1     # histogram kinds (traced scalar)
    interval_slot: int = -1
    missing_bucket: bool = False
    after_slot: int = -1      # traced i32 scalar (plan.has_after only)

    def sig(self) -> str:  # qwlint: disable=QW001 - int() of a python bool dataclass field into the signature string; runs at plan-build time on host
        return (f"csrc({self.kind},{self.values_slot},{self.present_slot},"
                f"{self.origin_slot},{self.interval_slot},"
                f"{int(self.missing_bucket)},{self.after_slot})")


@dataclass(frozen=True)
class CompositeAggExec:
    """`composite` lowered TPU-first: per-source i32 key planes, one
    multi-key `lax.sort` over the doc space, run-boundary detection, and a
    static-size readback of the first `size` distinct key tuples + counts
    (role of tantivy's composite collector driven via `collector.rs:523`).

    Bucket children (`subs`) evaluate in DOC space: the sort permutation
    scatters each doc's run id (composite bucket index) back to its
    original position, and the normal nested-bucket evaluator runs with
    the composite as the outermost radix level (child flat index =
    run_id * child_nb + child_local)."""
    name: str
    sources: tuple[CompositeSourceExec, ...]
    size: int
    has_after: bool
    metrics: tuple["MetricSlots", ...] = ()
    subs: tuple["BucketAggExec", ...] = ()
    host_info: Any = None     # per-source decode info (not jit-relevant)

    def sig(self) -> str:  # qwlint: disable=QW001 - int() of a python bool dataclass field into the signature string; runs at plan-build time on host
        return (f"cagg({self.size},{int(self.has_after)},"
                + ",".join(s.sig() for s in self.sources) + ";"
                + ",".join(m.sig() for m in self.metrics) + ";"
                + ",".join(s.sig() for s in self.subs) + ")")


def coerce_numeric_bound(field_type: FieldType, value: Any):  # qwlint: disable=QW001 - coerces user query-JSON bounds (python str/int/float); no device value can reach here
    """Numeric range-bound coercion shared by the leaf lowering
    (`_parse_bound`) and the root's zonemap pruning
    (`root.extract_numeric_constraints`) — the two MUST stay identical or
    the root could prune a split the leaf matches: int() truncation for
    integer fields, the ES u64 domain clamp, float for f64. Raises
    ValueError/TypeError on unparseable input."""
    if field_type is FieldType.F64:
        return float(value)
    parsed = int(value)
    if field_type is FieldType.U64:
        # ES clamps out-of-domain u64 bounds instead of erroring
        parsed = max(0, min(parsed, (1 << 64) - 1))
    return parsed


def aligned_origin(vmin, interval, offset=0):  # qwlint: disable=QW001 - float() of the np.floor host scalar over column min/max stats, pre-dispatch
    """ES bucket alignment shared by every histogram lowering (plain and
    composite): the bucket boundary k*interval + offset at or below vmin.
    Exact integer math for date micros, float for numeric histograms."""
    if isinstance(interval, int):
        return ((vmin - offset) // interval) * interval + offset
    return float(np.floor((vmin - offset) / interval) * interval + offset)


# --------------------------------------------------------------------------
# sort

# sentinel present_slot: presence is derived on-device as values >= 0
# (dict-ordinal columns encode missing as -1; no bool column shipped)
PRESENT_FROM_VALUES = -2


@dataclass(frozen=True)
class SortExec:
    """Static sort plan: by score, by column, or by doc id; optional
    secondary key (the reference supports up to two sort fields)."""
    by: str                  # "score" | "column" | "doc"
    descending: bool = True
    values_slot: int = -1
    present_slot: int = -1
    by2: str = "none"        # "none" | "score" | "column"
    descending2: bool = True
    values2_slot: int = -1
    present2_slot: int = -1

    def sig(self) -> str:
        return (f"sort({self.by},{self.descending},{self.values_slot},"
                f"{self.present_slot},{self.by2},{self.descending2},"
                f"{self.values2_slot},{self.present2_slot})")


# --------------------------------------------------------------------------

@dataclass
class LoweredPlan:
    root: Any
    sort: SortExec
    aggs: list[Any]
    arrays: list[np.ndarray]          # device inputs, slot-indexed
    array_keys: list[str]             # cache keys for device-transfer reuse
    scalars: list[np.ndarray]         # traced scalar inputs, slot-indexed
    num_docs: int
    num_docs_padded: int
    # search_after pushdown: "none" | "lt" | "lt_tie" | "le" (static; the
    # marker value/doc travel as trailing traced scalars)
    search_after_relation: str = "none"
    sa_value_slot: int = -1
    sa_value2_slot: int = -1
    sa_doc_slot: int = -1
    # text-field (dict-ordinal) primary sort: the leaf decodes the returned
    # ordinals back to term strings; merging happens on the strings
    sort_text_field: Optional[str] = None
    # dynamic top-K threshold pushdown: traced f64 scalar (internal
    # higher-is-better key) masking sub-threshold docs before top_k. Like
    # search_after, only PRESENCE is static — the value rides a scalar slot
    # so the compiled executable is reused across threshold values. Under
    # a stacked multi-query dispatch (search/batcher.py QueryGroupPlanner)
    # every scalar slot — this one included — widens to a [Q] lane vector:
    # each query lane carries its OWN killing threshold, masked per lane
    # inside the one compiled program (executor.dispatch_plan_stacked).
    threshold_slot: int = -1
    # FOR-packed value loads: array slot -> (scale_slot, min_slot) traced
    # scalars. Consumers that need actual values (sort keys, metric/bucket
    # aggs) reconstruct `packed * scale + min` in-kernel; the SLOT map is
    # static (part of the signature), the scale/min values are traced so
    # per-split frames share one compiled executable.
    rebase: dict[int, tuple[int, int]] = dc_field(default_factory=dict)
    # impact prefix cutoff (format v3): when the lowering truncated the
    # sole scoring term's postings to the live above-threshold prefix, the
    # kernel's matched-doc count runs over fewer lanes — the exact count
    # (the term's df) is known host-side and overrides it at the leaf.
    # Host-only; deliberately NOT in the signature.
    count_override: Optional[int] = None
    # chunked execution (search/chunkexec.py): dense chunk sub-plans carry
    # the chunk's global doc offset as a traced int32 scalar so doc-id sort
    # keys and search_after doc comparisons stay in GLOBAL doc space while
    # the arrays are chunk-local. -1 (every plan the normal lowering
    # produces) keeps today's programs byte-identical; presence is static
    # (part of the signature), the offset value is traced so every chunk of
    # a split shares one compiled executable.
    doc_base_slot: int = -1

    def signature(self, k: int) -> tuple:
        # memoized per k: the signature is pure in the plan's static
        # structure (scalar VALUES are deliberately excluded, only dtypes
        # count), every mutation path goes through dataclasses.replace
        # (fresh instance -> fresh memo), and the dispatch hot path asks
        # for it up to three times per query (flight event, profile
        # attribution, executor cache key)
        memo = getattr(self, "_sig_memo", None)
        if memo is None:
            memo = {}
            object.__setattr__(self, "_sig_memo", memo)
        cached = memo.get(k)
        if cached is not None:
            return cached
        shapes = tuple((a.shape, str(a.dtype)) for a in self.arrays)
        scalar_dtypes = tuple(str(s.dtype) for s in self.scalars)
        agg_sig = ",".join(a.sig() for a in self.aggs)
        rebase_sig = tuple(sorted(
            (slot, slots) for slot, slots in self.rebase.items()))
        sig = (self.root.sig(), self.sort.sig(), agg_sig, shapes,
               scalar_dtypes, k, self.num_docs_padded,
               self.search_after_relation, self.sa_value2_slot >= 0,
               self.threshold_slot >= 0, rebase_sig,
               self.doc_base_slot >= 0)
        memo[k] = sig
        return sig

    def structure_digest(self, k: int) -> str:
        """Stable hex digest of the compile-cache structure key.

        The signature tuple is built from primitive types only (node sig
        strings, shape tuples, dtype names, ints/bools), so its repr is
        deterministic across processes — tools/qwir keys its compile-cache
        closure manifest on this digest. Anything that changes the compiled
        program's identity MUST flow through `signature` (and therefore
        through this digest), or the closure certificate stops being a
        proof."""
        import hashlib
        return hashlib.blake2b(repr(self.signature(k)).encode(),
                               digest_size=16).hexdigest()

    def group_key(self, k: int, split_key) -> tuple:
        """Grouping key for device-side multi-query stacking: two queries
        whose plans agree on this key are shape-compatible — same lowered
        structure (node sigs, sort spec, agg shape, array shapes/dtypes,
        scalar dtypes, threshold/search_after/rebase presence) over the
        same split — and may stack as lanes of ONE compiled dispatch with
        their terms/filters/thresholds riding stacked operands
        (docs/query-batching.md). Deliberately WIDER than the convoy key
        (which also pins `array_keys`): distinct queries are the point."""
        return ("qb", self.structure_digest(k), split_key)


class _Builder:
    def __init__(self, reader: SplitReader):
        self.reader = reader
        self.arrays: list[np.ndarray] = []
        self.array_keys: list[str] = []
        self.scalars: list[np.ndarray] = []
        self._array_slots: dict[str, int] = {}

    def add_array(self, key: str, fetch) -> int:  # qwlint: disable=QW001 - np.asarray stages host column data into the plan's jit-input tuple; columns are numpy by the reader contract
        """Deduplicated array slot; `fetch()` runs only on first use."""
        slot = self._array_slots.get(key)
        if slot is None:
            slot = len(self.arrays)
            self.arrays.append(np.asarray(fetch()))
            self.array_keys.append(key)
            self._array_slots[key] = slot
        return slot

    def add_scalar(self, value, dtype) -> int:  # qwlint: disable=QW001 - np.asarray on python/numpy plan scalars being staged as jit inputs, pre-dispatch
        self.scalars.append(np.asarray(value, dtype=dtype))
        return len(self.scalars) - 1


# --------------------------------------------------------------------------

class Lowering:
    """`batch_overrides` (multi-split batches, parallel/fanout.py) forces a
    split-independent plan structure: missing terms lower to empty posting
    slots instead of PMatchNone, date_histogram bucket spaces come from the
    batch-global time range, and terms-agg ordinals are remapped to a
    batch-global dictionary."""

    def __init__(self, doc_mapper: DocMapper, reader: SplitReader,
                 batch_overrides: Optional[dict] = None,
                 absence_sink=None):
        self.doc_mapper = doc_mapper
        self.reader = reader
        self.b = _Builder(reader)
        # absence_sink(field, term): every term-dictionary miss is an
        # immutable proof of absence in this split — feeds the predicate/
        # negative cache (predicate_cache.py)
        self.absence_sink = absence_sink
        self.batch = batch_overrides  # {"histograms": {name: (origin, nb)},
                                      #  "terms_dicts": {field: {key: gord}},
                                      #  "terms_cards": {field: int}}
        # FOR-packed slots needing in-kernel reconstruction (LoweredPlan.rebase)
        self.rebase: dict[int, tuple[int, int]] = {}
        # impact prefix-cutoff context, armed by lower_request ONLY when the
        # whole query is a single scoring term with a pushed-down threshold
        # (no aggs / filters / search_after / time window / batch): the one
        # shape where dropping a term's below-threshold posting tail cannot
        # change any result the threshold mask would keep
        self._impact_term: Optional[tuple[str, str, float]] = None
        self._impact_threshold: Optional[float] = None
        self.count_override: Optional[int] = None

    # --- helpers ----------------------------------------------------------
    def _field(self, name: str) -> FieldMapping:
        fm = self.doc_mapper.field(name)
        if fm is None:
            if (name == "_doc_length"
                    and self.doc_mapper.store_document_size):
                return FieldMapping("_doc_length", FieldType.I64,
                                    fast=True, indexed=False)
            if (self.doc_mapper.mode == "dynamic"
                    and not self.doc_mapper.shadows_concrete_field(name)):
                # unmapped path under dynamic mode: the split may hold it
                # as a materialized dynamic field; term lookups on splits
                # that never saw the path lower to empty postings
                return self.doc_mapper.dynamic_field(name)
            raise PlanError(f"unknown field {name!r}")
        return fm

    def _postings_node(self, field: str, term: str, scoring: bool,
                       boost: float) -> Any:
        fm = self.doc_mapper.field(field)
        if fm is not None and fm.tokenizer == "en_stem":
            extra = self.reader.footer.extra or {}
            from ..index.writer import ANALYZER_VERSION
            if extra.get("analyzer_version", 1) != ANALYZER_VERSION:
                # stemmer output changed since this split was written:
                # query-side terms may not match — results need a reindex
                emit, _ = _ANALYZER_WARN.should_log("analyzer")
                if emit:
                    logger.warning(
                        "split %s was written with analyzer_version %s "
                        "(current %s): en_stem terms may mismatch — "
                        "reindex to refresh", self.reader.path,
                        extra.get("analyzer_version", 1), ANALYZER_VERSION)
        info = self.reader.lookup_term(field, term)
        if info is None:
            if self.absence_sink is not None:
                self.absence_sink(field, term)
            if self.batch is None:
                return PMatchNone()
            return self._empty_postings_node(field, term, scoring)
        impact_ordered = self.reader.impact_info(field) is not None
        prefix = None
        if (scoring and impact_ordered and self.batch is None
                and self._impact_term is not None
                and self._impact_term[0] == field
                and self._impact_term[1] == term):
            prefix = self._impact_prefix(field, info, boost)
        if prefix is not None and prefix["live_len"] < info.post_len:
            # impact order makes the threshold cutoff a PREFIX cutoff: the
            # tail never stages to HBM (smaller arrays fall through the
            # same HbmBudget/residency accounting), and the matched-doc
            # count is restored host-side from the term's df
            live_len = prefix["live_len"]
            ids_slot = self.b.add_array(
                f"post.{field}.{info.ordinal}.ids@{live_len}",
                lambda: self.reader.array_slice(
                    f"inv.{field}.postings.ids", info.post_off, live_len))
            tfs_slot = self.b.add_array(
                f"post.{field}.{info.ordinal}.tfs@{live_len}",
                lambda: self.reader.array_slice(
                    f"inv.{field}.postings.tfs", info.post_off, live_len))
            self.count_override = info.df
        else:
            ids_slot = self.b.add_array(
                f"post.{field}.{info.ordinal}.ids",
                lambda: self.reader.postings(field, info)[0])
            tfs_slot = self.b.add_array(
                f"post.{field}.{info.ordinal}.tfs",
                lambda: self.reader.postings(field, info)[1])
        if not scoring:
            return PPostings(ids_slot, tfs_slot, scoring=False,
                             impact_ordered=impact_ordered)
        meta = self.reader.field_meta(field)
        norm_slot = self._fieldnorm_slot(field)
        idf_value = bm25_idf(self.reader.num_docs, info.df) * boost
        idf_slot = self.b.add_scalar(idf_value, np.float32)
        avg_slot = self.b.add_scalar(meta.get("avg_len", 1.0), np.float32)
        bmax_slot = scale_slot = -1
        if prefix is not None:
            live_blocks = prefix["live_blocks"]
            bmax_live = prefix["bmax"][:live_blocks]
            bmax_slot = self.b.add_array(
                f"impact.{field}.{info.ordinal}.bmax@{live_blocks}",
                lambda: bmax_live)
            # boost folds into the traced scale exactly like it folds into
            # the idf scalar, so the kernel bound covers the boosted score
            scale_slot = self.b.add_scalar(prefix["scale"] * boost,
                                           np.float64)
        return PPostings(ids_slot, tfs_slot, True, norm_slot, idf_slot,
                         avg_slot, impact_bmax_slot=bmax_slot,
                         impact_scale_slot=scale_slot,
                         impact_ordered=impact_ordered)

    def _impact_prefix(self, field: str, info: "TermInfo", boost: float):
        """Host-side prefix-cutoff decision for one impact-ordered term:
        how many leading 128-posting blocks can still reach the pushed-down
        threshold. Block bounds are non-increasing (postings sorted by
        descending impact), so the live set is a prefix; its length rounds
        UP to a power of two of blocks (capped at the term's total) to keep
        the distinct staged shapes — and therefore executor recompiles —
        logarithmic in term length. Returns None when the side arrays are
        unusable."""
        from .hostdecode import host_int
        bmax, scale = self.reader.impact_term_bounds(field, info)
        nblocks = info.post_len // IMPACT_BLOCK
        if nblocks <= 0 or bmax.shape[0] != nblocks:
            return None
        bounds = bmax.astype(np.float64) * (np.float64(scale) * boost)
        live = host_int(np.count_nonzero(bounds >= self._impact_threshold))
        # at least one block stays: downstream shapes must be non-empty,
        # and the kernel mask handles an all-dead block exactly
        live_blocks = 1
        while live_blocks < live:
            live_blocks *= 2
        live_blocks = min(live_blocks, nblocks)
        skipped = nblocks - live_blocks
        from ..observability.profile import profile_add
        profile_add("impact_blocks_scored", live_blocks)
        from ..observability.metrics import (
            IMPACT_BLOCKS_SCORED_TOTAL, IMPACT_BLOCKS_SKIPPED_TOTAL,
            IMPACT_POSTINGS_BYTES_AVOIDED_TOTAL, IMPACT_PREFIX_CUTOFFS_TOTAL)
        IMPACT_BLOCKS_SCORED_TOTAL.inc(live_blocks)
        if skipped > 0:
            # ids + tfs are int32: 8 bytes per posting never staged
            bytes_avoided = skipped * IMPACT_BLOCK * 8
            profile_add("impact_blocks_skipped", skipped)
            profile_add("impact_postings_bytes_avoided", bytes_avoided)
            profile_add("impact_prefix_cutoffs")
            IMPACT_BLOCKS_SKIPPED_TOTAL.inc(skipped)
            IMPACT_POSTINGS_BYTES_AVOIDED_TOTAL.inc(bytes_avoided)
            IMPACT_PREFIX_CUTOFFS_TOTAL.inc()
        return {"bmax": bmax, "scale": scale, "live_blocks": live_blocks,
                "live_len": live_blocks * IMPACT_BLOCK}

    def _fieldnorm_slot(self, field: str) -> int:
        """Fieldnorm array slot, tolerating splits that never materialized
        the field (dynamic-mode paths absent from a split): zeros keep the
        plan structure uniform and contribute nothing to BM25."""
        reader = self.reader
        if reader.has_array(f"inv.{field}.fieldnorm"):
            return self.b.add_array(
                f"norm.{field}", lambda: reader.fieldnorm(field))
        return self.b.add_array(
            f"norm.{field}.absent",
            lambda: np.zeros(reader.num_docs_padded, dtype=np.int32))

    def _empty_postings_node(self, field: str, term: str, scoring: bool) -> Any:
        """Uniform-structure stand-in for a term absent from this split."""
        from ..index.format import POSTING_PAD
        # impact_ordered is in the plan sig: the stand-in has no postings
        # (either storage-order claim is vacuously true), so mirror the
        # batch peers that do hold the field — otherwise a v3 batch with
        # the field absent from ONE split fails the uniformity check
        impact = any(
            r.impact_info(field) is not None
            for r in self.batch.get("batch_readers", ()))
        sentinel = self.reader.num_docs_padded
        ids_slot = self.b.add_array(
            f"post.{field}.absent:{term}.ids",
            lambda: np.full(POSTING_PAD, sentinel, dtype=np.int32))
        tfs_slot = self.b.add_array(
            f"post.{field}.absent:{term}.tfs",
            lambda: np.zeros(POSTING_PAD, dtype=np.int32))
        if not scoring:
            return PPostings(ids_slot, tfs_slot, scoring=False,
                             impact_ordered=impact)
        meta = self.reader.field_meta(field)
        norm_slot = self._fieldnorm_slot(field)
        idf_slot = self.b.add_scalar(0.0, np.float32)
        avg_slot = self.b.add_scalar(meta.get("avg_len", 1.0), np.float32)
        return PPostings(ids_slot, tfs_slot, True, norm_slot, idf_slot,
                         avg_slot, impact_ordered=impact)

    def _precomputed_node(self, key: str, ids: np.ndarray, freqs: np.ndarray,  # qwlint: disable=QW001 - int() of the host-side document frequency from reader metadata when minting the idf scalar
                          field: str, scoring: bool, boost: float,
                          df_for_idf: int) -> Any:
        from ..index.format import POSTING_PAD, pad_to
        if ids.size == 0 and self.batch is None:
            return PMatchNone()
        padded = pad_to(max(ids.size, 1), POSTING_PAD)
        pids = np.full(padded, self.reader.num_docs_padded, dtype=np.int32)
        ptfs = np.zeros(padded, dtype=np.int32)
        pids[: ids.size] = ids
        ptfs[: freqs.size] = freqs
        ids_slot = self.b.add_array(f"pre.{key}.ids", lambda: pids)
        tfs_slot = self.b.add_array(f"pre.{key}.tfs", lambda: ptfs)
        if not scoring:
            return PPostings(ids_slot, tfs_slot, scoring=False)
        meta = self.reader.field_meta(field)
        norm_slot = self._fieldnorm_slot(field)
        idf_slot = self.b.add_scalar(
            bm25_idf(self.reader.num_docs, max(int(df_for_idf), 1)) * boost, np.float32)
        avg_slot = self.b.add_scalar(meta.get("avg_len", 1.0), np.float32)
        return PPostings(ids_slot, tfs_slot, True, norm_slot, idf_slot, avg_slot)

    def _column_slots(self, field: str) -> tuple[int, int]:
        fm = self._field(field)
        if not fm.fast:
            raise PlanError(f"field {field!r} is not a fast field")
        packed = self._packed_column_slots(field)
        if packed is not None:
            return packed
        values_slot = self.b.add_array(
            f"col.{field}.values", lambda: self.reader.column_values(field)[0])
        present_slot = self.b.add_array(
            f"col.{field}.present", lambda: self.reader.column_values(field)[1])
        return values_slot, present_slot

    def _packed_column_slots(self, field: str) -> Optional[tuple[int, int]]:
        """Column slots over the PACKED delta lanes (format v2): the narrow
        array is what ships to HBM, and a per-slot rebase entry (traced
        scale/min scalars) tells value consumers to reconstruct
        `delta * scale + min` in-register — full-width semantics, compact
        bytes. Works under batch plans: the slot map is structural, the
        frame values ride per-split traced scalars."""
        info = self.reader.column_packing(field)
        if info is None:
            return None
        values_slot = self.b.add_array(
            f"col.{field}.packed",
            lambda: self.reader.column_packed(field)[0])
        present_slot = self.b.add_array(
            f"col.{field}.present",
            lambda: self.reader.column_packed(field)[1])
        if values_slot not in self.rebase:
            meta = self.reader.field_meta(field)
            sdtype = (np.uint64
                      if (meta.get("col_type") or meta.get("type")) == "u64"
                      else np.int64)
            scale_slot = self.b.add_scalar(info["for_scale"], sdtype)
            min_slot = self.b.add_scalar(info["for_min"], sdtype)
            self.rebase[values_slot] = (scale_slot, min_slot)
        return values_slot, present_slot

    def _zonemap_slots(self, field: str) -> tuple[int, int]:
        """(zmin_slot, zmax_slot) of a column's block zonemaps, or (-1, -1)
        for splits that predate them (format v1)."""
        zm = self.reader.column_zonemaps(field)
        if zm is None:
            return -1, -1
        zmin_slot = self.b.add_array(f"col.{field}.zmin", lambda: zm[0])
        zmax_slot = self.b.add_array(f"col.{field}.zmax", lambda: zm[1])
        return zmin_slot, zmax_slot

    def _parse_bound(self, fm: FieldMapping, value: Any) -> Any:  # qwlint: disable=QW001 - int() truncation of query-JSON bounds on host (mirrors coerce_numeric_bound)
        if fm.type is FieldType.DATETIME:
            return parse_datetime_to_micros(value, fm.input_formats) \
                if not isinstance(value, (int, float)) or isinstance(value, bool) \
                else parse_datetime_to_micros(value, ("unix_timestamp",))
        if fm.type in (FieldType.I64, FieldType.U64, FieldType.F64):
            return coerce_numeric_bound(fm.type, value)
        if fm.type is FieldType.IP:
            return int(value)
        if fm.type is FieldType.BOOL:
            return 1 if str(value).lower() == "true" else 0
        raise PlanError(f"range query unsupported on field type {fm.type}")

    # --- node lowering ----------------------------------------------------
    def lower(self, ast: Q.QueryAst, scoring: bool, boost: float = 1.0) -> Any:
        if isinstance(ast, Q.MatchAll):
            return PMatchAll()
        if isinstance(ast, Q.MatchNone):
            return PMatchNone()
        if isinstance(ast, Q.Boost):
            return self.lower(ast.underlying, scoring, boost * ast.boost)
        if isinstance(ast, Q.Term):
            return self._lower_term(ast, scoring, boost)
        if isinstance(ast, Q.TermSet):
            nodes = []
            for field, terms in ast.terms_per_field.items():
                fm = self._field(field)
                for term in terms:
                    if not fm.indexed and fm.fast \
                            and fm.type is FieldType.TEXT:
                        nodes.append(self._fast_only_term(field, term))
                    else:
                        nodes.append(self._postings_node(
                            field, self._canonical(fm, term), False, boost))
            return self._or(nodes)
        if isinstance(ast, Q.FullText):
            return self._lower_full_text(ast, scoring, boost)
        if isinstance(ast, Q.PhrasePrefix):
            return self._lower_phrase_prefix(ast, scoring, boost)
        if isinstance(ast, Q.Wildcard):
            pattern = ast.pattern
            fm_w = self.doc_mapper.field(ast.field)
            if (fm_w is not None and fm_w.type is FieldType.TEXT
                    and fm_w.tokenizer not in ("raw", "whitespace")):
                # ES analyzes wildcard terms with the field's analyzer:
                # `Jou*al` matches tokens of lowercasing tokenizers
                # (raw and whitespace preserve case)
                pattern = pattern.lower()
            return self._lower_pattern(
                ast.field, fnmatch.translate(pattern), scoring, boost,
                literal_prefix=("" if ast.case_insensitive
                                else _wildcard_prefix(pattern)),
                case_insensitive=ast.case_insensitive)
        if isinstance(ast, Q.Regex):
            return self._lower_pattern(
                ast.field, ast.pattern, scoring, boost,
                literal_prefix=("" if ast.case_insensitive
                                else _regex_prefix(ast.pattern)),
                case_insensitive=ast.case_insensitive)
        if isinstance(ast, Q.FieldPresence):
            return self._lower_presence(ast.field)
        if isinstance(ast, Q.Range):
            return self._lower_range(ast)
        if isinstance(ast, Q.Bool):
            return PBool(
                must=tuple(self.lower(c, scoring, boost) for c in ast.must),
                must_not=tuple(self.lower(c, False, boost) for c in ast.must_not),
                should=tuple(self.lower(c, scoring, boost) for c in ast.should),
                filter=tuple(self.lower(c, False, boost) for c in ast.filter),
                minimum_should_match=ast.minimum_should_match,
            )
        raise PlanError(f"cannot lower query node {type(ast).__name__}")

    def _canonical(self, fm: FieldMapping, value: str) -> str:
        # single source of truth shared with the predicate cache's
        # required-term extraction: a drift between the two would make
        # negative-cache pruning unsound, not just ineffective
        from .predicate_cache import canonical_query_term
        return canonical_query_term(fm, value)

    def _lower_term(self, ast: Q.Term, scoring: bool, boost: float) -> Any:
        from .predicate_cache import term_is_tokenized_text
        fm = self._field(ast.field)
        if not ast.verbatim and term_is_tokenized_text(fm):
            # terms on tokenized text behave as a conjunctive full-text match
            # (quickwit's query language semantics)
            return self._lower_full_text(
                Q.FullText(ast.field, ast.value, "and"), scoring, boost)
        if not fm.indexed:
            if fm.fast and fm.type is FieldType.TEXT:
                # fast-only text field: exact-term match as an ordinal
                # EQUALITY on the dictionary column (reference: fast-field
                # queries on index:false fields)
                return self._fast_only_term(ast.field, ast.value)
            raise PlanError(f"field {ast.field!r} is not indexed")
        value = ast.value
        if (not ast.verbatim and fm.type is FieldType.TEXT
                and fm.tokenizer == "lowercase"):
            value = value.lower()
        return self._postings_node(ast.field, self._canonical(fm, value), scoring, boost)

    def _lower_full_text(self, ast: Q.FullText, scoring: bool, boost: float) -> Any:
        fm = self._field(ast.field)
        if fm.type is not FieldType.TEXT:
            return self._postings_node(ast.field, self._canonical(fm, ast.text),
                                       scoring, boost)
        if not fm.indexed:
            if fm.fast:
                # fast-only text field: the query text matches the exact
                # stored value on the dictionary column (reference:
                # fast-field search on index:false fields)
                return self._fast_only_term(ast.field, ast.text)
            raise PlanError(f"field {ast.field!r} is not indexed")
        tokens = get_tokenizer(fm.tokenizer)(ast.text)
        if not tokens:
            # ES zero_terms_query: "all" matches everything when the text
            # tokenizes to nothing (e.g. punctuation-only)
            if getattr(ast, "zero_terms", "none") == "all":
                return PMatchAll()
            return PMatchNone()
        if ast.mode in ("bool_prefix_and", "bool_prefix_or"):
            # match_bool_prefix: every analyzed token is a term match
            # except the LAST, which matches as a prefix
            prefix_node = self._lower_phrase_prefix(
                Q.PhrasePrefix(ast.field, tokens[-1].text), scoring, boost)
            term_nodes = [self._postings_node(ast.field, t.text, scoring,
                                              boost)
                          for t in tokens[:-1]]
            clauses = tuple(term_nodes) + (prefix_node,)
            if len(clauses) == 1:
                return clauses[0]
            if ast.mode == "bool_prefix_and":
                return PBool(must=clauses)
            return PBool(should=clauses, minimum_should_match=1)
        if ast.mode == "phrase" and len(tokens) > 1:
            return self._lower_phrase(ast.field, [t.text for t in tokens],
                                      ast.slop, scoring, boost)
        nodes = [self._postings_node(ast.field, t.text, scoring, boost)
                 for t in tokens]
        if len(nodes) == 1:
            return nodes[0]
        if ast.mode in ("and", "phrase"):
            return PBool(must=tuple(nodes))
        return self._or(nodes, scoring=scoring)

    def _lower_phrase(self, field: str, terms: list[str], slop: int,
                      scoring: bool, boost: float) -> Any:
        fm = self._field(field)
        if fm.record != "position":
            raise PlanError(
                f"phrase query on field {field!r} requires record='position'")
        infos = []
        empty = np.array([], dtype=np.int32)
        for term in terms:
            info = self.reader.lookup_term(field, term)
            if info is None:
                if self.absence_sink is not None:
                    self.absence_sink(field, term)
                if self.batch is None:
                    return PMatchNone()
                # batch mode: keep the structure uniform across splits
                return self._precomputed_node(
                    f"{field}.phrase.absent:" + "/".join(terms), empty, empty,
                    field, scoring, boost, df_for_idf=0)
            infos.append(info)
        postings = [self.reader.postings(field, i) for i in infos]
        positions = [self.reader.positions(field, i) for i in infos]
        ids, freqs = phrase_match(postings, positions, [i.df for i in infos],
                                  slop, term_keys=terms)
        key = f"{field}.phrase." + ".".join(str(i.ordinal) for i in infos)
        return self._precomputed_node(key, ids, freqs, field, scoring, boost,
                                      df_for_idf=ids.size)

    def _lower_phrase_prefix(self, ast: Q.PhrasePrefix, scoring: bool, boost: float) -> Any:
        fm = self._field(ast.field)
        tokenizer_name = getattr(ast, "analyzer", None) or fm.tokenizer
        tokens = [t.text for t in get_tokenizer(tokenizer_name)(ast.phrase)]
        if not tokens:
            return PMatchNone()
        td = self.reader.term_dict(ast.field)
        if td is None:
            return PMatchNone()
        prefix = tokens[-1]
        expansions = []
        budget = ast.max_expansions
        for term, _df in td.iter_terms(start=prefix):
            if not term.startswith(prefix):
                break
            expansions.append(term)
            # the exact term is a match, not an "expansion": it does not
            # consume the budget (tantivy prefix semantics)
            if term != prefix:
                budget -= 1
            if budget <= 0:
                break
        if not expansions:
            return PMatchNone()
        if len(tokens) == 1:
            return self._or([self._postings_node(ast.field, t, scoring, boost)
                             for t in expansions], scoring=scoring)
        nodes = [self._lower_phrase(ast.field, tokens[:-1] + [exp], 0, scoring, boost)
                 for exp in expansions]
        return self._or(nodes, scoring=scoring)

    def _lower_pattern(self, field: str, pattern: str, scoring: bool,
                       boost: float, literal_prefix: str = "",
                       case_insensitive: bool = False) -> Any:
        fm = self._field(field)
        td = self.reader.term_dict(field)
        if td is None:
            return PMatchNone()
        compiled = re.compile(pattern,
                              re.IGNORECASE if case_insensitive else 0)
        matches = []
        for term, _df in td.iter_terms(start=literal_prefix or None):
            if literal_prefix and not term.startswith(literal_prefix):
                break
            if compiled.fullmatch(term):
                matches.append(term)
                if len(matches) > MAX_EXPANSIONS:
                    raise PlanError(
                        f"pattern on {field!r} expands to more than {MAX_EXPANSIONS} terms")
        return self._or([self._postings_node(field, t, False, boost) for t in matches])

    def _lower_presence(self, field: str) -> Any:
        fm = self.doc_mapper.field(field)
        if fm is None:
            # ES exists semantics: an unknown field name may be the parent
            # path of mapped dotted fields ("payload" covers "payload.*");
            # a name matching nothing simply matches no documents
            prefix = field + "."
            children = [f for f in self.doc_mapper.field_mappings
                        if f.name.startswith(prefix)
                        and (f.fast or (f.indexed
                                        and f.type is FieldType.TEXT))]
            nodes = [self._lower_presence(f.name) for f in children]
            if self.doc_mapper.mode == "dynamic":
                # per-split dynamic fields from the footer registry: the
                # exact path, or any materialized leaf under it
                for name, meta in self.reader.footer.fields.items():
                    if not meta.get("dynamic"):
                        continue
                    if name == field or name.startswith(prefix):
                        nodes.append(self._dynamic_presence(name, meta))
            if not nodes:
                return PMatchNone()
            return self._or(nodes)
        if fm.fast:
            meta = self.reader.field_meta(field)
            if meta.get("column_kind") == "ordinal":
                slot = self.b.add_array(
                    f"col.{field}.ordinals", lambda: self.reader.column_ordinals(field))
                return PPresence(slot, is_ordinal=True)
            _vals, present_slot = self._column_slots(field)
            return PPresence(present_slot)
        if fm.indexed and fm.type is FieldType.TEXT:
            return PNormPresence(self._fieldnorm_slot(field))
        raise PlanError(f"presence query needs a fast or indexed text field: {field!r}")

    def _dynamic_presence(self, name: str, meta: dict) -> Any:
        """Presence of one materialized dynamic field in this split."""
        kind = meta.get("column_kind")
        if kind == "ordinal":
            slot = self.b.add_array(
                f"col.{name}.ordinals",
                lambda: self.reader.column_ordinals(name))
            return PPresence(slot, is_ordinal=True)
        if kind == "numeric":
            _vals, present_slot = self._column_slots(name)
            return PPresence(present_slot)
        if meta.get("indexed"):
            return PNormPresence(self._fieldnorm_slot(name))
        return PMatchNone()

    def _fast_only_term(self, field: str, value: str) -> Any:
        """Exact term on a fast-only (index:false) text field: an ordinal
        equality interval on the dictionary column."""
        fm = self._field(field)
        return self._lower_text_range(Q.Range(
            field, lower=Q.RangeBound(value, True),
            upper=Q.RangeBound(value, True)), fm)

    def _lower_text_range(self, ast: Q.Range, fm: FieldMapping) -> Any:
        """Lexicographic range on a text field via the sorted ordinal
        column (ordinals are assigned in sorted term order, so the range
        becomes an integer ordinal interval computed host-side — ES range
        on keyword semantics)."""
        import bisect
        if not fm.fast:
            raise PlanError(
                f"range on text field {ast.field!r} requires fast=true")
        meta = self.reader.field_meta(ast.field)
        if meta.get("column_kind") != "ordinal":
            raise PlanError(
                f"range on text field {ast.field!r} needs an ordinal column")
        terms = self.reader.column_dict(ast.field)

        def norm(v: Any) -> str:
            text = str(v)
            return text.lower() if fm.normalizer == "lowercase" else text

        lo_ord = 0
        hi_ord = len(terms) - 1
        if ast.lower is not None:
            v = norm(ast.lower.value)
            lo_ord = (bisect.bisect_left(terms, v) if ast.lower.inclusive
                      else bisect.bisect_right(terms, v))
        if ast.upper is not None:
            v = norm(ast.upper.value)
            hi_ord = (bisect.bisect_right(terms, v) - 1
                      if ast.upper.inclusive
                      else bisect.bisect_left(terms, v) - 1)
        if lo_ord > hi_ord:
            if self.batch is None:
                return PMatchNone()
            lo_ord, hi_ord = 0, -1  # uniform structure, empty interval
        ord_slot = self.b.add_array(
            f"col.{ast.field}.ordinals",
            lambda: self.reader.column_ordinals(ast.field))
        present_slot = self.b.add_array(
            f"col.{ast.field}.ord_present",
            lambda: (self.reader.column_ordinals(ast.field) >= 0)
            .astype(np.uint8))
        lo_slot = self.b.add_scalar(lo_ord, np.int32)
        hi_slot = self.b.add_scalar(hi_ord, np.int32)
        return PRange(ord_slot, present_slot, lo_slot, hi_slot, True, True)

    def _lower_range(self, ast: Q.Range, bounds_are_micros: bool = False) -> Any:  # qwlint: disable=QW001 - int() of host-coerced query bounds when choosing the packed fast path
        """`bounds_are_micros`: bounds on a datetime field are already in
        micros (request-level time filters) — skip input-format parsing."""
        fm = self._field(ast.field)
        if (self.doc_mapper.field(ast.field) is None
                and self.doc_mapper.mode == "dynamic"
                and fm.type is FieldType.TEXT):
            # dynamic path: route by the column this split actually
            # materialized (string→ordinal, numeric→typed values); a
            # split that never saw the field (or coerced it to another
            # class) matches nothing
            meta = self.reader.field_meta(ast.field)
            kind = meta.get("column_kind")
            if kind == "numeric":
                fm = FieldMapping(ast.field,
                                  FieldType(meta.get("col_type", "f64")),
                                  fast=True, indexed=False)
            elif kind != "ordinal":
                return PMatchNone()
        if fm.type is FieldType.TEXT:
            return self._lower_text_range(ast, fm)
        dtype = (np.float64 if fm.type is FieldType.F64
                 else np.uint64 if fm.type is FieldType.U64
                 else np.int64)
        if bounds_are_micros:
            parse = lambda v: int(v)  # noqa: E731
        elif ast.format and fm.type is FieldType.DATETIME:
            from ..utils.datetime_utils import parse_java_time_format
            parse = lambda v: parse_java_time_format(ast.format, str(v))  # noqa: E731
        else:
            parse = lambda v: self._parse_bound(fm, v)  # noqa: E731
        if fm.type is FieldType.DATETIME and fm.fast_precision:
            # bounds truncate to the column precision, matching stored
            # values (reference fast_precision semantics)
            from ..utils.datetime_utils import truncate_to_precision
            base_parse = parse
            parse = lambda v: truncate_to_precision(  # noqa: E731
                base_parse(v), fm.fast_precision)
        lo_val = parse(ast.lower.value) if ast.lower is not None else None
        hi_val = parse(ast.upper.value) if ast.upper is not None else None
        lo_incl = ast.lower.inclusive if ast.lower is not None else True
        hi_incl = ast.upper.inclusive if ast.upper is not None else True

        packed = self._packed_range_slots(ast.field, fm, lo_val, lo_incl,
                                          hi_val, hi_incl)
        if packed is not None:
            return packed

        s32 = self._s32_range_slots(ast.field, fm, lo_val, lo_incl,
                                    hi_val, hi_incl)
        if s32 is not None:
            return PRange(*s32, lo_incl, hi_incl)

        values_slot, present_slot = self._column_slots(ast.field)
        lo_slot = (self.b.add_scalar(lo_val, dtype)
                   if lo_val is not None else -1)
        hi_slot = (self.b.add_scalar(hi_val, dtype)
                   if hi_val is not None else -1)
        zmin_slot, zmax_slot = self._zonemap_slots(ast.field)
        return PRange(values_slot, present_slot, lo_slot, hi_slot,
                      lo_incl, hi_incl, zmin_slot, zmax_slot)

    def _packed_range_slots(self, field: str, fm: FieldMapping, lo_val,  # qwlint: disable=QW001 - int() of numpy packing metadata (bit widths, frame mins) from the column header, pre-dispatch
                            lo_incl: bool, hi_val, hi_incl: bool):
        """Narrow-integer fast path for range predicates over FOR-packed
        columns: bounds rebase host-side into the scaled delta domain
        (`ceil((lo - for_min) / for_scale)` / floor for the upper), so the
        kernel compares the u8/u16/u32 delta lanes against i32 scalars —
        no full-width operands in HBM and no i64 emulation on device.
        EXACT for every bound: stored values are for_min + k*for_scale, so
        the monotone ceil/floor rebase preserves the predicate. Bounds
        normalize to inclusive integers first; out-of-frame bounds clamp
        to span+1 / -1, which match nothing (deltas live in [0, span]).
        Returns a complete PRange (with zonemap gating) or None."""
        if fm.type is FieldType.F64:
            return None  # f64 columns are never packed
        info = self.reader.column_packing(field)
        if info is None:
            return None
        m, s = int(info["for_min"]), int(info["for_scale"])
        meta = self.reader.field_meta(field)
        span = (int(meta["max_value"]) - m) // s  # fits i32 by construction
        if lo_val is None:
            lo_r = 0
        else:
            lo_exact = int(lo_val) + (0 if lo_incl else 1)
            lo_r = -((m - lo_exact) // s)  # ceil((lo - m) / s)
        if hi_val is None:
            hi_r = span
        else:
            hi_exact = int(hi_val) - (0 if hi_incl else 1)
            hi_r = (hi_exact - m) // s     # floor((hi - m) / s)
        lo_r = max(0, min(lo_r, span + 1))
        hi_r = max(-1, min(hi_r, span))
        values_slot = self.b.add_array(
            f"col.{field}.packed",
            lambda: self.reader.column_packed(field)[0])
        present_slot = self.b.add_array(
            f"col.{field}.present",
            lambda: self.reader.column_packed(field)[1])
        lo_slot = self.b.add_scalar(lo_r, np.int32)
        hi_slot = self.b.add_scalar(hi_r, np.int32)
        zmin_slot, zmax_slot = self._zonemap_slots(field)
        return PRange(values_slot, present_slot, lo_slot, hi_slot,
                      True, True, zmin_slot, zmax_slot)

    def _s32_range_slots(self, field: str, fm: FieldMapping, lo_val,  # qwlint: disable=QW001 - int() of host query bounds snapped to the i32-seconds domain, pre-dispatch
                         lo_incl: bool, hi_val, hi_incl: bool):
        """i32-seconds fast path for datetime range filters (the range
        twin of the date_histogram s32 path): i64 compares are emulated
        on TPU and the µs values column is 2x the HBM bytes of the
        derived seconds column. EXACT for whole-second inclusive-lower /
        exclusive-upper bounds regardless of sub-second values, because
        floor is monotone: ts >= L*1e6 <=> floor(ts/1e6) >= L, and
        ts < U*1e6 <=> floor(ts/1e6) < U. Any other bound shape (or a
        batch plan, whose per-split base would break uniformity) returns
        None and takes the i64 path. Returns (values_slot, present_slot,
        lo_slot, hi_slot) or None."""
        if (fm.type is not FieldType.DATETIME or self.batch is not None
                or (lo_val is not None
                    and not (lo_incl and lo_val % 1_000_000 == 0))
                or (hi_val is not None
                    and not (not hi_incl and hi_val % 1_000_000 == 0))):
            return None
        meta = self.reader.field_meta(field)
        vmin, vmax = meta.get("min_value"), meta.get("max_value")
        if vmin is None:
            return None
        base_s = vmin // 1_000_000
        # every compared quantity must fit i32 after the base shift;
        # out-of-split bounds clamp (equivalent: they pass/fail all docs)
        span_ok = (vmax // 1_000_000 - base_s) < 2**31 - 2
        if not span_ok:
            return None

        def offset(bound_micros: int) -> int:
            shifted = bound_micros // 1_000_000 - base_s
            return int(max(-(2**31) + 2, min(shifted, 2**31 - 2)))

        values_slot, present_slot = self._s32_column_slots(field, base_s)
        lo_slot = (self.b.add_scalar(offset(lo_val), np.int32)
                   if lo_val is not None else -1)
        hi_slot = (self.b.add_scalar(offset(hi_val), np.int32)
                   if hi_val is not None else -1)
        return values_slot, present_slot, lo_slot, hi_slot

    def _or(self, nodes: list, scoring: bool = False) -> Any:
        nodes = [n for n in nodes if not isinstance(n, PMatchNone)]
        if not nodes:
            return PMatchNone()
        if len(nodes) == 1:
            return nodes[0]
        return PBool(should=tuple(nodes))

    # --- aggregations -----------------------------------------------------
    def lower_metric(self, spec: MetricAgg) -> MetricSlots:
        fm = self._field(spec.field)
        if spec.kind == "cardinality":
            return self._lower_cardinality(spec, fm)
        if fm.type is FieldType.TEXT:
            raise PlanError(f"metric aggregation on text field {spec.field!r}")
        values_slot, present_slot = self._column_slots(spec.field)
        return MetricSlots(spec.name, spec.kind, values_slot, present_slot,
                           tuple(spec.percents),
                           keyed=getattr(spec, "keyed", True))

    def _lower_cardinality(self, spec: MetricAgg,
                           fm: FieldMapping) -> MetricSlots:
        """Cardinality via HLL registers computed on device. Text columns
        gather host-precomputed per-ordinal TERM hashes so register merges
        are consistent across splits (ordinals are split-local)."""
        if not fm.fast:
            raise PlanError(
                f"cardinality aggregation requires fast field {spec.field!r}")
        meta = self.reader.field_meta(spec.field)
        if meta.get("column_kind") == "ordinal":
            ord_slot = self.b.add_array(
                f"col.{spec.field}.ordinals",
                lambda: self.reader.column_ordinals(spec.field))

            def term_hashes() -> np.ndarray:
                from ..ops.aggs import hll_hash_bytes
                terms = self.reader.column_dict(spec.field)
                return np.array([hll_hash_bytes(t.encode()) for t in terms]
                                or [0], dtype=np.uint64)

            hash_slot = self.b.add_array(
                f"col.{spec.field}.ord_hash", term_hashes)
            return MetricSlots(spec.name, "cardinality", ord_slot, -1,
                               hash_slot=hash_slot)
        values_slot, present_slot = self._column_slots(spec.field)
        return MetricSlots(spec.name, "cardinality", values_slot,
                           present_slot)

    def lower_agg(self, spec: AggSpec) -> Any:
        if isinstance(spec, MetricAgg):
            return MetricAggExec(spec.name, self.lower_metric(spec))
        if isinstance(spec, CompositeAgg):
            return self._lower_composite_agg(spec)
        return self._lower_bucket_tree(spec, spec.name, parent_space=1)

    def _lower_bucket_tree(self, spec: AggSpec, path: str,
                           parent_space: int) -> "BucketAggExec":
        """Lower one bucket agg and its children recursively. Children
        resolve batch overrides under path-qualified keys ("a>b>c"): ES
        names are only unique per level. `parent_space` is the flattened
        bucket count above this node — the chain product is capped."""
        exec_ = self._lower_bucket_agg(spec, override_key=path)
        space = parent_space * max(exec_.num_buckets, 1)
        if space > MAX_BUCKETS and parent_space > 1:
            # the cap guards the flattened PRODUCT space; a single level's
            # own bucket count is governed by its own kind's limits
            # (histogram caps at lowering; terms ordinal spaces uncapped)
            raise PlanError(
                f"nested aggregation {path!r} would create {space} "
                f"buckets (max {MAX_BUCKETS})")
        children = []
        for sub_spec in getattr(spec, "sub_buckets", ()):
            child = self._lower_bucket_tree(
                sub_spec, f"{path}>{sub_spec.name}", space)
            if exec_.kind == "terms_mv" or child.kind == "terms_mv":
                raise PlanError(
                    "multivalued terms aggs cannot nest (pair arrays and "
                    "doc-space buckets have different shapes)")
            children.append(child)
        if children:
            from dataclasses import replace as dc_replace
            exec_ = dc_replace(exec_, subs=tuple(children))
        return exec_

    def _lower_bucket_agg(self, spec: AggSpec,  # qwlint: disable=QW001 - int() of agg-spec JSON sizes/intervals and numpy column stats while sizing static bucket counts
                          override_key: Optional[str] = None) -> "BucketAggExec":
        override_key = override_key or spec.name
        if isinstance(spec, DateHistogramAgg):
            fm = self._field(spec.field)
            if fm.type is not FieldType.DATETIME or not fm.fast:
                raise PlanError("date_histogram requires a fast datetime field")
            meta = self.reader.field_meta(spec.field)
            vmin, vmax = meta.get("min_value"), meta.get("max_value")
            interval = spec.interval_micros
            # resolve the bucket space (batch-global origin wins)
            if self.batch is not None and override_key in self.batch.get("histograms", {}):
                origin, num_buckets = self.batch["histograms"][override_key]
            elif vmin is None:
                origin, num_buckets = 0, 1
            else:
                lo, hi = vmin, vmax
                if spec.extended_bounds:
                    lo = min(lo, spec.extended_bounds[0])
                    hi = max(hi, spec.extended_bounds[1])
                # ES `offset` shifts every bucket boundary: buckets start at
                # k*interval + offset
                offset = getattr(spec, "offset_micros", 0)
                origin = aligned_origin(lo, interval, offset)
                num_buckets = int((hi - origin) // interval) + 1
                if num_buckets > MAX_BUCKETS:
                    raise PlanError(
                        f"date_histogram would create {num_buckets} buckets "
                        f"(max {MAX_BUCKETS})")
            # i32 seconds fast path: i64 division is emulated on TPU; for
            # whole-second intervals the bucket index computes on a derived
            # (ts_micros//1e6 - base_s) i32 column (base cancels per split)
            base_s = (vmin // 1_000_000) if vmin is not None else 0
            # guard the full i32 range: value offsets span (vmax-vmin)/1e6 and
            # the in-kernel (value - origin) subtraction adds |origin offset|;
            # batches must stay on the i64 path (per-split vmin would lower
            # splits to different structures and break batch uniformity)
            use_s32 = (interval % 1_000_000 == 0
                       and origin % 1_000_000 == 0
                       and self.batch is None
                       and vmin is not None
                       and (vmax // 1_000_000 - base_s)
                       + abs(origin // 1_000_000 - base_s) < 2**31)
            if use_s32:
                values_slot, present_slot = self._s32_column_slots(
                    spec.field, base_s)
                origin_slot = self.b.add_scalar(
                    origin // 1_000_000 - base_s, np.int32)
                interval_slot = self.b.add_scalar(interval // 1_000_000, np.int32)
            else:
                values_slot, present_slot = self._column_slots(spec.field)
                origin_slot = self.b.add_scalar(origin, np.int64)
                interval_slot = self.b.add_scalar(interval, np.int64)
            return BucketAggExec(
                spec.name, "date_histogram", values_slot, present_slot,
                num_buckets, origin_slot, interval_slot,
                metrics=self._metric_tuple(spec.sub_metrics),
                host_info={"interval": interval, "origin": origin,
                           "min_doc_count": spec.min_doc_count,
                           "extended_bounds": spec.extended_bounds,
                           "offset": getattr(spec, "offset_micros", 0)})
        if isinstance(spec, HistogramAgg):
            fm = self._field(spec.field)
            values_slot, present_slot = self._column_slots(spec.field)
            if self.batch is not None and override_key in self.batch.get("histograms", {}):
                origin, num_buckets = self.batch["histograms"][override_key]
                return BucketAggExec(
                    spec.name, "histogram", values_slot, present_slot, num_buckets,
                    self.b.add_scalar(origin, np.float64),
                    self.b.add_scalar(spec.interval, np.float64),
                    metrics=self._metric_tuple(spec.sub_metrics),
                    host_info={"interval": spec.interval, "origin": origin,
                               "min_doc_count": spec.min_doc_count})
            meta = self.reader.field_meta(spec.field)
            vmin, vmax = meta.get("min_value"), meta.get("max_value")
            if vmin is None:
                vmin = vmax = 0
            origin = aligned_origin(vmin, spec.interval)
            num_buckets = int((vmax - origin) // spec.interval) + 1
            if num_buckets > MAX_BUCKETS:
                raise PlanError(f"histogram would create {num_buckets} buckets")
            return BucketAggExec(
                spec.name, "histogram", values_slot, present_slot, num_buckets,
                self.b.add_scalar(origin, np.float64),
                self.b.add_scalar(spec.interval, np.float64),
                metrics=self._metric_tuple(spec.sub_metrics),
                host_info={"interval": spec.interval, "origin": origin,
                           "min_doc_count": spec.min_doc_count})
        if isinstance(spec, TermsAgg):
            return self._lower_terms_agg(spec)
        if isinstance(spec, RangeAgg):
            fm = self._field(spec.field)
            if fm.type is FieldType.TEXT or not fm.fast:
                raise PlanError(
                    f"range aggregation requires a fast numeric field: "
                    f"{spec.field!r}")
            values_slot, present_slot = self._column_slots(spec.field)
            froms = np.array([lo if lo is not None else -np.inf
                              for _, lo, _ in spec.ranges], dtype=np.float64)
            tos = np.array([hi if hi is not None else np.inf
                            for _, _, hi in spec.ranges], dtype=np.float64)
            froms_slot = self.b.add_array(
                f"agg.{spec.name}.range_froms", lambda: froms)
            tos_slot = self.b.add_array(
                f"agg.{spec.name}.range_tos", lambda: tos)
            return BucketAggExec(
                spec.name, "range", values_slot, present_slot,
                len(spec.ranges),
                froms_slot=froms_slot, tos_slot=tos_slot,
                metrics=self._metric_tuple(spec.sub_metrics),
                host_info={"ranges": list(spec.ranges),
                           "min_doc_count": 0})
        raise PlanError(f"unsupported aggregation {spec!r}")

    def _metric_tuple(self, specs: tuple[MetricAgg, ...]) -> tuple[MetricSlots, ...]:
        return tuple(self.lower_metric(m) for m in specs)

    def _terms_host_info(self, spec: TermsAgg, keys) -> dict:
        """The one terms finalization-parameter dict (four call sites)."""
        return {"keys": keys, "size": spec.size,
                "min_doc_count": spec.min_doc_count,
                "order_desc": spec.order_by_count_desc,
                "order_target": spec.order_target,
                "split_size": spec.split_size}

    def _lower_terms_agg(self, spec: TermsAgg) -> Any:
        fm = self._field(spec.field)
        if not fm.fast:
            raise PlanError(f"terms aggregation requires fast field: {spec.field!r}")
        meta = self.reader.field_meta(spec.field)
        if meta.get("multivalued") and self.batch is not None:
            # multivalued pair arrays have split-dependent shapes: the
            # batch path cannot host them — fall back per split
            raise PlanError(
                f"multivalued terms agg {spec.field!r} is per-split")
        if self.batch is not None and spec.field in self.batch.get("terms_dicts", {}):
            # remap this split's local ordinals into the batch-global dictionary
            global_of = self.batch["terms_dicts"][spec.field]
            cardinality = self.batch["terms_cards"][spec.field]
            global_keys = self.batch["terms_keys"][spec.field]

            def fetch_remapped():
                if meta.get("column_kind") == "ordinal":
                    local = self.reader.column_ordinals(spec.field)
                    local_keys = self.reader.column_dict(spec.field)
                else:
                    local, local_keys = self._ordinalize_numeric(spec.field)
                lut = np.array([global_of[k] for k in local_keys], dtype=np.int32)
                out = np.full_like(local, -1)
                valid = local >= 0
                out[valid] = lut[local[valid]]
                return out

            return BucketAggExec(
                spec.name, "terms",
                self.b.add_array(f"col.{spec.field}.ordinals_global", fetch_remapped),
                -1, max(cardinality, 1),
                metrics=self._metric_tuple(spec.sub_metrics),
                host_info=self._terms_host_info(spec, global_keys))
        if meta.get("column_kind") == "ordinal" and meta.get("multivalued"):
            if self.batch is not None:
                raise PlanError(
                    f"multivalued terms agg {spec.field!r} is per-split "
                    "(batch path falls back)")
            if spec.sub_metrics or spec.sub_buckets:
                raise PlanError(
                    f"sub-aggregations under multivalued terms "
                    f"{spec.field!r} are not supported yet")
            keys = self.reader.column_dict(spec.field)
            ords_slot = self.b.add_array(
                f"col.{spec.field}.mv_ords",
                lambda: self.reader.array(f"col.{spec.field}.mv_ords"))
            docs_slot = self.b.add_array(
                f"col.{spec.field}.mv_docs",
                lambda: self.reader.array(f"col.{spec.field}.mv_docs"))
            return BucketAggExec(
                spec.name, "terms_mv", ords_slot, docs_slot,
                max(len(keys), 1),
                host_info=self._terms_host_info(spec, keys))
        if meta.get("column_kind") == "ordinal":
            ordinals_slot = self.b.add_array(
                f"col.{spec.field}.ordinals", lambda: self.reader.column_ordinals(spec.field))
            keys = self.reader.column_dict(spec.field)
            return BucketAggExec(
                spec.name, "terms", ordinals_slot, -1, max(len(keys), 1),
                metrics=self._metric_tuple(spec.sub_metrics),
                host_info=self._terms_host_info(spec, keys))
        # numeric column: ordinalize host-side once per split (cached)
        ordinals, uniques = self._ordinalize_numeric(spec.field)
        return BucketAggExec(
            spec.name, "terms",
            self.b.add_array(f"col.{spec.field}.ordinals_dyn", lambda: ordinals),
            -1, max(len(uniques), 1),
            metrics=self._metric_tuple(spec.sub_metrics),
            host_info=self._terms_host_info(spec, uniques))

    def _lower_composite_agg(self, spec: CompositeAgg) -> CompositeAggExec:
        if self.batch is not None:
            # split-local ordinals/origins in the key encoding: the batch
            # (vmapped multi-split) path falls back per split like
            # multivalued terms
            raise PlanError(f"composite agg {spec.name!r} is per-split")
        execs = []
        infos = []
        for si, src in enumerate(spec.sources):
            after_val = spec.after[si] if spec.after is not None else None
            execs.append(self._lower_composite_source(
                spec.name, src, spec.after is not None, after_val, infos))
        children = []
        for sub_spec in getattr(spec, "sub_buckets", ()):
            child = self._lower_bucket_tree(
                sub_spec, f"{spec.name}>{sub_spec.name}",
                parent_space=spec.size)
            if child.kind == "terms_mv":
                raise PlanError(
                    "multivalued terms aggs cannot nest under composite "
                    "(pair arrays and doc-space buckets have different "
                    "shapes)")
            children.append(child)
        return CompositeAggExec(
            name=spec.name, sources=tuple(execs), size=spec.size,
            has_after=spec.after is not None,
            metrics=self._metric_tuple(spec.sub_metrics),
            subs=tuple(children),
            host_info={"sources": infos, "size": spec.size,
                       "metric_kinds": {m.name: m.kind
                                        for m in spec.sub_metrics}})

    def _lower_composite_source(self, agg_name: str, src: CompositeSource,  # qwlint: disable=QW001 - int()/float()/.item() decode split-local key metadata from host numpy column stats into the source spec
                                has_after: bool, after_val,
                                infos: list) -> CompositeSourceExec:
        fm = self._field(src.field)
        if not fm.fast:
            raise PlanError(
                f"composite {agg_name!r}: source field {src.field!r} must "
                "be a fast field")
        meta = self.reader.field_meta(src.field)
        if meta.get("multivalued"):
            raise PlanError(
                f"composite {agg_name!r}: multivalued source field "
                f"{src.field!r} is not supported")

        def after_slot_for(encoded) -> int:
            if not has_after:
                return -1
            clamped = int(np.clip(encoded, -(2**31) + 1, 2**31 - 2))
            return self.b.add_scalar(clamped, np.int32)

        if src.kind == "terms":
            if meta.get("column_kind") == "ordinal":
                values_slot = self.b.add_array(
                    f"col.{src.field}.ordinals",
                    lambda: self.reader.column_ordinals(src.field))
                keys = self.reader.column_dict(src.field)
            else:
                ordinals, uniques = self._ordinalize_numeric(src.field)
                values_slot = self.b.add_array(
                    f"col.{src.field}.ordinals_dyn", lambda: ordinals)
                keys = uniques
            enc = 0
            if after_val is not None:
                import bisect
                keys_list = list(keys)
                if keys_list and not isinstance(after_val,
                                                type(keys_list[0])):
                    # the dictionary's type is authoritative: coerce the
                    # marker (a term field holding literal "i64:42" was
                    # prefix-decoded to int) rather than letting bisect
                    # raise a TypeError mid-split
                    try:
                        after_val = type(keys_list[0])(after_val)
                    except (TypeError, ValueError):
                        raise PlanError(
                            f"composite after value for source "
                            f"{src.name!r} does not match the field type")
                pos = bisect.bisect_left(keys_list, after_val)
                if pos < len(keys_list) and keys_list[pos] == after_val:
                    enc = (pos + 1) * 2       # exact: strictly past it
                else:
                    enc = pos * 2 + 1         # between split-local keys
                enc = max(enc, 1)             # non-null after excludes null
            infos.append({"name": src.name, "kind": "terms",
                          "keys": [k.item() if isinstance(k, np.generic)
                                   else k for k in keys]})
            return CompositeSourceExec(
                "terms_ord", values_slot,
                missing_bucket=src.missing_bucket,
                after_slot=after_slot_for(enc))
        if src.kind == "date_histogram":
            if fm.type is not FieldType.DATETIME:
                raise PlanError(
                    f"composite {agg_name!r}: date_histogram source "
                    f"requires a datetime field, got {src.field!r}")
            interval = src.interval_micros
            vmin = meta.get("min_value")
            vmax = meta.get("max_value")
            origin = 0 if vmin is None else aligned_origin(vmin, interval)
            # the key encoding (idx+1)*2 must fit i32, a looser bound than
            # MAX_BUCKETS (composite never materializes a bucket array)
            if vmax is not None and (vmax - origin) // interval > 2**29:
                raise PlanError(
                    f"composite {agg_name!r}: date_histogram interval too "
                    "fine for the split's time range")
            enc = 0
            if after_val is not None:
                micros = int(float(after_val) * 1000)  # ES after is ms
                enc = max(int((micros - origin) // interval + 1) * 2, 1)
            infos.append({"name": src.name, "kind": "date_histogram",
                          "origin": int(origin), "interval": int(interval)})
            # whole-second intervals ride the same derived-i32 seconds
            # column as the plain date_histogram lowering (i64 division is
            # emulated on TPU); origin is interval-aligned so origin%1s==0
            base_s = (vmin // 1_000_000) if vmin is not None else 0
            use_s32 = (interval % 1_000_000 == 0
                       and vmin is not None
                       and (vmax // 1_000_000 - base_s)
                       + abs(origin // 1_000_000 - base_s) < 2**31)
            if use_s32:
                values_slot, present_slot = self._s32_column_slots(
                    src.field, base_s)
                origin_slot = self.b.add_scalar(
                    origin // 1_000_000 - base_s, np.int32)
                interval_slot = self.b.add_scalar(
                    interval // 1_000_000, np.int32)
            else:
                values_slot, present_slot = self._column_slots(src.field)
                origin_slot = self.b.add_scalar(origin, np.int64)
                interval_slot = self.b.add_scalar(interval, np.int64)
            return CompositeSourceExec(
                "date_histogram", values_slot, present_slot,
                origin_slot=origin_slot, interval_slot=interval_slot,
                missing_bucket=src.missing_bucket,
                after_slot=after_slot_for(enc))
        # histogram
        if fm.type is FieldType.TEXT:
            raise PlanError(
                f"composite {agg_name!r}: histogram source requires a "
                f"numeric field, got {src.field!r}")
        interval_f = src.interval
        vmin = meta.get("min_value")
        vmax = meta.get("max_value")
        origin_f = 0.0 if vmin is None else aligned_origin(vmin, interval_f)
        # i32 key-encoding bound, looser than MAX_BUCKETS (see above)
        if vmax is not None and (vmax - origin_f) / interval_f > 2**29:
            raise PlanError(
                f"composite {agg_name!r}: histogram interval too fine for "
                "the split's value range")
        values_slot, present_slot = self._column_slots(src.field)
        enc = 0
        if after_val is not None:
            idx = int(np.floor((float(after_val) - origin_f) / interval_f))
            enc = max((idx + 1) * 2, 1)
        infos.append({"name": src.name, "kind": "histogram",
                      "origin": origin_f, "interval": interval_f})
        return CompositeSourceExec(
            "histogram", values_slot, present_slot,
            origin_slot=self.b.add_scalar(origin_f, np.float64),
            interval_slot=self.b.add_scalar(interval_f, np.float64),
            missing_bucket=src.missing_bucket,
            after_slot=after_slot_for(enc))

    def _ordinalize_numeric(self, field: str):
        return ordinalize_numeric_column(self.reader, field)

    def _s32_column_slots(self, field: str, base_s: int) -> tuple[int, int]:
        """(values_slot, present_slot) of the derived i32-seconds column —
        the ONE place its cache keys and derivation are defined (shared by
        the range fast path and both date_histogram lowerings)."""
        values_slot = self.b.add_array(
            f"col.{field}.values_s32",
            lambda: self._seconds_column(field, base_s))
        # present column only — the i64 values column is not read
        present_slot = self.b.add_array(
            f"col.{field}.present",
            lambda: self.reader.column_values(field)[1])
        return values_slot, present_slot

    def _seconds_column(self, field: str, base_s: int) -> np.ndarray:
        """Derived i32 seconds column, cached per reader."""
        cache_key = f"_s32.{field}.{base_s}"
        cache = getattr(self.reader, "_dyn_cache", None)
        if cache is None:
            cache = self.reader._dyn_cache = {}
        cached = cache.get(cache_key)
        if cached is None:
            values, _present = self.reader.column_values(field)
            cached = (values // 1_000_000 - base_s).astype(np.int32)
            cache[cache_key] = cached
        return cached

    def _is_text_sort(self, field: str) -> bool:
        """True for dict-ordinal (raw text fast) columns: sortable on device
        by local ordinal — the dictionary is lex-sorted, so per-split
        ordinal order == string order. Cross-split comparison happens on
        the DECODED term strings in the collector (the reference likewise
        returns term bytes as leaf sort values for string sorts)."""
        fm = self._field(field)
        if fm.type is not FieldType.TEXT:
            return False
        if not fm.fast:
            raise PlanError(f"sorting by text field {field!r} requires "
                            f"fast: true")
        return True

    def _ordinal_sort_slots(self, field: str) -> tuple[int, int]:
        def fetch_ordinals():
            return self.reader.column_ordinals(field)
        values_slot = self.b.add_array(f"col.{field}.ordinals", fetch_ordinals)
        # presence is derivable on-device (ordinal >= 0): the sentinel slot
        # avoids shipping + keeping a whole bool column in HBM
        return values_slot, PRESENT_FROM_VALUES

    # --- sort -------------------------------------------------------------
    def lower_sort(self, sort_field: str, order: str,
                   sort2_field: Optional[str] = None,
                   sort2_order: str = "desc") -> SortExec:
        descending = order == "desc"
        if sort_field == "_score":
            primary = SortExec("score", descending)
        elif sort_field == "_doc":
            primary = SortExec("doc", descending)
        elif self._is_text_sort(sort_field):
            if sort2_field is not None and sort2_field != "_doc":
                raise PlanError(
                    f"text-field sort {sort_field!r} cannot be combined "
                    f"with a secondary sort key")
            values_slot, present_slot = self._ordinal_sort_slots(sort_field)
            return SortExec("column", descending, values_slot, present_slot)
        else:
            values_slot, present_slot = self._column_slots(sort_field)
            primary = SortExec("column", descending, values_slot, present_slot)
        if sort2_field is None or sort2_field == "_doc" or primary.by == "doc":
            # doc order is the implicit final tie-break already
            return primary
        from dataclasses import replace as dc_replace
        if sort2_field == "_score":
            return dc_replace(primary, by2="score",
                              descending2=sort2_order == "desc")
        if self._is_text_sort(sort2_field):
            raise PlanError(
                f"text field {sort2_field!r} is not supported as a "
                f"secondary sort key")
        v2, p2 = self._column_slots(sort2_field)
        return dc_replace(primary, by2="column",
                          descending2=sort2_order == "desc",
                          values2_slot=v2, present2_slot=p2)


def ordinalize_numeric_column(reader: SplitReader, field: str):  # qwlint: disable=QW001 - .item() over host numpy uniques building the ordinal dictionary; reader columns are numpy, never device arrays
    """(ordinals, unique_values) of a numeric fast column, cached per reader
    (terms aggregations over numeric fields need a dictionary)."""
    cache_key = f"_ordinalized.{field}"
    cached = getattr(reader, "_dyn_cache", {}).get(cache_key)
    if cached is not None:
        return cached
    values, present = reader.column_values(field)
    real = values[: reader.num_docs][present[: reader.num_docs].astype(bool)]
    uniques = np.unique(real)
    ordinals = np.full(reader.num_docs_padded, -1, dtype=np.int32)
    mask = present.astype(bool)
    ordinals[mask] = np.searchsorted(uniques, values[mask]).astype(np.int32)
    result = (ordinals, [v.item() for v in uniques])
    if not hasattr(reader, "_dyn_cache"):
        reader._dyn_cache = {}
    reader._dyn_cache[cache_key] = result
    return result


def _wildcard_prefix(pattern: str) -> str:
    for i, ch in enumerate(pattern):
        if ch in "*?[":
            return pattern[:i]
    return pattern


def _regex_prefix(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch in ".*+?()[]{}|^$\\":
            break
        out.append(ch)
    return "".join(out)


def lower_request(
    query_ast: Q.QueryAst,
    doc_mapper: DocMapper,
    reader: SplitReader,
    agg_specs: list[AggSpec],
    sort_field: str = "_score",
    sort_order: str = "desc",
    sort2_field: Optional[str] = None,
    sort2_order: str = "desc",
    start_timestamp: Optional[int] = None,
    end_timestamp: Optional[int] = None,
    batch_overrides: Optional[dict] = None,
    search_after: Optional[tuple] = None,  # (internal_value, relation, doc_id)
    absence_sink=None,
    sort_value_threshold: Optional[float] = None,  # internal higher-is-better
    mask_override: Optional[np.ndarray] = None,  # packed predicate mask
    mask_key: Optional[str] = None,              # its array-cache key
) -> LoweredPlan:
    """Full request lowering: query + request-level time filter + sort + aggs.

    `mask_override` (Tier A, search/mask_cache.py): a cached packed filter
    bitmask standing in for the whole predicate — query lowering AND the
    time-filter wrap are skipped (the digest already covers both), so no
    predicate column is fetched or staged. Sort and agg columns lower as
    usual. `mask_key` keys the mask's array slot so warm splits reuse its
    device copy through `ResidentColumnStore` like any column."""
    low = Lowering(doc_mapper, reader, batch_overrides, absence_sink)
    scoring = "_score" in (sort_field, sort2_field)
    if mask_override is not None:
        if scoring:
            raise PlanError("mask_override cannot serve scoring requests")
        root = PMaskRef(packed_slot=low.b.add_array(
            mask_key or "mask.override", lambda: mask_override))
        return _finish_lowering(low, root, reader, agg_specs, sort_field,
                                sort_order, sort2_field, sort2_order,
                                search_after, sort_value_threshold)
    if (sort_value_threshold is not None and batch_overrides is None
            and not agg_specs and search_after is None
            and start_timestamp is None and end_timestamp is None
            and sort_field == "_score" and sort_order == "desc"
            and sort2_field is None):
        # impact prefix cutoff: sound only when the request is EXACTLY one
        # scoring term — a bare Term/FullText (possibly boosted), never a
        # Bool, so no filter/should sibling can rescue a dropped posting
        # and the term's df is the exact matched-doc count
        node = query_ast
        while isinstance(node, Q.Boost):
            node = node.underlying
        if isinstance(node, (Q.Term, Q.FullText)):
            from .pruning import scoring_terms
            terms = scoring_terms(query_ast, doc_mapper)
            if terms is not None and len(terms) == 1:
                low._impact_term = terms[0]
                low._impact_threshold = sort_value_threshold
    root = low.lower(query_ast, scoring=scoring)
    if start_timestamp is not None or end_timestamp is not None:
        ts_field = doc_mapper.timestamp_field
        if ts_field is None:
            raise PlanError("time-range request on an index without timestamp field")
        # end_timestamp is exclusive (reference: SearchRequest semantics)
        ts_node = low._lower_range(Q.Range(
            ts_field,
            lower=Q.RangeBound(start_timestamp, True) if start_timestamp is not None else None,
            upper=Q.RangeBound(end_timestamp, False) if end_timestamp is not None else None,
        ), bounds_are_micros=True)
        root = PBool(must=(root,), filter=(ts_node,))
    return _finish_lowering(low, root, reader, agg_specs, sort_field,
                            sort_order, sort2_field, sort2_order,
                            search_after, sort_value_threshold)


def _finish_lowering(  # qwlint: disable=QW001 - float()/int() of search_after/threshold wire values (python scalars off the root merge) staged as plan scalars
    low: "Lowering",
    root: Any,
    reader: SplitReader,
    agg_specs: list[AggSpec],
    sort_field: str,
    sort_order: str,
    sort2_field: Optional[str],
    sort2_order: str,
    search_after: Optional[tuple],
    sort_value_threshold: Optional[float],
) -> LoweredPlan:
    """Sort/agg/search-after/threshold lowering shared by the query path
    and the mask-override path of `lower_request`."""
    sort = low.lower_sort(sort_field, sort_order, sort2_field, sort2_order)
    sort_text_field = sort_field if (
        sort_field not in ("_score", "_doc")
        and low._is_text_sort(sort_field)) else None
    aggs = [low.lower_agg(spec) for spec in agg_specs]
    sa_relation, sa_value_slot, sa_value2_slot, sa_doc_slot = "none", -1, -1, -1
    if search_after is not None:
        sa_value, sa_value2, sa_relation, sa_doc = search_after
        sa_value_slot = low.b.add_scalar(float(sa_value), np.float64)
        if sa_value2 is not None:
            sa_value2_slot = low.b.add_scalar(float(sa_value2), np.float64)
        sa_doc_slot = low.b.add_scalar(int(sa_doc), np.int32)
    threshold_slot = -1
    if (sort_value_threshold is not None and sort_field != "_doc"
            and sort_text_field is None):
        # text sorts compare split-local ordinals — a cross-split threshold
        # is meaningless there, so the pushdown silently disarms
        threshold_slot = low.b.add_scalar(
            float(sort_value_threshold), np.float64)
    return LoweredPlan(
        root=root, sort=sort, aggs=aggs,
        arrays=low.b.arrays, array_keys=low.b.array_keys, scalars=low.b.scalars,
        num_docs=reader.num_docs, num_docs_padded=reader.num_docs_padded,
        search_after_relation=sa_relation,
        sa_value_slot=sa_value_slot, sa_value2_slot=sa_value2_slot,
        sa_doc_slot=sa_doc_slot,
        sort_text_field=sort_text_field,
        threshold_slot=threshold_slot,
        rebase=low.rebase,
        count_override=low.count_override,
    )


# --------------------------------------------------------------------------
# slot classification (staged-bytes attribution, observability/metrics.py)

def _query_node_slots(node: Any, out: set[int]) -> None:
    if isinstance(node, PPostings):
        for slot in (node.ids_slot, node.tfs_slot, node.norm_slot,
                     node.impact_bmax_slot):
            if slot >= 0:
                out.add(slot)
    elif isinstance(node, PRange):
        for slot in (node.values_slot, node.present_slot,
                     node.zmin_slot, node.zmax_slot):
            if slot >= 0:
                out.add(slot)
    elif isinstance(node, PPresence):
        if node.present_slot >= 0:
            out.add(node.present_slot)
    elif isinstance(node, PNormPresence):
        if node.norm_slot >= 0:
            out.add(node.norm_slot)
    elif isinstance(node, PBool):
        for clause in (*node.must, *node.must_not, *node.should, *node.filter):
            _query_node_slots(clause, out)
    # PMatchAll / PMatchNone / PMaskRef: no predicate columns. A PMaskRef's
    # packed slot is deliberately NOT a predicate column — it's the cached
    # substitute for them, and counting it would make the "zero predicate
    # staging on a warm hit" invariant unassertable.


def _metric_slots(metric: MetricSlots, out: set[int]) -> None:
    for slot in (metric.values_slot, metric.present_slot, metric.hash_slot):
        if slot >= 0:
            out.add(slot)


def _agg_slots(agg: Any, out: set[int]) -> None:
    if isinstance(agg, BucketAggExec):
        for slot in (agg.values_slot, agg.present_slot,
                     agg.froms_slot, agg.tos_slot):
            if slot >= 0:
                out.add(slot)
        for metric in agg.metrics:
            _metric_slots(metric, out)
        for sub in agg.subs:
            _agg_slots(sub, out)
    elif isinstance(agg, MetricAggExec):
        _metric_slots(agg.metric, out)
    elif isinstance(agg, CompositeAggExec):
        for source in agg.sources:
            for slot in (source.values_slot, source.present_slot):
                if slot >= 0:
                    out.add(slot)
        for metric in agg.metrics:
            _metric_slots(metric, out)
        for sub in agg.subs:
            _agg_slots(sub, out)


def predicate_only_slots(plan: LoweredPlan) -> set[int]:
    """Array slots referenced ONLY by the query root — the staging a
    predicate-mask hit avoids. Slots shared with sort or aggs are excluded
    (a mask hit still stages those), as are sort/agg-only slots."""
    root_slots: set[int] = set()
    _query_node_slots(plan.root, root_slots)
    other_slots: set[int] = set()
    for slot in (plan.sort.values_slot, plan.sort.present_slot,
                 plan.sort.values2_slot, plan.sort.present2_slot):
        if slot >= 0:
            other_slots.add(slot)
    for agg in plan.aggs:
        _agg_slots(agg, other_slots)
    return root_slots - other_slots


# --------------------------------------------------------------------------
# chunked-execution slot classification (search/chunkexec.py)

@dataclass(frozen=True)
class ChunkSlotPlan:
    """How each array slot of a plan partitions along the doc dimension.

    `chunkexec` slices a dense plan into doc-span sub-plans; every slot
    must fall into exactly one class or the plan is chunk-ineligible:

    - `posting_pairs`: (ids_slot, tfs_slot) posting lists — doc ids are
      filtered to the chunk's doc window and rebased host-side (out-of-
      window lanes get the chunk's OOB scatter sentinel).
    - `doc_slots`: per-padded-doc columns (values, presence, fieldnorms,
      ordinals) — sliced `[base : base + span]`.
    - `zone_slots`: per-ZONEMAP_BLOCK zonemaps — sliced by block index.
    - `packed_slots`: np.packbits doc bitmasks — sliced by byte index.
    - `full_slots`: bounded non-doc tables (range-agg bounds, per-ordinal
      hash tables, impact block maxima) — passed through whole.
    """
    posting_pairs: tuple[tuple[int, int], ...]
    doc_slots: frozenset
    zone_slots: frozenset
    packed_slots: frozenset
    full_slots: frozenset


def chunk_slot_plan(plan: LoweredPlan) -> Optional[ChunkSlotPlan]:
    """Classify every array slot for doc-dimension chunking, or return None
    when the plan is chunk-ineligible (composite aggs sort the whole doc
    space at once; multivalued pair arrays gather by global doc id; any
    slot the walkers cannot attribute is conservatively disqualifying)."""
    from ..index.format import ZONEMAP_BLOCK
    pairs: list[tuple[int, int]] = []
    doc: set[int] = set()
    zone: set[int] = set()
    packed: set[int] = set()
    full: set[int] = set()

    def walk_node(node: Any) -> bool:
        if isinstance(node, PPostings):
            pairs.append((node.ids_slot, node.tfs_slot))
            if node.norm_slot >= 0:
                doc.add(node.norm_slot)
            if node.impact_bmax_slot >= 0:
                full.add(node.impact_bmax_slot)
            return True
        if isinstance(node, PRange):
            doc.add(node.values_slot)
            if node.present_slot >= 0:
                doc.add(node.present_slot)
            for slot in (node.zmin_slot, node.zmax_slot):
                if slot >= 0:
                    zone.add(slot)
            return True
        if isinstance(node, PPresence):
            doc.add(node.present_slot)
            return True
        if isinstance(node, PNormPresence):
            doc.add(node.norm_slot)
            return True
        if isinstance(node, PBool):
            return all(walk_node(c) for c in
                       (*node.must, *node.must_not, *node.should, *node.filter))
        if isinstance(node, PMaskRef):
            packed.add(node.packed_slot)
            return True
        return isinstance(node, (PMatchAll, PMatchNone))

    def walk_metric(metric: MetricSlots) -> bool:
        doc.add(metric.values_slot)
        if metric.present_slot >= 0:
            doc.add(metric.present_slot)
        if metric.hash_slot >= 0:
            full.add(metric.hash_slot)  # per-ordinal table, not per-doc
        return True

    def walk_agg(agg: Any) -> bool:
        if isinstance(agg, BucketAggExec):
            if agg.kind == "terms_mv":
                return False  # pair arrays gather the mask by global doc id
            doc.add(agg.values_slot)
            if agg.present_slot >= 0:
                doc.add(agg.present_slot)
            for slot in (agg.froms_slot, agg.tos_slot):
                if slot >= 0:
                    full.add(slot)  # [num_buckets] bound tables
            return (all(walk_metric(m) for m in agg.metrics)
                    and all(walk_agg(s) for s in agg.subs))
        if isinstance(agg, MetricAggExec):
            return walk_metric(agg.metric)
        return False  # CompositeAggExec: whole-doc-space sort

    if not walk_node(plan.root):
        return None
    for slot in (plan.sort.values_slot, plan.sort.present_slot,
                 plan.sort.values2_slot, plan.sort.present2_slot):
        if slot >= 0:
            doc.add(slot)
    for agg in plan.aggs:
        if not walk_agg(agg):
            return None

    padded = plan.num_docs_padded
    pair_slots = {s for p in pairs for s in p}
    classified = doc | zone | packed | full | pair_slots
    if classified != set(range(len(plan.arrays))):
        return None  # a slot nobody attributed — refuse to slice blind
    # one class per slot: a slot consumed under two different partitioning
    # rules cannot be sliced consistently
    buckets = [doc, zone, packed, full, pair_slots]
    for i, a in enumerate(buckets):
        for b in buckets[i + 1:]:
            if a & b:
                return None
    for slot in doc:
        a = plan.arrays[slot]
        if a.ndim != 1 or a.shape[0] != padded:
            return None
    for slot in zone:
        a = plan.arrays[slot]
        if a.ndim != 1 or a.shape[0] * ZONEMAP_BLOCK != padded:
            return None
    for slot in packed:
        a = plan.arrays[slot]
        if a.ndim != 1 or a.shape[0] != padded // 8:
            return None
    for ids_slot, tfs_slot in pairs:
        if plan.arrays[ids_slot].shape != plan.arrays[tfs_slot].shape:
            return None
    return ChunkSlotPlan(
        posting_pairs=tuple(pairs), doc_slots=frozenset(doc),
        zone_slots=frozenset(zone), packed_slots=frozenset(packed),
        full_slots=frozenset(full))
