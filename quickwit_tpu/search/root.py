"""Root search: plan, fan out, merge, fetch — the two-phase distributed query.

Role of the reference's `root_search` (`quickwit-search/src/root.rs:1295`)
and `ClusterClient` (`cluster_client.rs:46,85`):

1. resolve index patterns + doc mappings via the metastore,
2. list splits with time-range and tag pruning pushed into the metastore
   query (`refine_*`, `root.rs:1599`, `tag_pruning.rs`),
3. place per-split jobs on searcher nodes (rendezvous + cost balancing),
4. per node: one LeafSearchRequest per index, with retry of failed leaf
   requests on the next-best node,
5. merge leaf responses (IncrementalCollector),
6. phase 2: fetch docs for the global top hits from the nodes that
   searched them (cache affinity),
7. finalize aggregations into ES-shaped results.
"""

from __future__ import annotations

import fnmatch
import logging
import time
from typing import Any, Callable, Optional, Protocol

from ..common.deadline import (
    CancellationToken, CancelledQuery, Deadline, DeadlineExceeded, QueryBudget,
    cancel_scope, deadline_scope, is_cancel_error, is_deadline_error,
)
from ..common.clock import monotonic as clock_monotonic
from ..common.ctx import run_with_context
from ..metastore.base import ListSplitsQuery, Metastore, MetastoreError
from ..models.doc_mapper import DocMapper
from ..models.split_metadata import Split, SplitState
from ..observability.metrics import (
    SEARCH_FETCH_DOCS_RETRIES_TOTAL, SEARCH_LEAF_RETRIES_TOTAL,
    SEARCH_PROFILED_QUERIES_TOTAL, SEARCH_TIMED_OUT_TOTAL,
)
from ..observability.profile import (
    PHASE_FETCH_DOCS, PHASE_ROOT_MERGE, QueryProfile, current_profile,
    profile_scope, profiled_phase,
)
from ..observability import flight
from ..observability.slo import SLO_TRACKER
from ..observability.slowlog import SLOW_QUERY_LOG
from ..query import ast as Q
from ..tenancy.context import current_tenant, tenant_scope
from ..tenancy.overload import OverloadShed
from ..tenancy.registry import GLOBAL_TENANCY, TenantRateLimited
from .cancel import CANCEL_REGISTRY
from .collector import IncrementalCollector, finalize_aggregations
from .models import (
    FetchDocsRequest, Hit, LeafSearchRequest, LeafSearchResponse, SearchRequest,
    SearchResponse, SplitIdAndFooter, SplitSearchError, string_sort_of,
)
from .placer import SearchJob, nodes_for_split, place_jobs
from ..common import sync

logger = logging.getLogger(__name__)


def _all_splits_failed(leaf_request: LeafSearchRequest, error: str,
                       retryable: bool = True) -> LeafSearchResponse:
    """A leaf response reporting every split of the request as failed —
    never an empty `failed_splits` for work that was not done."""
    return LeafSearchResponse(
        failed_splits=[SplitSearchError(split_id=s.split_id, error=error,
                                        retryable=retryable)
                       for s in leaf_request.splits],
        num_attempted_splits=len(leaf_request.splits))


class SearchClient(Protocol):
    def leaf_search(self, request: LeafSearchRequest) -> LeafSearchResponse: ...
    def fetch_docs(self, request: FetchDocsRequest) -> list[dict[str, Any]]: ...


def extract_required_tags(ast: Q.QueryAst, tag_fields: tuple[str, ...]) -> set[str]:
    """Conservative tag extraction: only terms in purely conjunctive
    positions may prune (reference `tag_pruning.rs`)."""
    tags: set[str] = set()
    if isinstance(ast, Q.Term) and ast.field in tag_fields:
        tags.add(f"{ast.field}:{ast.value}")
    elif isinstance(ast, Q.Bool) and not ast.should:
        for child in ast.must + ast.filter:
            tags |= extract_required_tags(child, tag_fields)
    elif isinstance(ast, Q.Boost):
        tags |= extract_required_tags(ast.underlying, tag_fields)
    return tags


def extract_numeric_constraints(ast: Q.QueryAst,
                                doc_mapper) -> dict[str, tuple]:
    """Required numeric constraints per field, for zonemap pruning
    (reference: `quickwit-parquet-engine/src/zonemap/` min/max pruning —
    here at split granularity; doc granularity is the device masks).
    Like tag pruning, only purely conjunctive positions count; only
    fields EXPLICITLY mapped numeric (i64/u64/f64) participate —
    datetime bounds are unit-ambiguous before input-format parsing
    (seconds vs micros) and dynamic columns have uncertain coercion, so
    either could prune wrongly. Returns field -> (lo, lo_incl, hi,
    hi_incl) with None = unbounded; multiple constraints on one field
    intersect."""
    from ..models.doc_mapper import FieldType
    out: dict[str, tuple] = {}

    def numeric_field(field: str) -> bool:
        fm = doc_mapper.field(field)
        return fm is not None and fm.type in (
            FieldType.I64, FieldType.U64, FieldType.F64)

    def tighten(field: str, lo, lo_incl, hi, hi_incl) -> None:
        cur = out.get(field, (None, True, None, True))
        clo, clo_incl, chi, chi_incl = cur
        if lo is not None and (clo is None or lo > clo
                               or (lo == clo and not lo_incl)):
            clo, clo_incl = lo, lo_incl
        if hi is not None and (chi is None or hi < chi
                               or (hi == chi and not hi_incl)):
            chi, chi_incl = hi, hi_incl
        out[field] = (clo, clo_incl, chi, chi_incl)

    def numeric(value, field: str):
        """THE leaf's bound coercion (shared helper — a drift between
        leaf matching and root pruning would silently lose hits)."""
        if isinstance(value, bool) or value is None:
            return None
        from .plan import coerce_numeric_bound
        try:
            return coerce_numeric_bound(doc_mapper.field(field).type, value)
        except (ValueError, TypeError):
            return None

    def walk(node) -> None:
        if isinstance(node, Q.Range) and numeric_field(node.field):
            lo = (numeric(node.lower.value, node.field)
                  if node.lower is not None else None)
            hi = (numeric(node.upper.value, node.field)
                  if node.upper is not None else None)
            if (node.lower is not None and lo is None) \
                    or (node.upper is not None and hi is None):
                return  # unparseable bound: skip
            tighten(node.field, lo,
                    node.lower.inclusive if node.lower else True,
                    hi, node.upper.inclusive if node.upper else True)
        elif isinstance(node, Q.Term) and numeric_field(node.field):
            value = numeric(node.value, node.field)
            if value is not None:
                tighten(node.field, value, True, value, True)
        elif isinstance(node, Q.Bool) and not node.should:
            for child in node.must + node.filter:
                walk(child)
        elif isinstance(node, Q.Boost):
            walk(node.underlying)

    walk(ast)
    return out


def split_excluded_by_bounds(column_bounds: dict,
                             constraints: dict[str, tuple]) -> bool:
    """True when some required constraint cannot match any value within
    the split's recorded [min, max] for that column. Fields without
    recorded bounds (text columns, pre-zonemap splits) never prune."""
    for field, (lo, lo_incl, hi, hi_incl) in constraints.items():
        bounds = column_bounds.get(field)
        if bounds is None:
            continue
        bmin, bmax = bounds
        try:
            if lo is not None and (bmax < lo
                                   or (bmax == lo and not lo_incl)):
                return True
            if hi is not None and (bmin > hi
                                   or (bmin == hi and not hi_incl)):
                return True
        except TypeError:
            continue  # incomparable types: never prune
    return False


class RootSearcher:
    # Queries that arrive without an explicit budget still get one: the root
    # must never hang on a stuck leaf regardless of what the caller sent.
    DEFAULT_TIMEOUT_SECS = 30.0
    # Per-query retry pool shared across the whole fan-out (reference: the
    # retry policy retries each failed leaf request once; the pool caps the
    # aggregate so a wide outage cannot amplify into a retry storm).
    MAX_RETRIES_PER_QUERY = 8

    def __init__(
        self,
        metastore: Metastore,
        clients: dict[str, SearchClient],     # node_id -> client (live pool)
        nodes_provider: Optional[Callable[[], list[str]]] = None,
        default_timeout_secs: Optional[float] = None,
    ):
        self.metastore = metastore
        self.clients = clients
        self.nodes_provider = nodes_provider or (lambda: sorted(self.clients))
        self.default_timeout_secs = (
            self.DEFAULT_TIMEOUT_SECS if default_timeout_secs is None
            else default_timeout_secs)

    # ------------------------------------------------------------------
    def search(self, request: SearchRequest) -> SearchResponse:
        from ..observability.tracing import TRACER
        # per-tenant QPS bucket at ROOT admission: a tenant over its limit
        # is bounced before any metastore work, with a Retry-After the REST
        # layer turns into a 429. No bound tenant -> no check (neutral).
        tenant = current_tenant()
        if tenant is not None:
            GLOBAL_TENANCY.check_query_rate(tenant)
        if request.timeout_millis is not None:
            deadline = Deadline.from_millis(request.timeout_millis)
        else:
            deadline = Deadline.after(self.default_timeout_secs)
        budget = QueryBudget(deadline, max_retries=self.MAX_RETRIES_PER_QUERY)
        # profile on explicit request, or for EVERY query when the slow-query
        # log is armed — a slow query can only be captured if it was profiled
        # from admission, not discovered after the fact
        profile = None
        if request.profile or SLOW_QUERY_LOG.armed:
            import uuid
            profile = QueryProfile(query_id=uuid.uuid4().hex[:16])
            SEARCH_PROFILED_QUERIES_TOTAL.inc()
        # Cancellation seam: ambient token for the whole query. With a
        # query_id it is also registered for REST DELETE; without one it
        # still flows to the leaves so embedded callers can cancel
        # programmatically via the scope.
        cancel_token = CancellationToken()
        if request.query_id is not None:
            # A DELETE can race ahead of the query it targets (a client
            # cancelling a retry under its stable handle): adopt an
            # already-cancelled token registered under this id instead of
            # replacing it, so the early cancel still lands. Live tokens
            # are NOT adopted — last-writer-wins for genuine retries.
            raced = CANCEL_REGISTRY.get(request.query_id)
            if raced is not None and raced.cancelled:
                cancel_token = raced
            CANCEL_REGISTRY.register(request.query_id, cancel_token)
        t0 = time.monotonic()
        # flight-recorder bracket: timed on the clock seam so the recorded
        # elapsed is virtual (deterministic) under DST and wall in prod
        flight_t0 = clock_monotonic()
        qid = profile.query_id if profile is not None \
            else (request.query_id or "")
        if flight.recording():
            flight.emit("query.start", query_id=qid,
                        attrs={"indexes": ",".join(request.index_ids)})
        try:
            with TRACER.span("root_search",
                             {"indexes": ",".join(request.index_ids)}):
                with deadline_scope(deadline), cancel_scope(cancel_token), \
                        profile_scope(profile):
                    try:
                        response = self._search_traced(request, budget)
                    except CancelledQuery as exc:
                        # typed partial: the cancel landed before any merged
                        # result existed — report it as cancelled, not error
                        response = SearchResponse(
                            elapsed_time_micros=int(
                                (time.monotonic() - t0) * 1e6),
                            errors=[str(exc)],
                            cancelled=True,
                        )
        except BaseException as exc:
            if isinstance(exc, OverloadShed):
                status = "shed"
            elif isinstance(exc, TenantRateLimited):
                status = "rejected"
            elif is_deadline_error(str(exc)):
                status = "timed_out"
            elif is_cancel_error(str(exc)):
                status = "cancelled"
            else:
                status = "error"
            if tenant is not None:
                GLOBAL_TENANCY.note_query(tenant.tenant_id, status=status)
            self._account_query_done(tenant, qid, status,
                                     (clock_monotonic() - flight_t0) * 1000.0)
            if profile is not None:
                profile.mark_partial(f"error: {exc}")
                profile.finish(time.monotonic() - t0)
                self._capture_slow_query(request, profile,
                                         timed_out=is_deadline_error(str(exc)))
            raise
        finally:
            if request.query_id is not None:
                CANCEL_REGISTRY.unregister(request.query_id, cancel_token)
        if response.timed_out:
            SEARCH_TIMED_OUT_TOTAL.inc()
        status = ("cancelled" if response.cancelled
                  else "timed_out" if response.timed_out else "ok")
        if tenant is not None:
            GLOBAL_TENANCY.note_query(tenant.tenant_id, status=status)
        self._account_query_done(tenant, qid, status,
                                 (clock_monotonic() - flight_t0) * 1000.0)
        if profile is not None:
            if response.timed_out:
                profile.mark_partial("timed_out")
            profile.finish(response.elapsed_time_micros / 1e6)
            if tenant is not None:
                # execute-time attribution: device execute milliseconds from
                # the profile waterfall (embedded + remote leaves) charged
                # to the tenant's meter
                from ..observability.profile import PHASE_EXECUTE
                GLOBAL_TENANCY.note_execute_seconds(
                    tenant.tenant_id,
                    profile.phase_ms_recursive(PHASE_EXECUTE) / 1000.0)
            if request.profile:
                response.profile = profile.to_dict()
            self._capture_slow_query(request, profile,
                                     timed_out=response.timed_out)
        return response

    @staticmethod
    def _account_query_done(tenant, qid: str, status: str,
                            elapsed_ms: float) -> None:
        """Completion bookkeeping shared by the success and error exits:
        the `query.done` flight event and the per-class SLO judgement.
        Cancelled queries are excluded from SLO burn — the client chose to
        abandon them, the objective was not missed by the system."""
        if flight.recording():
            flight.emit("query.done", query_id=qid,
                        attrs={"status": status,
                               "elapsed_ms": round(elapsed_ms, 3)})
        if status == "cancelled":
            return
        if tenant is not None:
            cls = tenant.priority_class
            label = GLOBAL_TENANCY.metric_label(tenant.tenant_id)
        else:
            cls = GLOBAL_TENANCY.default_class
            label = "default"
        SLO_TRACKER.note(cls, label, elapsed_ms, ok=status == "ok")

    @staticmethod
    def _capture_slow_query(request: SearchRequest, profile,
                            timed_out: bool) -> None:
        elapsed_ms = profile.wall_ms or 0.0
        if not SLOW_QUERY_LOG.should_capture(elapsed_ms, timed_out):
            return
        tenant = current_tenant()
        counters = profile.counters()
        # PR-18 query-group context: a slow stacked query names its group
        # so the outlier is attributable to formation/lane position
        group = None
        if "qbatch_group_size" in counters:
            group = {"group_size": int(counters["qbatch_group_size"]),
                     "lane_index": int(counters.get("qbatch_lane_index", 0)),
                     "masked": bool(counters.get("qbatch_masked", 0.0))}
        SLOW_QUERY_LOG.record({
            "query_id": profile.query_id,
            "indexes": list(request.index_ids),
            "elapsed_ms": elapsed_ms,
            "timed_out": timed_out,
            # which tenant's query this was: a noisy-neighbor hunt starts
            # by grouping the slowlog on this field
            **({"tenant": tenant.tenant_id} if tenant is not None else {}),
            **({"query_group": group} if group is not None else {}),
            "profile": profile.to_dict(),
        })

    def _search_traced(self, request: SearchRequest,
                       budget: QueryBudget) -> SearchResponse:
        t0 = time.monotonic()
        indexes = self._resolve_indexes(request.index_ids)
        if not indexes:
            raise ValueError(f"no index matches {request.index_ids!r}")
        if request.aggs:
            # validate the agg request up front: an EMPTY index must
            # reject a malformed aggregation exactly like a populated
            # one (zero splits would otherwise skip the leaf parse)
            from ..query.aggregations import parse_aggs
            parse_aggs(request.aggs)

        # the merge key type must be consistent across every matched index:
        # a sort field that is text in one index and numeric in another has
        # no global order (the reference rejects this the same way)
        sort_modes = {string_sort_of(request, im.index_config.doc_mapper)
                      for im in indexes}
        if len(sort_modes) > 1:
            field = request.sort_fields[0].field
            raise ValueError(
                f"sort field {field!r} is a text fast field in some matched "
                f"indexes but not others; cross-index sort needs one type")
        string_sort = next(iter(sort_modes))
        collector = IncrementalCollector(
            max_hits=request.max_hits, start_offset=request.start_offset,
            search_after=(None if string_sort is not None
                          else self._search_after_key(request)),
            string_sort=string_sort,
            string_search_after=(self._string_search_after(request)
                                 if string_sort is not None else None))
        split_meta_by_id: dict[str, tuple[str, SplitIdAndFooter, dict]] = {}
        nodes = self.nodes_provider()
        dispatches: list[tuple[str, LeafSearchRequest]] = []

        for index_metadata in indexes:
            doc_mapper = index_metadata.index_config.doc_mapper
            splits = self._prune_splits(index_metadata, doc_mapper, request)
            if not splits:
                continue
            offsets = {}
            for split in splits:
                offset = SplitIdAndFooter(
                    split_id=split.metadata.split_id,
                    storage_uri=index_metadata.index_config.index_uri,
                    num_docs=split.metadata.num_docs,
                    time_range=(split.metadata.time_range_start,
                                split.metadata.time_range_end)
                    if split.metadata.time_range_start is not None else None,
                )
                offsets[split.metadata.split_id] = offset
                split_meta_by_id[split.metadata.split_id] = (
                    index_metadata.index_uid, offset, doc_mapper.to_dict())
            jobs = [SearchJob(s.metadata.split_id, cost=max(s.metadata.num_docs, 1))
                    for s in splits]
            assignment = place_jobs(jobs, nodes)
            for node_id, node_jobs in assignment.items():
                leaf_request = LeafSearchRequest(
                    search_request=request,
                    index_uid=index_metadata.index_uid,
                    doc_mapping=doc_mapper.to_dict(),
                    splits=[offsets[j.split_id] for j in node_jobs],
                )
                dispatches.append((node_id, leaf_request))

        responses = self._fan_out(dispatches, nodes, budget)
        # root merge covers only the post-join collector work: the fan-out
        # wall is already accounted inside each leaf's own phases, and an
        # umbrella phase here would double-count it against sum≈wall
        profile = current_profile()
        with profiled_phase(PHASE_ROOT_MERGE) as rec:
            if rec is not None:
                rec["leaf_responses"] = len(responses)
            for response in responses:
                collector.add_leaf_response(response)
                if profile is not None and response.profile is not None:
                    profile.add_child(response.profile)

        merged = collector
        deadline_hit = (budget.deadline.expired
                        or any(is_deadline_error(e.error)
                               for e in merged.failed_splits))
        cancel_hit = any(is_cancel_error(e.error)
                         for e in merged.failed_splits)
        if (merged.num_attempted_splits > 0
                and merged.num_successful_splits == 0 and merged.failed_splits
                and not deadline_hit and not cancel_hit):
            # every split failed: a query-level problem (e.g. unknown field),
            # not a partial outage — surface it as an error (reference 400s).
            # Deadline expiries are NOT query-level problems: they return a
            # timed_out partial response below.
            raise ValueError(merged.failed_splits[0].error)
        with profiled_phase(PHASE_FETCH_DOCS) as rec:
            hits = self._fetch_docs_phase(request, merged, split_meta_by_id,
                                          nodes, budget.deadline)
            if rec is not None:
                rec["docs"] = len(hits)
        aggregations = None
        if request.aggs:
            aggregations = finalize_aggregations(merged.aggregation_states())
            # ES returns the aggregation skeleton even when no split
            # contributed states (empty index / zero matching splits)
            _fill_empty_aggs(aggregations, request.aggs)
        return SearchResponse(
            num_hits=merged.num_hits,
            hits=hits,
            elapsed_time_micros=int((time.monotonic() - t0) * 1e6),
            errors=[f"{e.split_id}: {e.error}" for e in merged.failed_splits],
            aggregations=aggregations,
            timed_out=deadline_hit or budget.deadline.expired,
            cancelled=cancel_hit,
            failed_splits=list(merged.failed_splits),
            num_attempted_splits=merged.num_attempted_splits,
            num_successful_splits=merged.num_successful_splits,
        )

    # ------------------------------------------------------------------
    def _fan_out(self, dispatches: list[tuple[str, LeafSearchRequest]],
                 nodes: list[str],
                 budget: QueryBudget) -> list[LeafSearchResponse]:
        """Dispatch every leaf request concurrently and collect responses in
        dispatch order (merge determinism). Each join is bounded by the
        remaining deadline; a dispatch still running at expiry is abandoned —
        its daemon thread finishes in the background — and reported as
        deadline-failed splits instead of blocking the root."""
        if not dispatches:
            return []
        deadline = budget.deadline
        if len(dispatches) == 1 and not deadline.bounded:
            node_id, leaf_request = dispatches[0]
            return [self._leaf_search_with_retry(leaf_request, node_id, nodes,
                                                 budget)]
        results: list[Optional[LeafSearchResponse]] = [None] * len(dispatches)
        # fan-out threads start with empty span stacks and fresh contextvars:
        # capture the root's traceparent HERE (the tracer's span stack is
        # thread-local, not a contextvar) so every leaf dispatch joins the
        # root trace; the contextvar bindings — deadline, tenant, profile —
        # ride the run_with_context snapshot below
        from ..observability.tracing import TRACER
        parent_tp = TRACER.current_traceparent()
        profile = current_profile()
        tenant = current_tenant()

        control_errors: list = []

        def run(i: int, node_id: str, leaf_request: LeafSearchRequest) -> None:
            with TRACER.span("leaf_dispatch",
                             {"node": node_id,
                              "num_splits": len(leaf_request.splits)},
                             remote_parent=parent_tp):
                try:
                    results[i] = self._leaf_search_with_retry(
                        leaf_request, node_id, nodes, budget)
                except (OverloadShed, TenantRateLimited) as exc:
                    # re-raised on the main thread after join: local
                    # backpressure fails the whole query, not one leaf
                    control_errors.append(exc)
                    results[i] = _all_splits_failed(leaf_request, str(exc))
                except Exception as exc:  # noqa: BLE001 - surfaced per split
                    results[i] = _all_splits_failed(leaf_request, str(exc))

        # snapshot under the authoritative bindings: budget.deadline is THE
        # query deadline even if a caller ever invokes _fan_out outside its
        # scope, so re-enter the scopes explicitly before capturing
        with profile_scope(profile), deadline_scope(deadline), \
                tenant_scope(tenant):
            spawned_run = run_with_context(run)
        threads = []
        for i, (node_id, leaf_request) in enumerate(dispatches):
            thread = sync.thread(
                target=spawned_run, args=(i, node_id, leaf_request),
                name=f"root-fanout-{i}", daemon=True)
            threads.append(thread)
            thread.start()
        for thread in threads:
            thread.join(timeout=deadline.clamp(None))
        if control_errors:
            raise control_errors[0]
        out: list[LeafSearchResponse] = []
        for i, (node_id, leaf_request) in enumerate(dispatches):
            response = results[i]
            if response is None:
                response = _all_splits_failed(
                    leaf_request,
                    f"deadline exceeded waiting for leaf search on {node_id}",
                    retryable=False)
            out.append(response)
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def _string_search_after(request: SearchRequest):
        """Marker for text-field sorts: (raw_term|None, split|None, doc).
        Leafs push it down as per-split ordinal bounds; the root collector
        re-filters on the decoded term strings (split-local ordinals are
        not cross-split comparable)."""
        if not request.search_after:
            return None
        sa = request.search_after
        if len(sa) == 3:
            raw, m_split, m_doc = sa[0], sa[1], sa[2]
        elif len(sa) == 4:  # secondary sort rides along; primary governs
            raw, m_split, m_doc = sa[0], sa[2], sa[3]
        else:
            raise ValueError(
                "search_after expects [sort_value(s)..., split_id, doc_id]")
        return (raw, None if m_split is None else str(m_split),
                int(m_doc) if m_doc is not None else -1)

    def _resolve_indexes(self, patterns: list[str]):
        out = []
        seen = set()
        all_indexes = None
        for pattern in patterns:
            if any(ch in pattern for ch in "*?"):
                if all_indexes is None:
                    all_indexes = self.metastore.list_indexes()
                for im in all_indexes:
                    if fnmatch.fnmatch(im.index_id, pattern) and im.index_uid not in seen:
                        seen.add(im.index_uid)
                        out.append(im)
            else:
                try:
                    im = self.metastore.index_metadata(pattern)
                except MetastoreError:
                    # unknown index id in a multi-pattern request: skip the
                    # pattern (ES semantics); anything NOT a typed metastore
                    # failure — deadline expiry, backpressure — propagates
                    continue
                if im.index_uid not in seen:
                    seen.add(im.index_uid)
                    out.append(im)
        return out

    def _prune_splits(self, index_metadata, doc_mapper: DocMapper,
                      request: SearchRequest) -> list[Split]:
        required_tags = extract_required_tags(
            request.query_ast, doc_mapper.tag_fields) or None
        query = ListSplitsQuery(
            index_uids=[index_metadata.index_uid],
            states=[SplitState.PUBLISHED],
            time_range_start=request.start_timestamp,
            time_range_end=request.end_timestamp,
            required_tags=required_tags,
        )
        splits = self.metastore.list_splits(query)
        # zonemap pruning: drop splits whose numeric column bounds
        # preclude a required predicate, before any byte is fetched
        constraints = extract_numeric_constraints(request.query_ast,
                                                  doc_mapper)
        if constraints:
            before = len(splits)
            splits = [s for s in splits if not split_excluded_by_bounds(
                s.metadata.column_bounds, constraints)]
            if before != len(splits):
                profile = current_profile()
                if profile is not None:
                    profile.add("splits_pruned_zonemap", before - len(splits))
        return splits

    def _leaf_search_with_retry(self, leaf_request: LeafSearchRequest,
                                node_id: str, nodes: list[str],
                                budget: Optional[QueryBudget] = None,
                                ) -> LeafSearchResponse:
        budget = budget or QueryBudget(Deadline.never(),
                                       max_retries=self.MAX_RETRIES_PER_QUERY)
        first_error: Optional[str] = None
        tenant = current_tenant()
        try:
            budget.deadline.check(f"leaf dispatch to {node_id}")
            leaf_request.deadline_millis = budget.deadline.timeout_millis()
            if tenant is not None:
                # the resolved class rides the wire so a remote leaf
                # schedules in the same band without sharing tenant config
                leaf_request.tenant = tenant.to_wire()
            client = self.clients[node_id]
            response = client.leaf_search(leaf_request)
        except DeadlineExceeded as exc:
            return _all_splits_failed(leaf_request, str(exc), retryable=False)
        except (OverloadShed, TenantRateLimited):
            # local backpressure rejects the WHOLE query (429 upstream);
            # retrying on another node would defeat the controller. A
            # REMOTE leaf's 429 arrives as a client error instead and
            # keeps the failed-node retry path below.
            raise
        except Exception as exc:  # noqa: BLE001 - node-level failure
            logger.warning("leaf search on %s failed: %s", node_id, exc)
            first_error = f"leaf search on {node_id} failed: {exc}"
            response = None
        if response is not None and not response.failed_splits:
            return response
        # Per-split failures of the whole request when the node itself died;
        # these are what a no-retry path must RETURN, never drop — a response
        # with empty failed_splits claims splits were searched cleanly.
        original_failures = (
            list(response.failed_splits) if response is not None
            else [SplitSearchError(split_id=s.split_id, error=first_error)
                  for s in leaf_request.splits])
        retryable_ids = {e.split_id for e in original_failures if e.retryable}

        def with_failures(failures: list[SplitSearchError]) -> LeafSearchResponse:
            if response is None:
                return LeafSearchResponse(
                    failed_splits=failures,
                    num_attempted_splits=len(leaf_request.splits))
            response.failed_splits = failures
            return response

        if not retryable_ids:
            return with_failures(original_failures)
        retry_index = budget.try_acquire_retry()
        if retry_index is None:  # pool drained or deadline passed
            return with_failures(original_failures)
        # retry failed splits (or the whole request) on the next-best node
        retry_splits = [s for s in leaf_request.splits
                        if s.split_id in retryable_ids]
        retry_node = None
        for candidate in nodes_for_split(retry_splits[0].split_id, nodes):
            if candidate != node_id:
                retry_node = candidate
                break
        if retry_node is None:
            return with_failures(original_failures)
        if not budget.sleep_before_retry(retry_index):
            return with_failures(original_failures)
        SEARCH_LEAF_RETRIES_TOTAL.inc()
        non_retryable = [e for e in original_failures
                         if e.split_id not in retryable_ids]
        # seed the retry with the Kth sort value the first attempt already
        # collected: round 2 starts pruning where round 1 left off instead
        # of re-proving the threshold from scratch (search/pruning.py)
        retry_threshold = None
        if response is not None:
            from ..models.doc_mapper import DocMapper as _DM
            from .pruning import threshold_from_response
            retry_threshold = threshold_from_response(
                leaf_request.search_request,
                _DM.from_dict(leaf_request.doc_mapping), response)
        retry_request = LeafSearchRequest(
            search_request=leaf_request.search_request,
            index_uid=leaf_request.index_uid,
            doc_mapping=leaf_request.doc_mapping,
            splits=retry_splits,
            deadline_millis=budget.deadline.timeout_millis(),
            tenant=tenant.to_wire() if tenant is not None else None,
            sort_value_threshold=retry_threshold,
        )
        try:
            retry_response = self.clients[retry_node].leaf_search(retry_request)
        except (OverloadShed, TenantRateLimited):
            # the retry client can be LOCAL (in-process service): its
            # backpressure must fail the whole query as a typed 429, same
            # contract as the first attempt above — swallowing it here
            # demoted a controller rejection to a generic split failure
            raise
        except DeadlineExceeded as exc:
            return with_failures(
                [SplitSearchError(split_id=s.split_id, error=str(exc),
                                  retryable=False)
                 for s in retry_splits] + non_retryable)
        except Exception as exc:  # noqa: BLE001
            logger.warning("leaf retry on %s failed: %s", retry_node, exc)
            return with_failures(
                [SplitSearchError(split_id=s.split_id,
                                  error=f"retry on {retry_node} failed: {exc}")
                 for s in retry_splits] + non_retryable)
        if response is None:
            retry_response.failed_splits = (
                list(retry_response.failed_splits) + non_retryable)
            return retry_response
        # keep the successful part of the original + the retry results
        # (non-retryable failures from the first attempt ride along)
        from ..models.doc_mapper import DocMapper as _DM
        merged = IncrementalCollector(
            max_hits=leaf_request.search_request.max_hits
            + leaf_request.search_request.start_offset,
            string_sort=string_sort_of(
                leaf_request.search_request,
                _DM.from_dict(leaf_request.doc_mapping)))
        ok_part = LeafSearchResponse(
            num_hits=response.num_hits, partial_hits=response.partial_hits,
            failed_splits=non_retryable,
            intermediate_aggs=response.intermediate_aggs,
            num_attempted_splits=response.num_attempted_splits,
            num_successful_splits=response.num_successful_splits)
        merged.add_leaf_response(ok_part)
        merged.add_leaf_response(retry_response)
        return merged.to_leaf_response()

    def _fetch_docs_phase(self, request: SearchRequest,
                          collector: IncrementalCollector,
                          split_meta_by_id: dict,
                          nodes: list[str],
                          deadline: Optional[Deadline] = None) -> list[Hit]:
        deadline = deadline or Deadline.never()
        top_hits = collector.partial_hits()
        if not top_hits or request.max_hits == 0:
            return []
        by_split: dict[str, list] = {}
        for hit in top_hits:
            by_split.setdefault(hit.split_id, []).append(hit)
        docs_by_address: dict[tuple[str, int], dict] = {}
        for split_id, hits in by_split.items():
            if deadline.expired:
                # out of budget: return what phase 1 earned; hits whose docs
                # were not fetched are dropped from the (already partial) page
                break
            index_uid, offset, doc_mapping = split_meta_by_id[split_id]
            fetch_request = FetchDocsRequest(
                index_uid=index_uid, split=offset,
                doc_ids=[h.doc_id for h in hits],
                snippet_fields=request.snippet_fields,
                query_ast=request.query_ast if request.snippet_fields else None,
            )
            # first attempt on the split's preferred replica, then exactly
            # ONE retry on the next replica — and only if budget remains.
            # Unbounded replica walks here could blow far past the deadline
            # phase 1 already honored.
            docs = None
            candidates = nodes_for_split(split_id, nodes)
            for attempt, node_id in enumerate(candidates[:2]):
                if attempt > 0:
                    if deadline.expired:
                        logger.warning(
                            "fetch_docs for split %s: no budget left for a "
                            "replica retry", split_id)
                        break
                    SEARCH_FETCH_DOCS_RETRIES_TOTAL.inc()
                try:
                    docs = self.clients[node_id].fetch_docs(fetch_request)
                    break
                except (OverloadShed, TenantRateLimited):
                    # local backpressure fails the whole query as a typed
                    # 429 — replica-retrying it would defeat the controller
                    raise
                except Exception as exc:  # noqa: BLE001
                    logger.warning("fetch_docs on %s failed: %s", node_id, exc)
            if docs is None:
                continue
            for hit, doc in zip(hits, docs):
                docs_by_address[(split_id, hit.doc_id)] = doc
        out: list[Hit] = []
        scoring = not request.sort_fields or request.sort_fields[0].field == "_score"
        for hit in top_hits:
            doc = docs_by_address.get((hit.split_id, hit.doc_id))
            if doc is None:
                continue
            snippets = doc.pop("_snippets", None)
            sort_values = [hit.raw_sort_value]
            if len(request.sort_fields) > 1:
                sort_values.append(hit.raw_sort_value2)
            out.append(Hit(
                doc=doc,
                score=hit.raw_sort_value if scoring else None,
                sort_values=sort_values,
                split_id=hit.split_id,
                doc_id=hit.doc_id,
                snippets=snippets,
            ))
        return out

    @staticmethod
    def _search_after_key(request: SearchRequest):
        if not request.search_after:
            return None
        sa = request.search_after
        two_keys = len(request.sort_fields) > 1
        if len(sa) != (4 if two_keys else 3):
            raise ValueError(
                "search_after expects [sort_value(s)..., split_id, doc_id] "
                "matching the number of sort fields")

        def encode(value, sort):
            if value is None:
                from .leaf import MISSING_VALUE_SENTINEL
                return MISSING_VALUE_SENTINEL
            if isinstance(value, str):
                raise ValueError(
                    "search_after got a string for a numeric sort field")
            value = float(value)
            if sort and sort.order == "asc":
                value = -value
            return value

        v1 = encode(sa[0], request.sort_fields[0] if request.sort_fields else None)
        if two_keys:
            v2 = encode(sa[1], request.sort_fields[1])
            # m_split None = value-only ES marker (strictly after the value)
            return (v1, v2, None if sa[2] is None else str(sa[2]),
                    int(sa[3]))
        return (v1, 0.0, None if sa[1] is None else str(sa[1]), int(sa[2]))


def _fill_empty_aggs(aggregations: dict, aggs_request: dict) -> None:
    """Synthesize ES empty-result shapes for aggregations no split reported
    states for (empty index / zero matching splits). Shapes come from the
    SAME finalize path as real results (identity states in, finalize out),
    so empty and non-empty responses cannot diverge structurally."""
    import numpy as np

    from ..ops.aggs import HLL_NUM_REGISTERS, PCTL_NUM_BUCKETS
    from ..query.aggregations import (CompositeAgg, DateHistogramAgg,
                                      HistogramAgg, MetricAgg, RangeAgg,
                                      TermsAgg, parse_aggs)
    from .collector import finalize_aggregations
    try:
        specs = parse_aggs(aggs_request)
    # qwlint: disable-next-line=QW004 - pure parse of an already-validated
    # dict; no control-flow exception can originate here
    except Exception:  # noqa: BLE001 - request already validated upstream
        return
    empty_states: dict[str, dict] = {}
    for spec in specs:
        if spec.name in aggregations:
            continue
        if isinstance(spec, MetricAgg):
            if spec.kind == "percentiles":
                empty_states[spec.name] = {
                    "kind": "percentiles",
                    "sketch": np.zeros(PCTL_NUM_BUCKETS, dtype=np.int64),
                    "percents": list(spec.percents), "keyed": spec.keyed}
            elif spec.kind == "cardinality":
                empty_states[spec.name] = {
                    "kind": "cardinality",
                    "hll": np.zeros(HLL_NUM_REGISTERS, dtype=np.int32)}
            else:
                empty_states[spec.name] = {
                    "kind": spec.kind,
                    "state": np.array([0.0, 0.0, 0.0, np.inf, -np.inf])}
        elif isinstance(spec, RangeAgg):
            empty_states[spec.name] = {
                "kind": "range", "ranges": list(spec.ranges),
                "bucket_map": {}}
        elif isinstance(spec, CompositeAgg):
            empty_states[spec.name] = {
                "kind": "composite", "bucket_map": {}, "size": spec.size,
                "sources": [{"name": s.name, "kind": s.kind}
                            for s in spec.sources]}
        elif isinstance(spec, TermsAgg):
            empty_states[spec.name] = {
                "kind": "terms", "bucket_map": {}, "size": spec.size,
                "min_doc_count": spec.min_doc_count,
                "order_desc": spec.order_by_count_desc}
        elif isinstance(spec, (DateHistogramAgg, HistogramAgg)):
            interval = (spec.interval_micros
                        if isinstance(spec, DateHistogramAgg)
                        else spec.interval)
            empty_states[spec.name] = {
                "kind": ("date_histogram"
                         if isinstance(spec, DateHistogramAgg)
                         else "histogram"),
                "bucket_map": {}, "interval": interval, "origin": 0,
                "min_doc_count": spec.min_doc_count,
                "offset": getattr(spec, "offset_micros", 0)}
    if empty_states:
        aggregations.update(finalize_aggregations(empty_states))
