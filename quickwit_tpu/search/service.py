"""Search service: the node-local search endpoints.

Role of the reference's `SearchService` trait + `SearchServiceImpl`
(`quickwit-search/src/service.rs:65`) and the leaf entry point
`multi_index_leaf_search`/`single_doc_mapping_leaf_search`
(`leaf.rs:1497,1887`):

- `leaf_search`: search a batch of splits of one index on this node — split
  reordering for pruning (`CanSplitDoBetter`), leaf cache, batched mesh
  execution when the plan is split-uniform, per-split fallback otherwise,
  partial failure collection.
- `fetch_docs`: phase-2 doc fetch + snippet generation.

The SearcherContext owns the caches (reader/hotcache byte ranges + device
arrays per split, leaf results) and the admission budget — the roles of the
reference's SearcherContext (`service.rs:405`) and SearchPermitProvider.
"""

from __future__ import annotations

import logging
import re
import threading
from collections import OrderedDict
from typing import Any, Optional

from ..common.deadline import (
    Deadline, bind_deadline, current_deadline, deadline_scope,
)
from ..index.reader import SplitReader
from ..models.doc_mapper import DocMapper
from ..observability.metrics import (
    SEARCH_DEADLINE_REMAINING, SEARCH_SHED_TOTAL,
)
from ..query.ast import MatchAll
from ..parallel.fanout import build_batch, execute_batch, stage_device_inputs
from ..storage.base import StorageResolver
from .cache import LeafSearchCache, canonical_request_key
from .predicate_cache import PredicateCache, required_terms
from .collector import IncrementalCollector
from .leaf import (execute_prepared_split, leaf_search_single_split,
                   prepare_plan_only)
from .models import (
    FetchDocsRequest, LeafSearchRequest, LeafSearchResponse, SearchRequest,
    SplitIdAndFooter, SplitSearchError, string_sort_of,
)

logger = logging.getLogger(__name__)

# rate_limited_tracing.rs analogue: a bad query fanned over thousands of
# splits must not emit thousands of identical warnings
from ..observability.tracing import RateLimitedLog  # noqa: E402

_SPLIT_WARN_LIMITER = RateLimitedLog(limit=5, period_secs=60.0)


def _warn_split_failure(kind: str, split_id: str, exc: object) -> None:
    emit, suppressed = _SPLIT_WARN_LIMITER.should_log(kind)
    if emit:
        extra = f" ({suppressed} similar suppressed)" if suppressed else ""
        logger.warning("split %s %s failed: %s%s", split_id, kind, exc,
                       extra)


class SearcherContext:
    def __init__(self, storage_resolver: Optional[StorageResolver] = None,
                 max_open_splits: int = 128,
                 leaf_cache_bytes: int = 64 << 20,
                 batch_size: int = 8,
                 prefetch: bool = True,
                 offload_endpoint: Optional[str] = None,
                 offload_max_local_splits: int = 16,
                 offload_client_factory=None,
                 split_cache=None):
        self.storage_resolver = storage_resolver or StorageResolver.default()
        # disk-resident split cache (reference SearchSplitCache,
        # split_cache/mod.rs:43): reader opens check it first; misses
        # report the split as a download candidate
        self.split_cache = split_cache
        self.leaf_cache = LeafSearchCache(leaf_cache_bytes)
        self.batch_size = batch_size
        # warmup/compute pipelining (SURVEY hard-part #4): one prefetch
        # worker stages batch N+1's storage IO + H2D transfer while batch
        # N executes on device. Single worker = classic double buffering;
        # bounds both memory (at most one staged batch) and storage load.
        self.prefetch = prefetch
        self._prefetch_pool = None
        # predicate/negative cache: (split, term)-absence proofs prune
        # provably-empty splits before the reader is even constructed
        # (reference: leaf_cache.rs:197 + leaf.rs:758-841)
        self.predicate_cache = PredicateCache()
        # byte-accurate HBM admission (reference SearchPermitProvider):
        # the lowered plan knows every array's size, so over-budget work
        # queues instead of materializing
        from .admission import HbmBudget
        self.hbm_budget = HbmBudget()
        # cross-query dispatch coalescing: concurrent same-structure
        # queries on one split ride a single vmapped dispatch
        # (search/batcher.py; reference analogue: per-node leaf request
        # batching, leaf.rs:81)
        from .batcher import QueryBatcher
        self.query_batcher = QueryBatcher()
        self._readers: OrderedDict[str, SplitReader] = OrderedDict()
        self._max_open_splits = max_open_splits
        self._lock = threading.Lock()
        # serverless offload (reference: lambda leaf-search offload,
        # quickwit-lambda-client/src/invoker.rs:129 + the scheduling
        # split at leaf.rs:1658,1828): cold splits beyond
        # offload_max_local_splits per leaf request are dispatched to the
        # configured endpoint — any process serving the internal
        # leaf-search protocol (a peer node, a FaaS worker pool, ...)
        self.offload_endpoint = offload_endpoint
        self.offload_max_local_splits = offload_max_local_splits
        self._offload_client_factory = offload_client_factory
        self._offload_client = None

    def offload_client(self):
        with self._lock:
            if self._offload_client is None:
                if self._offload_client_factory is not None:
                    self._offload_client = self._offload_client_factory(
                        self.offload_endpoint)
                else:
                    from ..serve.http_client import HttpSearchClient
                    self._offload_client = HttpSearchClient(
                        self.offload_endpoint)
            return self._offload_client

    def has_warm_reader(self, split: SplitIdAndFooter) -> bool:
        """True when this split's reader (and its byte-range/device
        caches) is already resident — the 'warm split' signal the offload
        scheduling uses (the reference offloads splits absent from the
        local split cache)."""
        with self._lock:
            return f"{split.storage_uri}/{split.split_id}" in self._readers

    def prefetch_pool(self):
        from concurrent.futures import ThreadPoolExecutor
        with self._lock:
            if self._prefetch_pool is None:
                self._prefetch_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="leaf-prefetch")
            return self._prefetch_pool

    def reader(self, split: SplitIdAndFooter) -> SplitReader:
        """LRU-cached split readers: keeps footer, term dict, byte-range and
        device-array caches warm across queries (the warmup-amortization the
        reference's cache stack exists for)."""
        key = f"{split.storage_uri}/{split.split_id}"
        with self._lock:
            reader = self._readers.get(key)
            if reader is not None:
                self._readers.move_to_end(key)
                return reader
        storage = self.storage_resolver.resolve(split.storage_uri)
        if self.split_cache is not None:
            local = self.split_cache.local_path(split.split_id)
            if local is not None:
                from ..common.uri import Uri
                from ..storage.local import LocalFileStorage
                storage = LocalFileStorage(
                    Uri.parse(f"file://{self.split_cache.root_path}"))
            else:
                self.split_cache.report_split(
                    split.split_id, split.storage_uri,
                    num_bytes_hint=split.file_len or 0)
        reader = SplitReader(storage, f"{split.split_id}.split",
                             file_len=split.file_len)
        with self._lock:
            self._readers[key] = reader
            while len(self._readers) > self._max_open_splits:
                self._readers.popitem(last=False)
        return reader


class SearchService:
    """One node's search endpoints. Any node can act as root; leaf work runs
    where this service lives."""

    def __init__(self, context: Optional[SearcherContext] = None,
                 node_id: str = "node-0"):
        self.context = context or SearcherContext()
        self.node_id = node_id

    # ------------------------------------------------------------------
    def leaf_search(self, request: LeafSearchRequest) -> LeafSearchResponse:
        from ..observability.tracing import TRACER
        with TRACER.span("leaf_search",
                         {"num_splits": len(request.splits)}):
            return self._leaf_search_traced(request)

    def _leaf_search_traced(self,
                            request: LeafSearchRequest) -> LeafSearchResponse:
        # The wire deadline (remaining budget serialized by the root) wins;
        # in-process callers inherit the ambient scope; otherwise unbounded.
        if request.deadline_millis is not None:
            deadline = Deadline.from_millis(request.deadline_millis)
        else:
            deadline = current_deadline() or Deadline.never()
        if deadline.bounded:
            SEARCH_DEADLINE_REMAINING.observe(deadline.remaining())
        with deadline_scope(deadline):
            return self._leaf_search_deadlined(request, deadline)

    def _leaf_search_deadlined(self, request: LeafSearchRequest,
                               deadline: Deadline) -> LeafSearchResponse:
        doc_mapper = DocMapper.from_dict(request.doc_mapping)
        search_request = request.search_request
        splits = self._optimize_split_order(search_request, request.splits)

        collector = IncrementalCollector(
            max_hits=search_request.max_hits,
            start_offset=search_request.start_offset,
            string_sort=string_sort_of(search_request, doc_mapper))
        required = required_terms(search_request.query_ast, doc_mapper)
        num_pruned_by_predicate = 0
        pending: list[SplitIdAndFooter] = []
        for split in splits:
            if self._count_from_metadata(search_request, split):
                # pure count over the whole split: the metastore's doc count
                # IS the answer — never open or transfer the split
                # (reference: CanSplitDoBetter count path, leaf.rs:1361)
                collector.add_leaf_response(LeafSearchResponse(
                    num_hits=split.num_docs, num_attempted_splits=1,
                    num_successful_splits=1))
                continue
            if required and self.context.predicate_cache.known_empty(
                    split.split_id, required):
                # negative cache: a required term is proven absent from this
                # split — provably zero hits and identity agg states, so skip
                # the reader open, warmup, H2D, and kernel launch entirely
                num_pruned_by_predicate += 1
                collector.add_leaf_response(LeafSearchResponse(
                    num_hits=0, num_attempted_splits=1,
                    num_successful_splits=1))
                continue
            key = canonical_request_key(split.split_id, search_request,
                                        split.time_range)
            cached = self.context.leaf_cache.get(key)
            if cached is not None:
                collector.add_leaf_response(cached)
                continue
            pending.append(split)

        offload_future = None
        offload_result: dict[str, Any] = {}
        offloaded: list[SplitIdAndFooter] = []
        if (self.context.offload_endpoint
                and len(pending) > self.context.offload_max_local_splits):
            # scheduling split (reference schedule_search_tasks,
            # leaf.rs:1828): warm splits stay local; the coldest tail
            # beyond the local budget runs on the offload endpoint
            # CONCURRENTLY with the local loop
            warm = [s for s in pending if self.context.has_warm_reader(s)]
            cold = [s for s in pending
                    if not self.context.has_warm_reader(s)]
            budget = max(self.context.offload_max_local_splits, len(warm))
            local = (warm + cold)[:budget]
            offloaded = (warm + cold)[budget:]
            if offloaded:
                pending = local
                remote_request = LeafSearchRequest(
                    search_request=search_request,
                    index_uid=request.index_uid,
                    doc_mapping=request.doc_mapping, splits=offloaded,
                    deadline_millis=deadline.timeout_millis())
                result_box: dict[str, Any] = {}

                def _invoke(box=result_box, rr=remote_request):
                    try:
                        box["response"] = \
                            self.context.offload_client().leaf_search(rr)
                    except Exception as exc:  # noqa: BLE001 - fallback below
                        box["error"] = exc

                offload_future = threading.Thread(target=_invoke,
                                                  daemon=True)
                offload_future.start()
                offload_result = result_box

        num_skipped = 0
        prunable = self._pruning_applicable(search_request,
                                            doc_mapper.timestamp_field)
        batch_size = self.context.batch_size
        groups = [pending[b: b + batch_size]
                  for b in range(0, len(pending), batch_size)]
        # pipelined loop: group i executes while group i+1's storage IO and
        # H2D transfer run on the prefetch worker (double buffering —
        # reference rationale: the warmup/cache stack of leaf.rs:304)
        pipelined = self.context.prefetch and len(groups) > 1
        future = None
        if pipelined:
            # bind_deadline: contextvars do not reach pool worker threads
            future = self.context.prefetch_pool().submit(
                bind_deadline(self._prepare_group), groups[0], doc_mapper,
                search_request)
        for i, group in enumerate(groups):
            begin = i * batch_size
            if deadline.expired:
                # out of budget mid-request: every remaining split surfaces
                # as a typed, retryable failure — partial and on time
                SEARCH_SHED_TOTAL.inc(stage="leaf_groups")
                for split in pending[begin:]:
                    collector.failed_splits.append(SplitSearchError(
                        split_id=split.split_id,
                        error="deadline exceeded before split executed at leaf",
                        retryable=True))
                if future is not None:
                    self._discard_prepared(future.result())
                    future = None
                break
            if prunable and begin > 0 and self._can_skip_remaining(
                    search_request, collector, pending, begin):
                # reference `CanSplitDoBetter` short-circuit (leaf.rs:1608):
                # with exact counting off, splits whose best possible sort key
                # cannot beat the current kth hit are skipped entirely
                # (a prefetched group may be discarded here — wasted IO is
                # the price of overlap, never wrong results; its admitted
                # HBM pins must still be returned)
                num_skipped = len(pending) - begin
                if future is not None:
                    self._discard_prepared(future.result())
                    future = None
                break
            prepared = (future.result() if future is not None
                        else self._prepare_group(group, doc_mapper,
                                                 search_request))
            future = None
            if pipelined and i + 1 < len(groups):
                future = self.context.prefetch_pool().submit(
                    bind_deadline(self._prepare_group), groups[i + 1],
                    doc_mapper, search_request)
            self._execute_group(prepared, doc_mapper, search_request,
                                collector)

        num_offloaded = 0
        if offload_future is not None:
            offload_future.join(
                timeout=deadline.clamp(self._OFFLOAD_TIMEOUT_SECS))
            remote = offload_result.get("response")
            if remote is not None:
                collector.add_leaf_response(remote)
                num_offloaded = len(offloaded)
            else:
                # offload failed (endpoint down / timeout): the splits
                # still belong to this request — run them locally
                # (reference invoker falls back the same way)
                _warn_split_failure(
                    "offload", offloaded[0].split_id if offloaded else "-",
                    offload_result.get("error", "timeout"))
                for group in [offloaded[b: b + batch_size]
                              for b in range(0, len(offloaded), batch_size)]:
                    if deadline.expired:
                        SEARCH_SHED_TOTAL.inc(stage="offload_fallback")
                        for split in group:
                            collector.failed_splits.append(SplitSearchError(
                                split_id=split.split_id,
                                error="deadline exceeded before offloaded "
                                      "split ran locally",
                                retryable=True))
                        continue
                    prepared = self._prepare_group(group, doc_mapper,
                                                   search_request)
                    self._execute_group(prepared, doc_mapper, search_request,
                                        collector)

        response = collector.to_leaf_response()
        response.num_attempted_splits = len(splits)
        response.resource_stats["num_splits_skipped"] = num_skipped
        response.resource_stats["num_splits_pruned_by_predicate_cache"] = \
            num_pruned_by_predicate
        if num_offloaded:
            response.resource_stats["num_splits_offloaded"] = num_offloaded
        return response

    _OFFLOAD_TIMEOUT_SECS = 30.0

    @staticmethod
    def _count_from_metadata(request: SearchRequest,
                             split: SplitIdAndFooter) -> bool:
        """True when this split's contribution is exactly its doc count:
        match-all query, no hits wanted, no aggregations, and any request
        time filter fully covers the split's own time range (sound because
        the doc mapper requires the timestamp field on every doc, so the
        split range bounds all of them)."""
        if (request.max_hits != 0 or request.start_offset != 0
                or request.aggs or not isinstance(request.query_ast, MatchAll)):
            return False
        if request.start_timestamp is None and request.end_timestamp is None:
            return True
        if split.time_range is None:
            return False  # no bounds recorded: must evaluate
        lo, hi = split.time_range
        if request.start_timestamp is not None and request.start_timestamp > lo:
            return False
        # end_timestamp is exclusive; split ranges are inclusive
        if request.end_timestamp is not None and request.end_timestamp <= hi:
            return False
        return True

    @staticmethod
    def _pruning_applicable(request: SearchRequest, timestamp_field) -> bool:
        if request.count_hits_exact or request.aggs or request.max_hits == 0:
            return False
        sort = request.sort_fields[0] if request.sort_fields else None
        # split metadata only bounds the timestamp field's values
        return sort is not None and sort.field == timestamp_field

    @staticmethod
    def _can_skip_remaining(request: SearchRequest,
                            collector: IncrementalCollector,
                            pending: list[SplitIdAndFooter],
                            begin: int) -> bool:
        needed = request.start_offset + request.max_hits
        hits = collector.partial_hits()
        if len(hits) < request.max_hits or collector.num_hits < needed:
            return False
        if not hits:
            return False
        sort = request.sort_fields[0]
        worst_kept = hits[-1].sort_value  # internal higher-is-better key
        for i in range(begin, len(pending)):
            split = pending[i]
            if split.time_range is None:
                return False
            # best achievable internal key in this split for the sort field;
            # a TIE can still win the (split_id, doc_id) tie-break, so only
            # strictly-worse splits are skippable
            best = (split.time_range[1] if sort.order == "desc"
                    else -split.time_range[0])
            if best >= worst_kept:
                return False
        return True

    def _prepare_group(self, group, doc_mapper, search_request):
        """Stage 1 (prefetch-thread-safe): storage IO, plan lowering, and
        the async H2D transfer for one split group. Returns an opaque
        prepared unit for `_execute_group`."""
        # the batch path has no search_after pushdown or per-split terms
        # truncation; the per-split path handles those (2-key sorts ride
        # the batch via the lexicographic cross-split re-top-k)
        import json as _json
        if (len(group) > 1 and not search_request.search_after
                and string_sort_of(search_request, doc_mapper) is None
                and not any(key in _json.dumps(search_request.aggs or {})
                            for key in ("split_size", "shard_size",
                                        "segment_size"))):
            admitted = None
            batch = None
            try:
                readers = [self.context.reader(s) for s in group]
                batch = build_batch(
                    search_request, doc_mapper, readers,
                    [s.split_id for s in group],
                    absence_sink=self.context.predicate_cache
                    .record_term_absent)
                admitted = self.context.hbm_budget.admit(
                    batch, sum(a.nbytes for a in batch.arrays))
                stage_device_inputs(batch)  # async transfer starts now
                return ("batch", group, (batch, admitted))
            except Exception as exc:  # noqa: BLE001 - fall back per split
                if admitted is not None and batch is not None:
                    self.context.hbm_budget.release(batch, admitted)
                logger.debug("batch path failed (%s); searching per split", exc)
        return ("per_split", group,
                self._prepare_per_split(group, doc_mapper, search_request))

    def _discard_prepared(self, prepared) -> None:
        """A prefetched group dropped by the pruning short-circuit must
        return its admitted HBM pins (the per-split path takes none at
        prepare time — only the batch path pre-admits)."""
        kind, _group, data = prepared
        if kind == "batch":
            batch, admitted = data
            self.context.hbm_budget.release(batch, admitted)

    def _prepare_per_split(self, group, doc_mapper, search_request):
        prepared = []
        for split in group:
            try:
                reader = self.context.reader(split)
                cache = self.context.predicate_cache
                # plan-only (storage IO + lowering): the H2D transfer is
                # deferred to the execute stage so each split's
                # admit→transfer→execute→release cycle runs alone — a whole
                # group admitted up front could exceed the budget and
                # starve itself
                plan = prepare_plan_only(
                    search_request, doc_mapper, reader, split.split_id,
                    absence_sink=lambda f, t, s=split.split_id:
                        cache.record_term_absent(s, f, t))
                prepared.append((split, reader, plan, None))
            except Exception as exc:  # noqa: BLE001 - partial failure
                prepared.append((split, None, None, exc))
        return prepared

    def _execute_group(self, prepared, doc_mapper, search_request,
                       collector) -> None:
        """Stage 2 (main thread): kernel execution + readback + merge."""
        kind, group, data = prepared
        if kind == "batch":
            batch, admitted = data
            try:
                merged = execute_batch(batch, search_request)
                # batch responses cover several splits; cache only the merged
                # unit is wrong per-split, so cache skipped on the batch path
                collector.add_leaf_response(merged)
                return
            except Exception as exc:  # noqa: BLE001 - fall back per split
                logger.debug("batch execute failed (%s); per split", exc)
                # release BEFORE the per-split prepares re-admit: under a
                # tight budget the fallback would otherwise wait on its own
                # still-pinned batch bytes
                self.context.hbm_budget.release(batch, admitted)
                admitted = None
                data = self._prepare_per_split(group, doc_mapper,
                                               search_request)
            finally:
                if admitted is not None:
                    self.context.hbm_budget.release(batch, admitted)
        from .leaf import warmup_device_arrays
        deadline = current_deadline()
        for split, reader, plan, prep_error in data:
            if deadline is not None and deadline.expired:
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id,
                    error="deadline exceeded before split executed at leaf",
                    retryable=True))
                continue
            if prep_error is not None:
                _warn_split_failure("prepare", split.split_id, prep_error)
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id, error=str(prep_error),
                    retryable=True))
                continue
            admitted = 0
            warmed = False
            try:
                device_arrays, admitted = warmup_device_arrays(
                    reader, plan, self.context.hbm_budget)
                warmed = True
                response = execute_prepared_split(
                    search_request, doc_mapper, reader, split.split_id,
                    plan, device_arrays,
                    batcher=self.context.query_batcher)
                key = canonical_request_key(split.split_id, search_request,
                                            split.time_range)
                self.context.leaf_cache.put(key, response)
                collector.add_leaf_response(response)
            except Exception as exc:  # noqa: BLE001 - partial failure semantics
                _warn_split_failure("search", split.split_id, exc)
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id, error=str(exc), retryable=True))
            finally:
                if warmed:  # failed warmups release their own pins
                    self.context.hbm_budget.release(reader, admitted)

    @staticmethod
    def _optimize_split_order(request: SearchRequest,
                              splits: list[SplitIdAndFooter]) -> list[SplitIdAndFooter]:
        """Reference `CanSplitDoBetter::optimize_split_order` (leaf.rs:1279):
        timestamp sorts visit the splits most likely to own the top hits
        first (enables pruning + better partial results under timeouts)."""
        sort = request.sort_fields[0] if request.sort_fields else None
        if sort is None or not splits:
            return list(splits)
        if sort.field == "_score":
            return sorted(splits, key=lambda s: -s.num_docs)
        def end_key(s: SplitIdAndFooter):
            return s.time_range[1] if s.time_range else 0
        def start_key(s: SplitIdAndFooter):
            return s.time_range[0] if s.time_range else 0
        if sort.order == "desc":
            return sorted(splits, key=end_key, reverse=True)
        return sorted(splits, key=start_key)

    # ------------------------------------------------------------------
    def fetch_docs(self, request: FetchDocsRequest) -> list[dict[str, Any]]:
        reader = self.context.reader(request.split)
        docs = reader.fetch_docs(request.doc_ids)
        if request.snippet_fields and request.query_ast is not None:
            from .snippets import generate_snippets
            for doc in docs:
                doc["_snippets"] = generate_snippets(
                    doc, request.snippet_fields, request.query_ast)
        return docs


class LocalSearchClient:
    """In-process transport to a SearchService (the tests' and single-node
    deployments' client; the HTTP client in serve/ has the same surface)."""

    def __init__(self, service: SearchService):
        self.service = service

    def leaf_search(self, request: LeafSearchRequest) -> LeafSearchResponse:
        return self.service.leaf_search(request)

    def fetch_docs(self, request: FetchDocsRequest) -> list[dict[str, Any]]:
        return self.service.fetch_docs(request)
