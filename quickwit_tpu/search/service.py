"""Search service: the node-local search endpoints.

Role of the reference's `SearchService` trait + `SearchServiceImpl`
(`quickwit-search/src/service.rs:65`) and the leaf entry point
`multi_index_leaf_search`/`single_doc_mapping_leaf_search`
(`leaf.rs:1497,1887`):

- `leaf_search`: search a batch of splits of one index on this node — split
  reordering for pruning (`CanSplitDoBetter`), leaf cache, batched mesh
  execution when the plan is split-uniform, per-split fallback otherwise,
  partial failure collection.
- `fetch_docs`: phase-2 doc fetch + snippet generation.

The SearcherContext owns the caches (reader/hotcache byte ranges + device
arrays per split, leaf results) and the admission budget — the roles of the
reference's SearcherContext (`service.rs:405`) and SearchPermitProvider.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from collections import OrderedDict
from typing import Any, Optional

from ..common.ctx import run_with_context
from ..common.deadline import (
    CancelledQuery, Deadline, current_cancel_token, current_deadline,
    deadline_scope,
)
from ..index.reader import SplitReader
from ..models.doc_mapper import DocMapper
from ..observability.metrics import (
    SEARCH_DEADLINE_REMAINING, SEARCH_SHED_TOTAL,
    SEARCH_SPLITS_DOWNGRADED_TOTAL, SEARCH_SPLITS_PRUNED_TOTAL,
)
from ..observability.profile import (
    QueryProfile, current_profile, profile_scope,
)
from ..query.ast import MatchAll
from ..parallel.fanout import (
    build_batch, dispatch_batch, per_device_bytes, readback_batch,
    release_stack_pin, stage_device_inputs,
)
from ..storage.base import StorageResolver
from ..tenancy.context import (
    TenantContext, current_tenant, tenant_scope,
)
from ..tenancy.overload import OverloadShed
from ..tenancy.registry import TenantRateLimited
from .agg_cache import PartialAggCache, agg_shape_digest
from .cache import (LeafSearchCache, canonical_filter_digest,
                    canonical_request_key)
from .mask_cache import PredicateMaskCache, packed_mask_nbytes
from .predicate_cache import PredicateCache, required_terms
from .collector import IncrementalCollector
from .leaf import (execute_prepared_split, leaf_search_single_split,
                   prepare_plan_only)
from .models import (
    FetchDocsRequest, LeafSearchRequest, LeafSearchResponse, SearchRequest,
    SplitIdAndFooter, SplitSearchError, string_sort_of,
)
from .pruning import (
    PruningContext, ScoreBoundCache, ThresholdBox, downgrade_to_count,
    pruning_context, record_split_term_stats, split_best_internal_key,
)

logger = logging.getLogger(__name__)

# rate_limited_tracing.rs analogue: a bad query fanned over thousands of
# splits must not emit thousands of identical warnings
from ..observability.tracing import TRACER, RateLimitedLog  # noqa: E402
from ..common import sync

_SPLIT_WARN_LIMITER = RateLimitedLog(limit=5, period_secs=60.0)


def _warn_split_failure(kind: str, split_id: str, exc: object) -> None:
    emit, suppressed = _SPLIT_WARN_LIMITER.should_log(kind)
    if emit:
        extra = f" ({suppressed} similar suppressed)" if suppressed else ""
        logger.warning("split %s %s failed: %s%s", split_id, kind, exc,
                       extra)


class SearcherContext:
    def __init__(self, storage_resolver: Optional[StorageResolver] = None,
                 max_open_splits: int = 128,
                 leaf_cache_bytes: int = 64 << 20,
                 batch_size: int = 8,
                 prefetch: bool = True,
                 offload: Optional[dict] = None,
                 offload_endpoint: Optional[str] = None,
                 offload_max_local_splits: int = 16,
                 offload_client_factory=None,
                 split_cache=None,
                 enable_threshold_pruning: bool = True,
                 resident_columns: bool = True,
                 mask_cache_bytes: int = 32 << 20,
                 agg_cache_bytes: int = 32 << 20,
                 enable_mask_cache: bool = True,
                 enable_agg_cache: bool = True,
                 fault_injector=None):
        self.storage_resolver = storage_resolver or StorageResolver.default()
        # disk-resident split cache (reference SearchSplitCache,
        # split_cache/mod.rs:43): reader opens check it first; misses
        # report the split as a download candidate
        self.split_cache = split_cache
        self.leaf_cache = LeafSearchCache(leaf_cache_bytes)
        # hierarchical leaf caches (docs/hierarchical-cache.md). Tier A
        # memoizes evaluated filter bitmasks, Tier B memoizes per-split
        # count + intermediate agg states; both key on the canonical
        # FILTER digest so dashboard panels sharing one filter collapse.
        # Constructor flags serve equivalence tests; the QW_DISABLE_* env
        # kill switches serve operators (same pattern as QW_DISABLE_IMPACT).
        # `fault_injector` threads the chaos points (cache.mask_corrupt /
        # cache.evict) into both tiers and the residency store.
        self.fault_injector = fault_injector
        self.mask_cache = (
            PredicateMaskCache(mask_cache_bytes,
                               fault_injector=fault_injector)
            if enable_mask_cache
            and os.environ.get("QW_DISABLE_MASK_CACHE", "0") != "1"
            else None)
        self.agg_cache = (
            PartialAggCache(agg_cache_bytes, fault_injector=fault_injector)
            if enable_agg_cache
            and os.environ.get("QW_DISABLE_AGG_CACHE", "0") != "1"
            else None)
        self.batch_size = batch_size
        # warmup/compute pipelining (SURVEY hard-part #4): one prefetch
        # worker stages batch N+1's storage IO + H2D transfer while batch
        # N executes on device. Single worker = classic double buffering;
        # bounds both memory (at most one staged batch) and storage load.
        self.prefetch = prefetch
        self._prefetch_pool = None
        # predicate/negative cache: (split, term)-absence proofs prune
        # provably-empty splits before the reader is even constructed
        # (reference: leaf_cache.rs:197 + leaf.rs:758-841)
        self.predicate_cache = PredicateCache()
        # dynamic top-K pruning (reference CanSplitDoBetter, leaf.rs:1279):
        # once the collector holds K hits, splits whose sort bound cannot
        # beat the Kth value are skipped or downgraded to count-only.
        # The flag exists so equivalence tests can run an unpruned baseline.
        self.enable_threshold_pruning = enable_threshold_pruning
        # per-(split, field, term) df/max-tf for BM25 score upper bounds,
        # recorded at split open (search/pruning.py)
        self.score_bound_cache = ScoreBoundCache()
        # byte-accurate HBM admission (reference SearchPermitProvider):
        # the lowered plan knows every array's size, so over-budget work
        # queues instead of materializing
        from .admission import HbmBudget
        self.hbm_budget = HbmBudget()
        # device-resident column store (search/residency.py): a warm
        # split's packed columns stay in HBM across queries AND reader
        # reopens (residency keys on split id, not reader identity); the
        # budget sees resident bytes through its existing owner seam. The
        # flag exists so equivalence tests can run a cold-staging baseline.
        from .residency import ResidentColumnStore
        self.resident_store = (
            ResidentColumnStore(fault_injector=fault_injector)
            if resident_columns else None)
        # cross-query dispatch coalescing: concurrent same-structure
        # queries on one split ride a single vmapped dispatch
        # (search/batcher.py; reference analogue: per-node leaf request
        # batching, leaf.rs:81)
        from .batcher import QueryBatcher
        self.query_batcher = QueryBatcher()
        self._readers: OrderedDict[str, SplitReader] = OrderedDict()
        self._max_open_splits = max_open_splits
        self._lock = sync.lock("SearchService._lock")
        self._meshes: dict = {}
        # elastic leaf-search offload (reference: lambda leaf-search
        # offload, quickwit-lambda-client/src/invoker.rs:129 + the
        # scheduling split at leaf.rs:1658,1828): cold splits beyond
        # `max_local_splits` per leaf request fan out over an elastic
        # worker pool (quickwit_tpu/offload/) — any processes serving the
        # internal leaf-search protocol (peer nodes, a FaaS worker
        # fleet, ...). The legacy single-endpoint knobs migrate into a
        # pool-of-one; `offload=None` with no endpoint keeps the subsystem
        # unimported and the leaf path byte-identical to the pre-pool
        # behavior.
        if offload is None and offload_endpoint:
            offload = {"endpoints": [offload_endpoint]}
        self.offload = offload
        self.offload_endpoint = offload_endpoint
        self.offload_max_local_splits = (
            int(offload.get("max_local_splits", offload_max_local_splits))
            if offload is not None else offload_max_local_splits)
        self._offload_client_factory = offload_client_factory
        self._offload_pool = None
        self._offload_dispatcher = None

    def offload_dispatcher(self):
        """The pool dispatcher, built lazily on first offloading leaf
        request; None when no pool is configured."""
        if self.offload is None:
            return None
        with self._lock:
            if self._offload_dispatcher is None:
                from ..offload import (
                    Autoscaler, OffloadDispatcher, WorkerPool,
                )
                config = self.offload
                pool = WorkerPool(
                    suspect_after=int(config.get("suspect_after", 1)),
                    eject_after=int(config.get("eject_after", 3)),
                    readmit_backoff_secs=float(
                        config.get("readmit_backoff_secs", 0.5)),
                    readmit_backoff_max_secs=float(
                        config.get("readmit_backoff_max_secs", 30.0)))
                for endpoint in config.get("endpoints", ()):
                    if self._offload_client_factory is not None:
                        client = self._offload_client_factory(endpoint)
                    else:
                        from ..serve.http_client import HttpSearchClient
                        client = HttpSearchClient(endpoint)
                    pool.add_worker(endpoint, client)
                autoscaler = None
                launcher = config.get("launcher")
                if launcher is not None:
                    autoscale = config.get("autoscale") or {}
                    autoscaler = Autoscaler(
                        pool, launcher,
                        min_workers=int(autoscale.get("min_workers", 1)),
                        max_workers=int(autoscale.get("max_workers", 8)),
                        queue_per_worker=int(
                            autoscale.get("queue_per_worker", 16)),
                        scale_down_cooldown_secs=float(autoscale.get(
                            "scale_down_cooldown_secs", 10.0)))
                self._offload_pool = pool
                self._offload_dispatcher = OffloadDispatcher(
                    pool,
                    task_splits=int(config.get("task_splits", 8)),
                    max_inflight_per_worker=int(
                        config.get("max_inflight_per_worker", 1)),
                    hedge_min_delay_secs=float(
                        config.get("hedge_min_delay_secs", 0.05)),
                    hedge_max_delay_secs=float(
                        config.get("hedge_max_delay_secs", 5.0)),
                    injector=config.get("fault_injector"),
                    autoscaler=autoscaler)
            return self._offload_dispatcher

    def offload_pool(self):
        """The live WorkerPool (builds the dispatcher if needed); None
        when offload is unconfigured."""
        if self.offload_dispatcher() is None:
            return None
        return self._offload_pool

    def device_mesh(self, n_splits: int):
        """A 2D ("splits", "docs") mesh sized to shard `n_splits` across
        this host's accelerators, or None when the batch cannot shard —
        single device, single split, or no axis size >1 divides the batch.
        The None degenerate IS the seed single-device dispatch (host root
        merge), kept as the explicit fallback path.

        The splits axis takes the largest size ≤ ndev that divides the
        batch; leftover devices fold into the docs axis (largest power of
        two, so it always divides the DOC_PAD-aligned padded doc count) —
        dense column shards then spread over splits × docs while compute
        replicates along docs (parallel/fanout.mesh_batch_fn)."""
        import jax
        ndev = len(jax.devices())
        if ndev < 2 or n_splits < 2:
            return None
        axis = min(ndev, n_splits)
        while axis > 1 and n_splits % axis:
            axis -= 1
        if axis < 2:
            return None
        docs = 1
        while docs * 2 * axis <= ndev:
            docs *= 2
        with self._lock:
            mesh = self._meshes.get((axis, docs))
            if mesh is None:
                from ..parallel.fanout import make_mesh
                mesh = self._meshes[(axis, docs)] = make_mesh(axis, docs)
            return mesh

    def has_warm_reader(self, split: SplitIdAndFooter) -> bool:
        """True when this split's reader (and its byte-range/device
        caches) is already resident — the 'warm split' signal the offload
        scheduling uses (the reference offloads splits absent from the
        local split cache)."""
        with self._lock:
            return f"{split.storage_uri}/{split.split_id}" in self._readers

    def peek_reader(self, split: SplitIdAndFooter) -> Optional[SplitReader]:
        """Warm reader or None — NEVER opens a cold split. Threshold
        pruning consults footer metadata (field min/max, term max-tf)
        through this: paying a footer GET to maybe skip one kernel would
        often cost more than the kernel."""
        with self._lock:
            return self._readers.get(f"{split.storage_uri}/{split.split_id}")

    def prefetch_pool(self):
        from concurrent.futures import ThreadPoolExecutor
        with self._lock:
            if self._prefetch_pool is None:
                self._prefetch_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="leaf-prefetch")
            return self._prefetch_pool

    def reader(self, split: SplitIdAndFooter) -> SplitReader:
        """LRU-cached split readers: keeps footer, term dict, byte-range and
        device-array caches warm across queries (the warmup-amortization the
        reference's cache stack exists for)."""
        key = f"{split.storage_uri}/{split.split_id}"
        with self._lock:
            reader = self._readers.get(key)
            if reader is not None:
                self._readers.move_to_end(key)
                return reader
        storage = self.storage_resolver.resolve(split.storage_uri)
        if self.split_cache is not None:
            local = self.split_cache.local_path(split.split_id)
            if local is not None:
                from ..common.uri import Uri
                from ..storage.local import LocalFileStorage
                storage = LocalFileStorage(
                    Uri.parse(f"file://{self.split_cache.root_path}"))
            else:
                self.split_cache.report_split(
                    split.split_id, split.storage_uri,
                    num_bytes_hint=split.file_len or 0)
        reader = SplitReader(storage, f"{split.split_id}.split",
                             file_len=split.file_len)
        with self._lock:
            self._readers[key] = reader
            while len(self._readers) > self._max_open_splits:
                self._readers.popitem(last=False)
        return reader


class SearchService:
    """One node's search endpoints. Any node can act as root; leaf work runs
    where this service lives."""

    def __init__(self, context: Optional[SearcherContext] = None,
                 node_id: str = "node-0"):
        self.context = context or SearcherContext()
        self.node_id = node_id

    # ------------------------------------------------------------------
    def leaf_search(self, request: LeafSearchRequest) -> LeafSearchResponse:
        # A remote hop also drops the root's ambient tenant — rebuild it
        # from the wire field so leaf-side admission/batching enforce the
        # same class; embedded leaves (same process, fan-out thread)
        # already run under the root's binding.
        if request.tenant is not None and current_tenant() is None:
            with tenant_scope(TenantContext.from_wire(request.tenant)):
                return self._leaf_search_profiled(request)
        return self._leaf_search_profiled(request)

    def _leaf_search_profiled(self,
                              request: LeafSearchRequest) -> LeafSearchResponse:
        # A remote hop (REST/gRPC wire) drops the root's ambient profile
        # object — build a leaf-local one when profiling was requested and
        # ship it back on the response; embedded leaves (same process,
        # fan-out thread) write into the root's profile directly through
        # the ambient binding and must NOT double-profile.
        if (current_profile() is None
                and request.search_request.profile):
            local_profile = QueryProfile()
            with TRACER.span("leaf_search",
                             {"num_splits": len(request.splits)}):
                with profile_scope(local_profile):
                    response = self._leaf_search_traced(request)
            local_profile.finish()
            response.profile = local_profile.to_dict()
            return response
        with TRACER.span("leaf_search",
                         {"num_splits": len(request.splits)}):
            return self._leaf_search_traced(request)

    def _leaf_search_traced(self,
                            request: LeafSearchRequest) -> LeafSearchResponse:
        # The wire deadline (remaining budget serialized by the root) wins;
        # in-process callers inherit the ambient scope; otherwise unbounded.
        if request.deadline_millis is not None:
            deadline = Deadline.from_millis(request.deadline_millis)
        else:
            deadline = current_deadline() or Deadline.never()
        if deadline.bounded:
            SEARCH_DEADLINE_REMAINING.observe(deadline.remaining())
        with deadline_scope(deadline):
            return self._leaf_search_deadlined(request, deadline)

    def _leaf_search_deadlined(self, request: LeafSearchRequest,
                               deadline: Deadline) -> LeafSearchResponse:
        doc_mapper = DocMapper.from_dict(request.doc_mapping)
        search_request = request.search_request
        splits = self._optimize_split_order(search_request, request.splits)

        collector = IncrementalCollector(
            max_hits=search_request.max_hits,
            start_offset=search_request.start_offset,
            string_sort=string_sort_of(search_request, doc_mapper))
        # dynamic top-K pruning (reference CanSplitDoBetter): resolve the
        # sort kind once; the ThresholdBox carries the collector's Kth
        # value to the prefetch thread (monotone, so stale reads are sound)
        prune_ctx = (pruning_context(search_request, doc_mapper)
                     if self.context.enable_threshold_pruning
                     else PruningContext(None, None))
        threshold = ThresholdBox(
            seed=(request.sort_value_threshold
                  if prune_ctx.mode is not None else None))
        prune_stats = {"pruned": 0, "downgraded": 0}
        required = required_terms(search_request.query_ast, doc_mapper)
        num_pruned_by_predicate = 0
        pending: list[SplitIdAndFooter] = []
        for split in splits:
            if self._count_from_metadata(search_request, split):
                # pure count over the whole split: the metastore's doc count
                # IS the answer — never open or transfer the split
                # (reference: CanSplitDoBetter count path, leaf.rs:1361)
                collector.add_leaf_response(LeafSearchResponse(
                    num_hits=split.num_docs, num_attempted_splits=1,
                    num_successful_splits=1))
                continue
            if required and self.context.predicate_cache.known_empty(
                    split.split_id, required):
                # negative cache: a required term is proven absent from this
                # split — provably zero hits and identity agg states, so skip
                # the reader open, warmup, H2D, and kernel launch entirely
                num_pruned_by_predicate += 1
                collector.add_leaf_response(LeafSearchResponse(
                    num_hits=0, num_attempted_splits=1,
                    num_successful_splits=1))
                continue
            key = canonical_request_key(split.split_id, search_request,
                                        split.time_range)
            cached = self.context.leaf_cache.get(key)
            if cached is not None:
                collector.add_leaf_response(cached)
                continue
            agg_served = self._serve_from_agg_cache(search_request, split)
            if agg_served is not None:
                # Tier B full short-circuit: a count/agg-only request whose
                # count AND every agg state are cached never opens the
                # reader — the dashboard-fanout case collapses to a merge
                collector.add_leaf_response(agg_served)
                continue
            pending.append(split)

        if (prune_ctx.mode is not None and threshold.get() is not None
                and not search_request.count_hits_exact):
            # wire-seeded threshold (root retry round 2): drop provably
            # beaten splits BEFORE the offload cut, so pruned splits never
            # count against the local budget or ship to the endpoint
            still_pending: list[SplitIdAndFooter] = []
            for split in pending:
                best = self._split_bound(prune_ctx, split)
                if best is not None and best < threshold.get():
                    prune_stats["pruned"] += 1
                    SEARCH_SPLITS_PRUNED_TOTAL.inc()
                    collector.add_leaf_response(LeafSearchResponse(
                        num_hits=0, num_attempted_splits=1,
                        num_successful_splits=1))
                else:
                    still_pending.append(split)
            pending = still_pending

        offload_future = None
        offload_result: dict[str, Any] = {}
        offloaded: list[SplitIdAndFooter] = []
        offload_dispatcher = self.context.offload_dispatcher()
        if (offload_dispatcher is not None
                and len(pending) > self.context.offload_max_local_splits):
            # scheduling split (reference schedule_search_tasks,
            # leaf.rs:1828): warm splits stay local; the coldest tail
            # beyond the local budget fans out over the worker pool
            # CONCURRENTLY with the local loop
            warm = [s for s in pending if self.context.has_warm_reader(s)]
            cold = [s for s in pending
                    if not self.context.has_warm_reader(s)]
            budget = max(self.context.offload_max_local_splits, len(warm))
            local = (warm + cold)[:budget]
            offloaded = (warm + cold)[budget:]
            if offloaded:
                pending = local
                offload_tenant = current_tenant()
                remote_request = LeafSearchRequest(
                    search_request=search_request,
                    index_uid=request.index_uid,
                    doc_mapping=request.doc_mapping, splits=offloaded,
                    deadline_millis=deadline.timeout_millis(),
                    # the offload workers enforce the same tenant class
                    tenant=(offload_tenant.to_wire()
                            if offload_tenant is not None else None),
                    # seeded at dispatch time inside _invoke (below): the
                    # threshold is monotone, so the LATEST value prunes
                    # strictly more on the workers than a capture-time copy
                    sort_value_threshold=None)
                result_box: dict[str, Any] = {}
                # the dispatch thread has an empty thread-local span stack:
                # capture the traceparent HERE so each worker RPC's
                # injected header joins this query's trace (same capture
                # as root _fan_out)
                offload_tp = TRACER.current_traceparent()

                def _invoke(box=result_box, rr=remote_request,
                            tp=offload_tp):
                    try:
                        # read the shared ThresholdBox from the dispatch
                        # thread, NOT at capture time: the local execute
                        # loop keeps raising it concurrently
                        if prune_ctx.mode is not None:
                            rr = dataclasses.replace(
                                rr, sort_value_threshold=threshold.get())
                        with TRACER.span(
                                "leaf_offload",
                                {"num_splits": len(rr.splits)},
                                remote_parent=tp):
                            box["outcome"] = offload_dispatcher.dispatch(
                                rr, deadline=deadline, traceparent=tp)
                    except (OverloadShed, TenantRateLimited) as exc:
                        # typed backpressure from a worker: this query is
                        # rejected as a WHOLE (HTTP 429), NOT retried
                        # locally — a local retry would defeat the remote
                        # tenant limits
                        box["backpressure"] = exc
                    # qwlint: disable-next-line=QW004 - only generic pool
                    # failure lands here (typed backpressure is re-raised
                    # above); the offloaded splits fall back to LOCAL
                    # execution below, so nothing is swallowed
                    except Exception as exc:  # noqa: BLE001 - fallback below
                        box["error"] = exc

                # run_with_context: the dispatch thread (and the worker
                # attempt threads it spawns) must see the query's
                # deadline, tenant and profile
                offload_future = sync.thread(
                    target=run_with_context(_invoke),
                    name="leaf-offload", daemon=True)
                offload_future.start()
                offload_result = result_box

        batch_size = self.context.batch_size
        groups = [pending[b: b + batch_size]
                  for b in range(0, len(pending), batch_size)]
        # pipelined loop: group i executes while group i+1's storage IO and
        # H2D transfer run on the prefetch worker (double buffering —
        # reference rationale: the warmup/cache stack of leaf.rs:304).
        # The prefetch worker re-reads the ThresholdBox before staging, so
        # a split that just became prunable never burns storage IO or H2D;
        # the execute stage re-checks once more (the threshold is monotone,
        # so both reads are sound however stale).
        pipelined = self.context.prefetch and len(groups) > 1
        future = None
        if pipelined:
            # contextvars do not reach pool worker threads: one snapshot
            # carries deadline+tenant+profile (and any future binding)
            future = self.context.prefetch_pool().submit(
                run_with_context(self._prepare_group),
                groups[0], doc_mapper, search_request, prune_ctx, threshold)
        for i, group in enumerate(groups):
            begin = i * batch_size
            if deadline.expired:
                # out of budget mid-request: every remaining split surfaces
                # as a typed, retryable failure — partial and on time
                SEARCH_SHED_TOTAL.inc(stage="leaf_groups")
                shed_profile = current_profile()
                if shed_profile is not None:
                    shed_profile.mark_partial("shed: leaf group loop")
                for split in pending[begin:]:
                    collector.failed_splits.append(SplitSearchError(
                        split_id=split.split_id,
                        error="deadline exceeded before split executed at leaf",
                        retryable=True))
                if future is not None:
                    self._discard_prepared(future.result())
                    future = None
                break
            prepared = (future.result() if future is not None
                        else self._prepare_group(group, doc_mapper,
                                                 search_request, prune_ctx,
                                                 threshold))
            future = None
            if pipelined and i + 1 < len(groups):
                future = self.context.prefetch_pool().submit(
                    run_with_context(self._prepare_group),
                    groups[i + 1], doc_mapper, search_request, prune_ctx,
                    threshold)
            self._execute_group(prepared, doc_mapper, search_request,
                                collector, prune_ctx, threshold, prune_stats)
            # publish the (possibly higher) Kth value for the next groups
            threshold.update(collector.sort_value_threshold())

        num_offloaded = 0
        if offload_future is not None:
            offload_future.join(
                timeout=deadline.clamp(self._OFFLOAD_TIMEOUT_SECS))
            backpressure = offload_result.get("backpressure")
            if backpressure is not None:
                # a worker said 429 for this tenant/node: surface the SAME
                # typed error so serve/rest.py renders a real 429 instead
                # of silently re-running the splits locally (which would
                # bypass the remote admission decision)
                raise backpressure
            outcome = offload_result.get("outcome")
            leftovers: list[SplitIdAndFooter] = []
            if outcome is not None:
                for remote in outcome.responses:
                    collector.add_leaf_response(remote)
                    if remote.profile is not None:
                        remote_profile = current_profile()
                        if remote_profile is not None:
                            remote_profile.add_child(remote.profile)
                leftovers = list(outcome.unserved)
                num_offloaded = len(offloaded) - len(leftovers)
                stats_profile = current_profile()
                if stats_profile is not None:
                    for stat_key, value in outcome.stats.items():
                        if value:
                            stats_profile.add(f"offload_{stat_key}", value)
            else:
                leftovers = list(offloaded)
            if leftovers:
                # pool failed / timed out / left splits unserved: the
                # splits still belong to this request — run them locally
                # (reference invoker falls back the same way)
                _warn_split_failure(
                    "offload", leftovers[0].split_id,
                    offload_result.get(
                        "error",
                        "unserved" if outcome is not None else "timeout"))
                for group in [leftovers[b: b + batch_size]
                              for b in range(0, len(leftovers), batch_size)]:
                    if deadline.expired:
                        SEARCH_SHED_TOTAL.inc(stage="offload_fallback")
                        shed_profile = current_profile()
                        if shed_profile is not None:
                            shed_profile.mark_partial(
                                "shed: offload fallback")
                        for split in group:
                            collector.failed_splits.append(SplitSearchError(
                                split_id=split.split_id,
                                error="deadline exceeded before offloaded "
                                      "split ran locally",
                                retryable=True))
                        continue
                    prepared = self._prepare_group(group, doc_mapper,
                                                   search_request, prune_ctx,
                                                   threshold)
                    self._execute_group(prepared, doc_mapper, search_request,
                                        collector, prune_ctx, threshold,
                                        prune_stats)
                    threshold.update(collector.sort_value_threshold())

        response = collector.to_leaf_response()
        response.num_attempted_splits = len(splits)
        # num_splits_skipped predates the threshold subsystem and stays as
        # an alias of the threshold-pruned count (dashboards key on it)
        response.resource_stats["num_splits_skipped"] = prune_stats["pruned"]
        response.resource_stats["num_splits_pruned_by_threshold"] = \
            prune_stats["pruned"]
        response.resource_stats["num_splits_downgraded_to_count"] = \
            prune_stats["downgraded"]
        response.resource_stats["num_splits_pruned_by_predicate_cache"] = \
            num_pruned_by_predicate
        if num_offloaded:
            response.resource_stats["num_splits_offloaded"] = num_offloaded
        profile = current_profile()
        if profile is not None:
            # pruning decisions land in the waterfall's counters; the
            # threshold that killed the pruned splits rides along so the
            # profile can answer "skipped — against WHAT bound?"
            for stat_key, value in response.resource_stats.items():
                profile.add(stat_key, value)
            final_threshold = threshold.get()
            if final_threshold is not None and (
                    prune_stats["pruned"] or prune_stats["downgraded"]):
                profile.set_counter("topk_prune_threshold",
                                    float(final_threshold))
        return response

    _OFFLOAD_TIMEOUT_SECS = 30.0

    @staticmethod
    def _count_from_metadata(request: SearchRequest,
                             split: SplitIdAndFooter) -> bool:
        """True when this split's contribution is exactly its doc count:
        match-all query, no hits wanted, no aggregations, and any request
        time filter fully covers the split's own time range (sound because
        the doc mapper requires the timestamp field on every doc, so the
        split range bounds all of them)."""
        if (request.max_hits != 0 or request.start_offset != 0
                or request.aggs or not isinstance(request.query_ast, MatchAll)):
            return False
        if request.start_timestamp is None and request.end_timestamp is None:
            return True
        if split.time_range is None:
            return False  # no bounds recorded: must evaluate
        lo, hi = split.time_range
        if request.start_timestamp is not None and request.start_timestamp > lo:
            return False
        # end_timestamp is exclusive; split ranges are inclusive
        if request.end_timestamp is not None and request.end_timestamp <= hi:
            return False
        return True

    def _split_bound(self, prune_ctx: PruningContext,
                     split: SplitIdAndFooter) -> Optional[float]:
        """Best internal sort key any doc of `split` can reach, or None
        (must run). Consults only metadata already in hand: split
        time_range, a WARM reader's footer field min/max, or the score
        bound cache (falling back to a warm reader's term stats)."""
        def field_meta():
            reader = self.context.peek_reader(split)
            return (reader.field_meta(prune_ctx.sort.field)
                    if reader is not None else None)

        def score_stats(field, term):
            stats = self.context.score_bound_cache.get(
                split.split_id, field, term)
            if stats is None:
                reader = self.context.peek_reader(split)
                if reader is None:
                    return None
                df, max_tf = reader.term_stats(field, term)
                cap = reader.term_score_cap(field, term)
                stats = (df, max_tf, cap)
                self.context.score_bound_cache.record(
                    split.split_id, field, term, df, max_tf, cap)
            return stats

        return split_best_internal_key(prune_ctx, split,
                                       field_meta_fn=field_meta,
                                       score_stats_fn=score_stats)

    def _classify_group(self, group, search_request, prune_ctx, threshold):
        """(run, skipped, to_count): splits whose bound cannot beat the
        current threshold are skipped (inexact counting) or downgraded to
        count-only requests (exact counting); ties always run."""
        thr = threshold.get() if prune_ctx.mode is not None else None
        if thr is None:
            return list(group), [], []
        run, skipped, to_count = [], [], []
        for split in group:
            best = self._split_bound(prune_ctx, split)
            if best is not None and best < thr:
                (to_count if search_request.count_hits_exact
                 else skipped).append(split)
            else:
                run.append(split)
        return run, skipped, to_count

    def _prepare_group(self, group, doc_mapper, search_request, prune_ctx,
                       threshold):
        """Stage 1 (prefetch-thread-safe): threshold re-check + storage IO,
        plan lowering, and the async H2D transfer for one split group.
        Returns an opaque prepared unit for `_execute_group`:
        (kind, run_group, data, extras) where extras carries the
        threshold-pruned splits (skipped / count-ready / count-prepared)."""
        run_group, skipped, to_count = self._classify_group(
            group, search_request, prune_ctx, threshold)
        count_ready: list[tuple] = []
        count_prepared: list[tuple] = []
        count_request = None
        if to_count:
            # exact counting: the split still owes its hit count — re-issue
            # as a count-only request (max_hits=0) riding the metadata
            # count, the leaf cache, or the k==0 no-sort/no-top-k kernel
            count_request = downgrade_to_count(search_request)
            for split in to_count:
                if self._count_from_metadata(count_request, split):
                    count_ready.append((split, LeafSearchResponse(
                        num_hits=split.num_docs, num_attempted_splits=1,
                        num_successful_splits=1)))
                    continue
                key = canonical_request_key(split.split_id, count_request,
                                            split.time_range)
                cached = self.context.leaf_cache.get(key)
                if cached is not None:
                    count_ready.append((split, cached))
                    continue
                if self.context.agg_cache is not None:
                    # Tier B: the count entry shares the filter digest with
                    # the full request, so a downgraded split whose count
                    # was ever computed (any top-K, sort, or agg variant)
                    # resolves without opening the reader
                    cached_count = self.context.agg_cache.get_count(
                        split.split_id,
                        canonical_filter_digest(count_request,
                                                split.time_range))
                    if cached_count is not None:
                        count_ready.append((split, LeafSearchResponse(
                            num_hits=cached_count, num_attempted_splits=1,
                            num_successful_splits=1)))
                        continue
                count_prepared.extend(self._prepare_per_split(
                    [split], doc_mapper, count_request, prune_ctx=None))
        extras = {"skipped": skipped, "count_ready": count_ready,
                  "count_prepared": count_prepared,
                  "count_request": count_request}
        push_thr = (threshold.get() if prune_ctx.mode is not None else None)
        # the batch path has no search_after pushdown or per-split terms
        # truncation; the per-split path handles those (2-key sorts ride
        # the batch via the lexicographic cross-split re-top-k)
        import json as _json
        if (len(run_group) > 1 and not search_request.search_after
                and string_sort_of(search_request, doc_mapper) is None
                and not self._split_caches_route_per_split(search_request)
                and not any(key in _json.dumps(search_request.aggs or {})
                            for key in ("split_size", "shard_size",
                                        "segment_size"))):
            # Batch lanes must be in split_id order: the kernel's
            # cross-split merge breaks sort-value ties by flattened lane
            # index (fanout.batch_fn / ops.topk.exact_topk_2key), and the
            # collector's total order is (key desc, split_id asc, doc asc).
            # _optimize_split_order and the offload cut reorder/recompose
            # run_group between passes, so an all-ties search would
            # otherwise keep a DIFFERENT tie subset under truncation cold
            # vs warm, breaking cache_cold_equivalence.
            run_group = sorted(run_group, key=lambda s: s.split_id)
            admitted = None
            batch = None
            try:
                readers = [self.context.reader(s) for s in run_group]
                if prune_ctx.mode == "score":
                    for reader, split in zip(readers, run_group):
                        record_split_term_stats(
                            self.context.score_bound_cache, split.split_id,
                            reader, prune_ctx.terms)
                batch = build_batch(
                    search_request, doc_mapper, readers,
                    [s.split_id for s in run_group],
                    absence_sink=self.context.predicate_cache
                    .record_term_absent,
                    sort_value_threshold=push_thr)
                # the mesh is fixed at staging time: arrays committed for
                # one sharding must not feed an executor traced for another
                mesh = self.context.device_mesh(batch.n_splits)
                # per-DEVICE admission: each chip pins only its shard of
                # the stacks; column-family bytes are admitted under the
                # mesh-resident stack owner inside stage_device_inputs
                # (and stay warm), so exclude them here when that store
                # will take them
                stack_store = (self.context.resident_store
                               if mesh is not None else None)
                admitted = self.context.hbm_budget.admit(
                    batch, per_device_bytes(
                        batch, mesh,
                        exclude_stack_resident=(
                            stack_store is not None
                            and stack_store.enabled)))
                stage_device_inputs(  # async transfer starts now
                    batch, mesh, resident_store=stack_store,
                    budget=self.context.hbm_budget)
                return ("batch", run_group, (batch, admitted, mesh), extras)
            except (OverloadShed, TenantRateLimited):
                # whole-query backpressure, not a split failure: falling
                # back per split would just re-admit and shed again
                if admitted is not None and batch is not None:
                    self.context.hbm_budget.release(batch, admitted)
                if batch is not None:
                    release_stack_pin(batch, self.context.hbm_budget)
                raise
            except Exception as exc:  # noqa: BLE001 - fall back per split
                if admitted is not None and batch is not None:
                    self.context.hbm_budget.release(batch, admitted)
                if batch is not None:
                    release_stack_pin(batch, self.context.hbm_budget)
                logger.debug("batch path failed (%s); searching per split", exc)
        return ("per_split", run_group,
                self._prepare_per_split(run_group, doc_mapper, search_request,
                                        prune_ctx=prune_ctx,
                                        sort_value_threshold=push_thr),
                extras)

    def _discard_prepared(self, prepared) -> None:
        """A prefetched group dropped by the deadline must return its
        admitted HBM pins (the per-split path takes none at prepare time —
        only the batch path pre-admits)."""
        kind, _group, data, _extras = prepared
        if kind == "batch":
            batch, admitted, _mesh = data
            self.context.hbm_budget.release(batch, admitted)
            release_stack_pin(batch, self.context.hbm_budget)

    def _prepare_per_split(self, group, doc_mapper, search_request,
                           prune_ctx=None, sort_value_threshold=None):
        prepared = []
        for split in group:
            try:
                reader = self.context.reader(split)
                cache = self.context.predicate_cache
                if prune_ctx is not None and prune_ctx.mode == "score":
                    # remember df/max-tf at split open so future queries
                    # can bound this split before (re)opening it
                    record_split_term_stats(
                        self.context.score_bound_cache, split.split_id,
                        reader, prune_ctx.terms)
                # plan-only (storage IO + lowering): the H2D transfer is
                # deferred to the execute stage so each split's
                # admit→transfer→execute→release cycle runs alone — a whole
                # group admitted up front could exceed the budget and
                # starve itself
                cache_ctx = self._consult_split_caches(search_request,
                                                       split, reader)
                plan = prepare_plan_only(
                    search_request, doc_mapper, reader, split.split_id,
                    absence_sink=lambda f, t, s=split.split_id:
                        cache.record_term_absent(s, f, t),
                    sort_value_threshold=sort_value_threshold,
                    aggs_override=(cache_ctx or {}).get("aggs_override"),
                    mask_override=(cache_ctx or {}).get("mask"),
                    mask_key=(cache_ctx or {}).get("mask_key"))
                prepared.append((split, reader, plan, None, cache_ctx))
            except (OverloadShed, TenantRateLimited):
                # whole-query backpressure: demoting it to a per-split
                # failure here would turn a typed 429 into a generic 400
                # (same contract as _prepare_group/_execute_per_split)
                raise
            except Exception as exc:  # noqa: BLE001 - partial failure
                prepared.append((split, None, None, exc, None))
        return prepared

    # --- hierarchical leaf caches (Tier A/B, docs/hierarchical-cache.md) --

    def _serve_from_agg_cache(self, request, split):
        """Full Tier B short-circuit: a count/agg-only request (max_hits=0,
        no offset) whose count AND every agg state are cached builds its
        LeafSearchResponse from partials alone — no reader open, no
        staging, no kernel. Any missing piece returns None (the split runs
        normally and refills)."""
        agg_cache = self.context.agg_cache
        if (agg_cache is None or request.max_hits != 0
                or request.start_offset != 0):
            return None
        digest = canonical_filter_digest(request, split.time_range)
        count = agg_cache.get_count(split.split_id, digest)
        if count is None:
            return None
        states: dict[str, Any] = {}
        for name, spec in (request.aggs or {}).items():
            state = agg_cache.get_agg(split.split_id, digest,
                                      agg_shape_digest(spec))
            if state is None:
                return None
            states[name] = state
        return LeafSearchResponse(
            num_hits=count, num_attempted_splits=1, num_successful_splits=1,
            intermediate_aggs=states)

    def _split_caches_route_per_split(self, request) -> bool:
        """True when the Tier A/B caches could serve or warm this request.
        Consults and fills are per-split operations; the fused batch path
        merges its results on-mesh, so a batched group can neither use a
        cached mask nor attribute partials back to one split. Such groups
        route per-split instead — cheap since the resident column store
        keeps warm splits on device either way. Scoring sorts stay fused
        (mask-ineligible: the default sort IS _score and the mask carries
        no BM25 scores) except agg-only requests, where Tier B applies
        regardless of sort. Both kill switches off restores the fused
        routing bit-identically."""
        sort_fields = [s.field for s in request.sort_fields] or ["_score"]
        if self.context.mask_cache is not None and "_score" not in sort_fields:
            return True
        return (self.context.agg_cache is not None and bool(request.aggs)
                and request.max_hits == 0 and request.start_offset == 0)

    def _consult_split_caches(self, request, split, reader):
        """Tier A/B lookups for one split, before lowering. Returns None
        (both tiers off) or a cache_ctx dict driving `prepare_plan_only`
        and the post-execute fill:

        - mask / mask_key: a cached packed predicate mask replaces the
          whole query root (zero predicate columns fetched or staged);
          mask_fill marks a miss to backfill. Scoring requests are
          ineligible — the mask carries no BM25 scores, and the default
          sort IS _score.
        - agg_hits: cached intermediate states attached post-execute;
          aggs_override: the missed subset actually lowered ({} lowers
          none); agg_fill: names to backfill from the response."""
        mask_cache = self.context.mask_cache
        agg_cache = self.context.agg_cache
        if mask_cache is None and agg_cache is None:
            return None
        digest = canonical_filter_digest(request, split.time_range)
        ctx: dict[str, Any] = {
            "digest": digest, "mask": None, "mask_key": None,
            "mask_fill": False, "agg_hits": {}, "aggs_override": None,
            "agg_fill": []}
        sort_fields = [s.field for s in request.sort_fields] or ["_score"]
        if mask_cache is not None and "_score" not in sort_fields:
            packed = mask_cache.get(split.split_id, digest,
                                    packed_mask_nbytes(reader.num_docs_padded))
            if packed is not None:
                ctx["mask"] = packed
                ctx["mask_key"] = f"mask.{digest}"
            else:
                ctx["mask_fill"] = True
        if agg_cache is not None and request.aggs:
            missing: dict[str, Any] = {}
            for name, spec in request.aggs.items():
                state = agg_cache.get_agg(split.split_id, digest,
                                          agg_shape_digest(spec))
                if state is not None:
                    ctx["agg_hits"][name] = state
                else:
                    missing[name] = spec
            if ctx["agg_hits"]:
                ctx["aggs_override"] = missing
            ctx["agg_fill"] = list(missing)
        return ctx

    def _fill_split_caches(self, request, split, plan, device_arrays,
                           response, cache_ctx, owner=None) -> None:
        """Post-execute backfill, while the split's device arrays are still
        pinned. Fills are best-effort: a failure (including injected cache
        faults) degrades to an uncached split, never fails the query."""
        if cache_ctx is None:
            return
        digest = cache_ctx["digest"]
        mask_cache = self.context.mask_cache
        if (mask_cache is not None and cache_ctx.get("mask_fill")
                and plan.count_override is None):
            # count_override marks an impact-prefix-truncated plan (format
            # v3): the kernel never saw the posting tail, so its mask is
            # incomplete — skip the fill, never cache a partial mask
            from .executor import compute_packed_mask
            try:
                host_packed, dev_packed = compute_packed_mask(
                    plan, device_arrays)
                mask_cache.put(split.split_id, digest, host_packed)
                store = self.context.resident_store
                if (store is not None and owner is not None
                        and getattr(owner, "_device_array_cache",
                                    None) is not None):
                    # seed the device copy under the SAME key a mask-hit
                    # plan will stage (`mask.<digest>`): the next warm run
                    # finds every array resident and uploads nothing.
                    # Accounted in the store's byte stats (columns=0: the
                    # mask is not a column miss); the padded/8 bytes ride
                    # outside HbmBudget admission by design — they are
                    # noise next to any column and admission could shed a
                    # best-effort fill
                    owner._device_array_cache[f"mask.{digest}"] = dev_packed
                    store.note_upload(split.split_id,
                                      int(dev_packed.nbytes), 0)
            except (OverloadShed, TenantRateLimited):
                raise
            except Exception as exc:  # noqa: BLE001 - fill is best-effort
                logger.debug("mask-cache fill failed for %s: %s",
                             split.split_id, exc)
        agg_cache = self.context.agg_cache
        if agg_cache is None:
            return
        try:
            # sound under threshold pushdown and search_after: the kernel
            # computes count/aggs from the FULL filter mask (executor.py);
            # only the hit list is eligibility-restricted
            agg_cache.put_count(split.split_id, digest, response.num_hits)
            for name in cache_ctx.get("agg_fill", ()):
                state = response.intermediate_aggs.get(name)
                spec = (request.aggs or {}).get(name)
                if state is not None and spec is not None:
                    agg_cache.put_agg(split.split_id, digest,
                                      agg_shape_digest(spec), state)
        except (OverloadShed, TenantRateLimited):
            raise
        except Exception as exc:  # noqa: BLE001 - fill is best-effort
            logger.debug("agg-cache fill failed for %s: %s",
                         split.split_id, exc)

    def _execute_group(self, prepared, doc_mapper, search_request,
                       collector, prune_ctx, threshold, prune_stats) -> None:
        """Stage 2 (main thread): kernel execution + readback + merge."""
        kind, group, data, extras = prepared
        for split in extras["skipped"]:
            # conclusively handled without execution: zero hits here can
            # reach the top-K (num_hits is a lower bound when
            # count_hits_exact=False, same contract as before)
            prune_stats["pruned"] += 1
            SEARCH_SPLITS_PRUNED_TOTAL.inc()
            collector.add_leaf_response(LeafSearchResponse(
                num_hits=0, num_attempted_splits=1, num_successful_splits=1))
        for _split, response in extras["count_ready"]:
            prune_stats["downgraded"] += 1
            SEARCH_SPLITS_DOWNGRADED_TOTAL.inc()
            collector.add_leaf_response(response)
        if extras["count_prepared"]:
            prune_stats["downgraded"] += len(extras["count_prepared"])
            SEARCH_SPLITS_DOWNGRADED_TOTAL.inc(
                len(extras["count_prepared"]))
            self._execute_per_split(
                extras["count_prepared"], doc_mapper,
                extras["count_request"], collector,
                prune_ctx=None, threshold=None, prune_stats=None)
        if kind == "batch":
            batch, admitted, mesh = data
            try:
                # dispatch and readback are split so the deadline can shed
                # BETWEEN them: the fused kernel may run to completion on
                # device, but a query nobody is waiting for never pays the
                # device->host transfer (scalars die with their buffers)
                dispatched = dispatch_batch(batch, search_request, mesh)
                deadline = current_deadline()
                if deadline is not None and deadline.expired:
                    from ..parallel.fanout import abandon_dispatch
                    from .residency import RESIDENT_READBACKS_SHED
                    # the mesh-dispatch guard (CPU host platform) must
                    # still observe program completion before the next
                    # collective program may enqueue
                    abandon_dispatch(dispatched)
                    RESIDENT_READBACKS_SHED.inc()
                    profile = current_profile()
                    if profile is not None:
                        profile.mark_partial("shed: batch readback")
                    for split_id in batch.split_ids:
                        if split_id:
                            collector.failed_splits.append(SplitSearchError(
                                split_id=split_id,
                                error="deadline exceeded before readback "
                                      "was awaited",
                                retryable=True))
                    return
                merged = readback_batch(dispatched)
                # batch responses cover several splits; cache only the merged
                # unit is wrong per-split, so cache skipped on the batch path
                collector.add_leaf_response(merged)
                return
            except (OverloadShed, TenantRateLimited):
                self.context.hbm_budget.release(batch, admitted)
                admitted = None  # the finally below must not release twice
                raise
            except Exception as exc:  # noqa: BLE001 - fall back per split
                logger.debug("batch execute failed (%s); per split", exc)
                # release BEFORE the per-split prepares re-admit: under a
                # tight budget the fallback would otherwise wait on its own
                # still-pinned batch bytes
                self.context.hbm_budget.release(batch, admitted)
                admitted = None
                release_stack_pin(batch, self.context.hbm_budget)
                data = self._prepare_per_split(
                    group, doc_mapper, search_request, prune_ctx=prune_ctx,
                    sort_value_threshold=(threshold.get()
                                          if prune_ctx.mode is not None
                                          else None))
            finally:
                if admitted is not None:
                    self.context.hbm_budget.release(batch, admitted)
                # idempotent: converts the stack pin to resident exactly
                # once, whichever exit path ran first
                release_stack_pin(batch, self.context.hbm_budget)
        self._execute_per_split(data, doc_mapper, search_request, collector,
                                prune_ctx=prune_ctx, threshold=threshold,
                                prune_stats=prune_stats)

    def _execute_per_split(self, data, doc_mapper, search_request, collector,
                           prune_ctx=None, threshold=None,
                           prune_stats=None) -> None:
        from .leaf import warmup_device_arrays
        deadline = current_deadline()
        cancel = current_cancel_token()
        profile = current_profile()
        for split, reader, plan, prep_error, cache_ctx in data:
            if cancel is not None and cancel.cancelled:
                # cancelled between splits: unexecuted splits are reported
                # as non-retryable cancel failures (the root must not spend
                # its retry pool re-running work the caller abandoned)
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id,
                    error=f"query cancelled before split executed"
                          f"{': ' + cancel.reason if cancel.reason else ''}",
                    retryable=False))
                continue
            if deadline is not None and deadline.expired:
                if profile is not None:
                    profile.mark_partial("shed: split execute")
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id,
                    error="deadline exceeded before split executed at leaf",
                    retryable=True))
                continue
            if prep_error is not None:
                _warn_split_failure("prepare", split.split_id, prep_error)
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id, error=str(prep_error),
                    retryable=True))
                continue
            if (prune_ctx is not None and prune_ctx.mode is not None
                    and threshold is not None
                    and not search_request.count_hits_exact):
                # execute-time re-check: the threshold may have risen past
                # this split's bound since the prefetch thread prepared it
                # (wasted prepare IO is the price of overlap, never wrong
                # results)
                thr = threshold.get()
                if thr is not None:
                    best = self._split_bound(prune_ctx, split)
                    if best is not None and best < thr:
                        if prune_stats is not None:
                            prune_stats["pruned"] += 1
                        SEARCH_SPLITS_PRUNED_TOTAL.inc()
                        collector.add_leaf_response(LeafSearchResponse(
                            num_hits=0, num_attempted_splits=1,
                            num_successful_splits=1))
                        continue
            admitted = 0
            warmed = False
            owner = reader
            try:
                device_arrays, admitted, owner = warmup_device_arrays(
                    reader, plan, self.context.hbm_budget,
                    store=self.context.resident_store,
                    split_id=split.split_id)
                warmed = True
                response = execute_prepared_split(
                    search_request, doc_mapper, reader, split.split_id,
                    plan, device_arrays,
                    batcher=self.context.query_batcher,
                    threshold_box=threshold,
                    fault_injector=self.context.fault_injector)
                if cache_ctx is not None and cache_ctx["agg_hits"]:
                    # Tier B hits join the response BEFORE the leaf-cache
                    # put and the merge — the cached LeafSearchResponse
                    # must be complete, and the collector merges by name
                    response.intermediate_aggs.update(cache_ctx["agg_hits"])
                self._fill_split_caches(search_request, split, plan,
                                        device_arrays, response, cache_ctx,
                                        owner=owner)
                if plan.threshold_slot < 0:
                    # a threshold-pushdown response may have its hit list
                    # truncated below k — correct for THIS query's merge,
                    # poison for a future query with a lower threshold
                    key = canonical_request_key(
                        split.split_id, search_request, split.time_range)
                    self.context.leaf_cache.put(key, response)
                collector.add_leaf_response(response)
                if threshold is not None:
                    threshold.update(collector.sort_value_threshold())
            except (OverloadShed, TenantRateLimited):
                # a shed/rate-limited tenant is rejected as a WHOLE query
                # (429 + Retry-After at the API layer) — recording it as a
                # retryable split failure would make the root burn retries
                # on work the controller just refused
                raise
            except CancelledQuery as exc:
                # NEVER retryable: the caller asked for the query to stop.
                # Remaining splits fall out at the top-of-loop cancel check.
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id, error=str(exc), retryable=False))
            except Exception as exc:  # noqa: BLE001 - partial failure semantics
                _warn_split_failure("search", split.split_id, exc)
                collector.failed_splits.append(SplitSearchError(
                    split_id=split.split_id, error=str(exc), retryable=True))
            finally:
                if warmed:  # failed warmups release their own pins
                    # releasing against the residency OWNER (not the reader)
                    # is what moves the pins to resident instead of freeing
                    # them: the owner carries `_device_array_cache`
                    self.context.hbm_budget.release(owner, admitted)

    @staticmethod
    def _optimize_split_order(request: SearchRequest,
                              splits: list[SplitIdAndFooter]) -> list[SplitIdAndFooter]:
        """Reference `CanSplitDoBetter::optimize_split_order` (leaf.rs:1279):
        timestamp sorts visit the splits most likely to own the top hits
        first (enables pruning + better partial results under timeouts)."""
        sort = request.sort_fields[0] if request.sort_fields else None
        if sort is None or not splits:
            return list(splits)
        if sort.field == "_score":
            return sorted(splits, key=lambda s: -s.num_docs)
        def end_key(s: SplitIdAndFooter):
            return s.time_range[1] if s.time_range else 0
        def start_key(s: SplitIdAndFooter):
            return s.time_range[0] if s.time_range else 0
        if sort.order == "desc":
            return sorted(splits, key=end_key, reverse=True)
        return sorted(splits, key=start_key)

    # ------------------------------------------------------------------
    def fetch_docs(self, request: FetchDocsRequest) -> list[dict[str, Any]]:
        reader = self.context.reader(request.split)
        docs = reader.fetch_docs(request.doc_ids)
        if request.snippet_fields and request.query_ast is not None:
            from .snippets import generate_snippets
            for doc in docs:
                doc["_snippets"] = generate_snippets(
                    doc, request.snippet_fields, request.query_ast)
        return docs


class LocalSearchClient:
    """In-process transport to a SearchService (the tests' and single-node
    deployments' client; the HTTP client in serve/ has the same surface)."""

    def __init__(self, service: SearchService):
        self.service = service

    def leaf_search(self, request: LeafSearchRequest) -> LeafSearchResponse:
        return self.service.leaf_search(request)

    def fetch_docs(self, request: FetchDocsRequest) -> list[dict[str, Any]]:
        return self.service.fetch_docs(request)
