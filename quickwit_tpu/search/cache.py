"""Leaf-search result cache.

Role of the reference's `LeafSearchCache` (`leaf_cache.rs:26`): memoizes one
split's LeafSearchResponse keyed by (split id, canonicalized request). The
request's time range is clamped to the split's own time range before keying
(the reference's `remove_redundant_timestamp_range`, `leaf.rs:1048`), so
rolling time windows that fully cover an immutable split hit the same entry.

`canonical_filter_digest` is the sibling key for the mask/partial-agg tiers
(search/mask_cache.py, search/agg_cache.py): it hashes only the
result-FILTERING fields (query AST + rebased time bounds) so every query
variant sharing a filter — different top-K, sort, aggs, pagination — lands
on one entry per split.
"""

from __future__ import annotations

import hashlib
import json
import pickle
from typing import Any, Optional

from ..observability.metrics import (
    LEAF_CACHE_EVICTED_BYTES_TOTAL, LEAF_CACHE_HITS_TOTAL,
    LEAF_CACHE_MISSES_TOTAL,
)
from .models import LeafSearchResponse, SearchRequest
from .tenant_cache import TenantPartitionedCache


def _rebase_time_bounds(request: SearchRequest,
                        split_time_range: Optional[tuple[int, int]]
                        ) -> tuple[Optional[int], Optional[int]]:
    """The reference's `remove_redundant_timestamp_range`: a bound the
    split's own time range can't exceed hashes as absent, so differently-
    bounded requests share entries when the split can't tell them apart."""
    start, end = request.start_timestamp, request.end_timestamp
    if split_time_range is not None:
        lo, hi = split_time_range
        # end is exclusive; a bound outside the split's range is redundant
        if start is not None and start <= lo:
            start = None
        if end is not None and end > hi:
            end = None
    return start, end


def canonical_filter_digest(
    request: SearchRequest,
    split_time_range: Optional[tuple[int, int]] = None,
) -> str:
    """Digest of the request's result-FILTERING fields only: the query AST
    plus the split-rebased time bounds. Deliberately excludes top-K/offset,
    sort, aggs, and search_after — none of them change WHICH docs match, so
    a predicate mask or partial-agg state keyed by this digest is reusable
    across all those variants (the classic query-reuse win). Soundness
    leans on splits being immutable: a (split, digest) pair can never go
    stale."""
    start, end = _rebase_time_bounds(request, split_time_range)
    payload = {
        "query": request.query_ast.to_dict(),
        "start": start,
        "end": end,
    }
    return hashlib.blake2b(
        json.dumps(payload, sort_keys=True).encode(),
        digest_size=16).hexdigest()


def canonical_request_key(
    split_id: str,
    request: SearchRequest,
    split_time_range: Optional[tuple[int, int]] = None,
) -> str:
    """Split-local cache key: the request's result-affecting fields plus the
    time filter REBASED against the split's own time range (a bound the
    split can't exceed hashes as absent, so differently-bounded requests
    share entries when the split can't tell them apart).

    Threshold-pruning downgrade audit (search/pruning.downgrade_to_count):
    a count-only downgrade of a top-K request MUST NOT alias the full
    request's entry — and cannot, because the downgrade changes at least
    `max_hits + start_offset` (→ 0) and the normalized `sort` (→ _doc asc),
    both hashed below. Threshold-pushdown responses themselves are never
    cached (their hit lists are truncated); see _execute_per_split."""
    start, end = _rebase_time_bounds(request, split_time_range)
    payload = {
        "query": request.query_ast.to_dict(),
        "max_hits": request.max_hits + request.start_offset,
        "sort": [s.to_dict() for s in request.sort_fields],
        "aggs": request.aggs,
        "start": start,
        "end": end,
        "search_after": request.search_after,
    }
    digest = hashlib.blake2b(
        json.dumps(payload, sort_keys=True).encode(), digest_size=16).hexdigest()
    return f"{split_id}:{digest}"


class LeafSearchCache:
    """Tier: whole-response memoization, tenant-partitioned (Tier C —
    search/tenant_cache.py). Stored pickled, so every hit hands the
    collector a FRESH response object (the merge mutates agg states)."""

    def __init__(self, capacity_bytes: int = 64 << 20):
        self._cache = TenantPartitionedCache(
            capacity_bytes,
            on_evict=LEAF_CACHE_EVICTED_BYTES_TOTAL.inc,
            tier="leaf_response")

    def get(self, key: str) -> Optional[LeafSearchResponse]:
        raw = self._cache.get(key)
        if raw is None:
            LEAF_CACHE_MISSES_TOTAL.inc()
            return None
        LEAF_CACHE_HITS_TOTAL.inc()
        return pickle.loads(raw)

    def put(self, key: str, response: LeafSearchResponse) -> None:
        self._cache.put(key, pickle.dumps(response))

    @property
    def stats(self) -> dict[str, Any]:
        return self._cache.stats
