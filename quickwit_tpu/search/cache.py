"""Leaf-search result cache.

Role of the reference's `LeafSearchCache` (`leaf_cache.rs:26`): memoizes one
split's LeafSearchResponse keyed by (split id, canonicalized request). The
request's time range is clamped to the split's own time range before keying
(the reference's `remove_redundant_timestamp_range`, `leaf.rs:1048`), so
rolling time windows that fully cover an immutable split hit the same entry.
"""

from __future__ import annotations

import hashlib
import json
import pickle
from typing import Any, Optional

from ..storage.cache import MemorySizedCache
from .models import LeafSearchResponse, SearchRequest


def canonical_request_key(
    split_id: str,
    request: SearchRequest,
    split_time_range: Optional[tuple[int, int]] = None,
) -> str:
    """Split-local cache key: the request's result-affecting fields plus the
    time filter REBASED against the split's own time range (a bound the
    split can't exceed hashes as absent, so differently-bounded requests
    share entries when the split can't tell them apart).

    Threshold-pruning downgrade audit (search/pruning.downgrade_to_count):
    a count-only downgrade of a top-K request MUST NOT alias the full
    request's entry — and cannot, because the downgrade changes at least
    `max_hits + start_offset` (→ 0) and the normalized `sort` (→ _doc asc),
    both hashed below. Threshold-pushdown responses themselves are never
    cached (their hit lists are truncated); see _execute_per_split."""
    start, end = request.start_timestamp, request.end_timestamp
    if split_time_range is not None:
        lo, hi = split_time_range
        # end is exclusive; a bound outside the split's range is redundant
        if start is not None and start <= lo:
            start = None
        if end is not None and end > hi:
            end = None
    payload = {
        "query": request.query_ast.to_dict(),
        "max_hits": request.max_hits + request.start_offset,
        "sort": [s.to_dict() for s in request.sort_fields],
        "aggs": request.aggs,
        "start": start,
        "end": end,
        "search_after": request.search_after,
    }
    digest = hashlib.blake2b(
        json.dumps(payload, sort_keys=True).encode(), digest_size=16).hexdigest()
    return f"{split_id}:{digest}"


class LeafSearchCache:
    def __init__(self, capacity_bytes: int = 64 << 20):
        self._cache = MemorySizedCache(capacity_bytes)

    def get(self, key: str) -> Optional[LeafSearchResponse]:
        raw = self._cache.get(key)
        if raw is None:
            return None
        return pickle.loads(raw)

    def put(self, key: str, response: LeafSearchResponse) -> None:
        self._cache.put(key, pickle.dumps(response))

    @property
    def stats(self) -> dict[str, int]:
        return {"hits": self._cache.hits, "misses": self._cache.misses,
                "size_bytes": self._cache.size_bytes}
