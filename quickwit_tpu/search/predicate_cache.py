"""Predicate / negative cache: provably-empty split pruning.

Role of the reference's `CacheNode` + term-absence negative cache
(`quickwit-query/src/query_ast/cache_node.rs:33,40`,
`quickwit-search/src/leaf_cache.rs:197`, consultation at
`leaf.rs:758-841`): a split is provably empty for a query when any
**conjunctively required** term has previously been proven absent from
it. Absence is an immutable, query- and time-window-independent property
of an (immutable) split, so the consultation is sound regardless of the
rest of the query — extra required clauses can only make it emptier.

TPU-first twist: in this engine the payoff is even larger than in the
reference. A pruned split skips not just warmup IO but the whole
device pipeline — byte-range GETs, plan lowering, H2D transfer, and a
jitted kernel launch (plus, for a cold split, the footer open itself:
consultation happens *before* the reader is constructed).

Absences are recorded during plan lowering: every term-dictionary miss
is a proof, whether or not the term was required in that query.
"""

from __future__ import annotations

from collections import OrderedDict

from ..models.doc_mapper import DocMapper, FieldMapping, FieldType
from ..observability.metrics import (
    PREDICATE_CACHE_EVICTED_BYTES_TOTAL, PREDICATE_CACHE_HITS_TOTAL,
    PREDICATE_CACHE_MISSES_TOTAL,
)
from ..query import ast as Q
from ..query.tokenizers import get_tokenizer
from ..common import sync

# Accounted cost of one absence marker beyond its key strings: the
# OrderedDict slot, the key tuple, and three string headers. An estimate
# (CPython internals vary), but a stable one — the point is that the cache
# is bounded in BYTES like its sibling tiers, not in entries, so long
# field/term keys can't blow past an entry-count bound's implied size.
_ENTRY_OVERHEAD_BYTES = 160


class PredicateCache:
    """Byte-bounded LRU of (split_id, field, term) → proven-absent markers."""

    def __init__(self, max_bytes: int = 8 << 20):
        self._entries: OrderedDict[tuple[str, str, str], int] = OrderedDict()
        self.max_bytes = max_bytes
        self._size = 0
        self._lock = sync.lock("PredicateCache._lock")
        self.hits = 0
        self.misses = 0
        self.evicted_bytes = 0

    @staticmethod
    def _entry_bytes(key: tuple[str, str, str]) -> int:
        return _ENTRY_OVERHEAD_BYTES + sum(len(part) for part in key)

    def record_term_absent(self, split_id: str, field: str, term: str) -> None:
        key = (split_id, field, term)
        nbytes = self._entry_bytes(key)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._size -= old
            self._entries[key] = nbytes
            self._size += nbytes
            dropped = 0
            while self._size > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._size -= evicted
                dropped += evicted
            if dropped:
                self.evicted_bytes += dropped
        if dropped:
            PREDICATE_CACHE_EVICTED_BYTES_TOTAL.inc(dropped)

    def is_term_absent(self, split_id: str, field: str, term: str) -> bool:
        with self._lock:
            present = (split_id, field, term) in self._entries
            if present:
                self._entries.move_to_end((split_id, field, term))
            return present

    def known_empty(self, split_id: str,
                    required: list[tuple[str, str]]) -> bool:
        """True when any required term is proven absent. Hit/miss counters
        live here (not in `is_term_absent`) so one consultation counts
        once, however many required terms it scans."""
        empty = any(self.is_term_absent(split_id, field, term)
                    for field, term in required)
        with self._lock:
            if empty:
                self.hits += 1
            else:
                self.misses += 1
        if empty:
            PREDICATE_CACHE_HITS_TOTAL.inc()
        else:
            PREDICATE_CACHE_MISSES_TOTAL.inc()
        return empty

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._size

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "size_bytes": self._size,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evicted_bytes": self.evicted_bytes,
            }


def term_is_tokenized_text(fm: FieldMapping) -> bool:
    """True when a Term node on this field lowers as a conjunctive
    full-text match (quickwit query-language semantics). Shared by
    `Lowering._lower_term` and `required_terms` so their dispatch cannot
    drift — divergence would make pruning unsound."""
    return fm.type is FieldType.TEXT and fm.tokenizer not in ("raw",
                                                              "lowercase")


def canonical_query_term(fm: FieldMapping, value: str) -> str:
    """Query-side canonical index-term string — THE transformation plan
    lowering applies before every term-dictionary lookup
    (`Lowering._canonical` delegates here), so predicate-cache keys and
    lookup keys coincide by construction."""
    from ..utils.datetime_utils import parse_datetime_to_micros
    if fm.type is FieldType.TEXT:
        return value
    if fm.type is FieldType.DATETIME:
        return str(parse_datetime_to_micros(value, fm.input_formats)
                   if not str(value).lstrip("-").isdigit()
                   else parse_datetime_to_micros(int(value),
                                                 ("unix_timestamp",)))
    if fm.type is FieldType.F64:
        return repr(float(value))
    if fm.type is FieldType.BOOL:
        return value.lower()
    return str(int(value))


def required_terms(ast: Q.QueryAst,
                   doc_mapper: DocMapper) -> list[tuple[str, str]]:
    """Conjunctively-required (field, canonical_term) pairs of a query:
    terms that every matching document must contain. Mirrors the
    lowering's tokenization/canonicalization so the pairs match
    term-dictionary lookup keys exactly. Unknown node types contribute
    nothing (sound: fewer proofs, never wrong ones)."""
    out: list[tuple[str, str]] = []
    _collect_required(ast, doc_mapper, out)
    return out


def _collect_required(ast: Q.QueryAst, doc_mapper: DocMapper,
                      out: list[tuple[str, str]]) -> None:
    if isinstance(ast, Q.Boost):
        _collect_required(ast.underlying, doc_mapper, out)
        return
    if isinstance(ast, Q.Bool):
        # must/filter are conjunctive; should/must_not prove nothing.
        # Exception: pure-should bools (no must/filter) where EVERY should
        # clause shares the conjunction would need minimum_should_match
        # analysis — skipped (sound).
        for clause in (*ast.must, *ast.filter):
            _collect_required(clause, doc_mapper, out)
        return
    if isinstance(ast, Q.Term):
        fm = doc_mapper.field(ast.field)
        if fm is None or not fm.indexed:
            return
        if not ast.verbatim and term_is_tokenized_text(fm):
            # lowered as a conjunctive full-text match
            _collect_required(Q.FullText(ast.field, ast.value, "and"),
                              doc_mapper, out)
            return
        value = ast.value
        if (not ast.verbatim and fm.type is FieldType.TEXT
                and fm.tokenizer == "lowercase"):
            value = value.lower()
        try:
            out.append((ast.field, canonical_query_term(fm, value)))
        except (ValueError, TypeError):
            pass  # unparsable term value: lowering will surface the error
        return
    if isinstance(ast, Q.FullText):
        fm = doc_mapper.field(ast.field)
        if fm is None:
            return
        if fm.type is not FieldType.TEXT:
            try:
                out.append((ast.field, canonical_query_term(fm, ast.text)))
            except (ValueError, TypeError):
                pass
            return
        tokens = get_tokenizer(fm.tokenizer)(ast.text)
        if ast.mode in ("and", "phrase"):
            out.extend((ast.field, t.text) for t in tokens)
        elif len(tokens) == 1:  # single-token OR is still required
            out.append((ast.field, tokens[0].text))
        return
    # Range / Wildcard / Regex / TermSet / FieldPresence / PhrasePrefix /
    # MatchAll: no single required term — contribute nothing.
