"""Tenant-partitioned byte quotas for the hierarchical leaf caches.

Tier C of the layered leaf cache (docs/hierarchical-cache.md): the
leaf-response, predicate-mask, and partial-aggregation caches all store
through this facade, which segments entries by the ambient `TenantContext`
(`tenancy/context.py`) so one tenant's dashboard storm can only evict its
OWN working set.

Each tenant gets its own `MemorySizedCache` partition whose quota is the
facade capacity split by the tenants' DRR weights — the same
`PRIORITY_CLASSES` weights the HBM admission scheduler uses, so cache
share and admission share follow one fairness policy:

    quota(t) = capacity * weight(t) / sum(weight(u) for known u)

The known-tenant set grows lazily from traffic; every new partition
re-quotas the existing ones (LRU entries over the shrunk quota are
evicted). With tenancy disabled nothing is ever bound, `effective_tenant`
returns the single implicit DEFAULT_TENANT, and the one partition's quota
is the full capacity — bit-identical behavior to an unpartitioned
`MemorySizedCache`, not a separate code path.
"""

from __future__ import annotations

from typing import Optional

from ..common import sync
from ..observability import flight
from ..storage.cache import MemorySizedCache
from ..tenancy.context import effective_tenant


class TenantPartitionedCache:
    """Byte-bounded LRU keyed (ambient tenant, key) with per-tenant quotas.

    `tier` names this cache in flight-recorder events (`cache.hit` /
    `cache.fill` / `cache.evict` carry it as the `tier` attribute) — the
    single instrumentation point for every tier that stores through the
    facade (leaf response, predicate mask, partial agg)."""

    def __init__(self, capacity_bytes: int, on_evict=None, tier: str = ""):
        self.capacity_bytes = capacity_bytes
        self.tier = tier
        self._parts: dict[str, MemorySizedCache] = {}
        self._weights: dict[str, float] = {}
        self._lock = sync.lock("TenantPartitionedCache._lock")
        sync.register_shared(self, "TenantPartitionedCache")
        self._on_evict = self._wrap_evict(on_evict) if tier else on_evict

    def _wrap_evict(self, inner):
        def _evict(nbytes: int) -> None:
            if flight.recording():
                flight.emit("cache.evict",
                            attrs={"tier": self.tier, "bytes": nbytes})
            if inner is not None:
                inner(nbytes)
        return _evict

    def _partition(self) -> MemorySizedCache:
        tenant = effective_tenant()
        with self._lock:
            sync.note_write(self, "parts")
            part = self._parts.get(tenant.tenant_id)
            if part is None:
                part = MemorySizedCache(self.capacity_bytes,
                                        on_evict=self._on_evict)
                self._parts[tenant.tenant_id] = part
                # qwlint: disable-next-line=QW001 - DRR weight is a host
                # python number off the ambient TenantContext, never device
                self._weights[tenant.tenant_id] = float(tenant.weight)
                self._requota_locked()
            return part

    def _requota_locked(self) -> None:
        total = sum(self._weights.values()) or 1.0
        for tenant_id, part in self._parts.items():
            # qwlint: disable-next-line=QW001 - quota math on host python
            # floats (capacity × weight share), no device values involved
            part.resize(int(self.capacity_bytes
                            * self._weights[tenant_id] / total))

    def get(self, key: str) -> Optional[bytes]:
        data = self._partition().get(key)
        if self.tier and data is not None and flight.recording():
            flight.emit("cache.hit",
                        attrs={"tier": self.tier, "bytes": len(data)})
        return data

    def put(self, key: str, data: bytes) -> None:
        if self.tier and flight.recording():
            flight.emit("cache.fill",
                        attrs={"tier": self.tier, "bytes": len(data)})
        self._partition().put(key, data)

    def delete(self, key: str) -> None:
        self._partition().delete(key)

    def clear_current_partition(self) -> int:
        """Forced eviction of the calling tenant's partition (the
        `cache.evict` chaos point degrades THIS tenant, never another's)."""
        return self._partition().clear()

    @property
    def stats(self) -> dict:
        with self._lock:
            sync.note_read(self, "parts")
            parts = dict(self._parts)
        # per-partition counters read under EACH partition's own lock
        # (stats_snapshot): the bare attribute reads this replaced raced
        # the hit/miss increments on the partitions (found by qwrace)
        snaps = {tenant_id: p.stats_snapshot()
                 for tenant_id, p in parts.items()}
        return {
            "hits": sum(s["hits"] for s in snaps.values()),
            "misses": sum(s["misses"] for s in snaps.values()),
            "size_bytes": sum(s["size_bytes"] for s in snaps.values()),
            "evicted_bytes": sum(s["evicted_bytes"] for s in snaps.values()),
            "partitions": {
                tenant_id: {"quota_bytes": s["capacity_bytes"],
                            "size_bytes": s["size_bytes"]}
                for tenant_id, s in snaps.items()},
        }
