"""Dynamic top-K split pruning: sort-value / BM25-score upper bounds.

Role of the reference's `CanSplitDoBetter` (`leaf.rs:1279`): once the
collector holds K hits, a pending split whose best achievable sort key
cannot beat the current Kth value is either skipped outright
(`count_hits_exact=False`) or downgraded to a count-only request that rides
the far cheaper no-sort/no-top-k path. The seed only used split bounds to
ORDER execution (`service._optimize_split_order`); this module supplies the
actual payoff.

Everything here works in the INTERNAL sort-key encoding (`PartialHit
.sort_value`: float64, higher-is-better — desc keeps the raw value, asc
negates it), so one comparison rule covers both orders:

    prune split  iff  best_internal_key(split) < threshold

Strictly less — a split that can only TIE the threshold may still win the
(sort_value2, split_id, doc_id) tie-break at the collector and must run.

Soundness per sort kind:
  timestamp   — split metadata `time_range` bounds every doc (the timestamp
                field is required), so the bound is exact metadata.
  fast field  — the split footer's per-field min/max bounds every doc WITH a
                value; docs missing the value key at MISSING_VALUE_SENTINEL,
                below any finite bound, so the bound covers them too.
  _score desc — per-(field,term) max term frequency recorded at split open:
                BM25's tf/(tf + K1*(1-B+B*norm/avg)) is increasing in tf and
                decreasing in norm, so norm→0, tf→max_tf upper-bounds every
                doc; the query bound sums the per-term bounds over every
                scoring (must+should) term. Impact-ordered splits (format
                v3) replace the formula with the exact dequantized first
                block maximum, which also reflects the real fieldnorms.
                Queries with score contributions
                we cannot bound (phrase, prefix, wildcard, regex) disable
                pruning entirely (return None) — sound, never wrong.
  _score asc / _doc / text sorts — never pruned.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Callable, Optional

from ..common import sync

from ..models.doc_mapper import DocMapper, FieldType
from ..ops.bm25 import B, K1, idf
from ..ops.topk import MISSING_VALUE_SENTINEL
from ..query import ast as Q
from ..query.tokenizers import get_tokenizer
from .models import (LeafSearchResponse, SearchRequest, SortField,
                     string_sort_of)
from .predicate_cache import canonical_query_term, term_is_tokenized_text


class ThresholdBox:
    """Monotone (non-decreasing) threshold shared between the merge loop and
    the prefetch worker.

    The collector itself is not thread-safe (`partial_hits` sorts in place),
    so the main thread PUBLISHES the Kth value here after each merge and the
    prefetch thread only READS. Monotonicity makes stale reads sound: the
    true threshold only ever rises, so a reader acting on an old value
    prunes less, never more.
    """

    def __init__(self, seed: Optional[float] = None):
        self._value = seed
        self._lock = sync.lock("ThresholdBox._lock")
        sync.register_shared(self, "ThresholdBox")
        # qwrace planted race (mandatory self-test): with
        # QW_RACE_BREAK_THRESHOLD set, update() does its read-modify-write
        # WITHOUT the box lock — the exact bug the monotone-publish
        # contract above exists to prevent
        self._break_unlocked = os.environ.get(
            "QW_RACE_BREAK_THRESHOLD", "").strip().lower() in (
                "1", "true", "yes")

    def get(self) -> Optional[float]:
        with self._lock:
            sync.note_read(self, "value")
            return self._value

    def update(self, value: Optional[float]) -> None:
        if value is None:
            return
        if self._break_unlocked:
            sync.note_write(self, "value")
            if self._value is None or value > self._value:
                self._value = value
            return
        with self._lock:
            sync.note_write(self, "value")
            if self._value is None or value > self._value:
                self._value = value


class ScoreBoundCache:
    """LRU of (split_id, field, term) → (df, max_tf[, score_cap]) recorded
    at split open.

    Like the predicate cache's absence proofs, the stats are immutable
    properties of an (immutable) split, so entries never invalidate; the
    backing `terms.max_tf` footer array persists them across reader
    evictions and process restarts. `score_cap` (format v3 impact-ordered
    splits) is the EXACT dequantized first-block maximum — sharper than the
    max_tf/norm→0 formula because it reflects the real fieldnorms — or None
    on v2 splits.
    """

    def __init__(self, max_entries: int = 1 << 17):
        self._entries: OrderedDict[tuple[str, str, str],
                                   tuple] = OrderedDict()
        self._max_entries = max_entries
        self._lock = sync.lock("ScoreBoundCache._lock")

    def record(self, split_id: str, field: str, term: str,
               df: int, max_tf: int,
               score_cap: Optional[float] = None) -> None:
        key = (split_id, field, term)
        with self._lock:
            self._entries[key] = (df, max_tf, score_cap)
            self._entries.move_to_end(key)
            while len(self._entries) > self._max_entries:
                self._entries.popitem(last=False)

    def get(self, split_id: str, field: str,
            term: str) -> Optional[tuple]:
        key = (split_id, field, term)
        with self._lock:
            stats = self._entries.get(key)
            if stats is not None:
                self._entries.move_to_end(key)
            return stats

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# --------------------------------------------------------------------------
# scoring-term extraction (mirror of Lowering.lower's scoring dispatch)

class _Unboundable(Exception):
    """Query has a score contribution we cannot upper-bound."""


def scoring_terms(ast: Q.QueryAst,
                  doc_mapper: DocMapper) -> Optional[list[tuple[str, str,
                                                                float]]]:
    """(field, canonical_term, boost) triples of every node that can
    contribute to a document's BM25 score, mirroring the tokenization and
    canonicalization of `Lowering.lower` so the terms match term-dictionary
    lookup keys exactly. Returns None when any scoring contribution is
    unboundable (phrase, prefix, wildcard, regex, unknown nodes) — callers
    must then disable score pruning for the query. must_not/filter clauses
    never score and contribute nothing regardless of content."""
    out: list[tuple[str, str, float]] = []
    try:
        _collect_scoring(ast, doc_mapper, out, 1.0)
    except _Unboundable:
        return None
    return out


def _collect_scoring(ast: Q.QueryAst, doc_mapper: DocMapper,
                     out: list[tuple[str, str, float]], boost: float) -> None:
    if isinstance(ast, (Q.MatchAll, Q.MatchNone, Q.Range, Q.FieldPresence)):
        return  # never contribute score
    if isinstance(ast, Q.Boost):
        _collect_scoring(ast.underlying, doc_mapper, out, boost * ast.boost)
        return
    if isinstance(ast, Q.Bool):
        # must/should children score; filter/must_not lower with
        # scoring=False (plan.py Lowering.lower) and contribute nothing
        for clause in (*ast.must, *ast.should):
            _collect_scoring(clause, doc_mapper, out, boost)
        return
    if isinstance(ast, Q.TermSet):
        return  # TermSet postings lower with scoring=False
    if isinstance(ast, Q.Term):
        fm = doc_mapper.field(ast.field)
        if fm is None:
            raise _Unboundable
        if not ast.verbatim and term_is_tokenized_text(fm):
            _collect_scoring(Q.FullText(ast.field, ast.value, "and"),
                             doc_mapper, out, boost)
            return
        if not fm.indexed:
            return  # fast-only ordinal equality: non-scoring
        value = ast.value
        if (not ast.verbatim and fm.type is FieldType.TEXT
                and fm.tokenizer == "lowercase"):
            value = value.lower()
        try:
            out.append((ast.field, canonical_query_term(fm, value), boost))
        except (ValueError, TypeError):
            raise _Unboundable from None
        return
    if isinstance(ast, Q.FullText):
        fm = doc_mapper.field(ast.field)
        if fm is None:
            raise _Unboundable
        if fm.type is not FieldType.TEXT:
            try:
                out.append((ast.field, canonical_query_term(fm, ast.text),
                            boost))
            except (ValueError, TypeError):
                raise _Unboundable from None
            return
        if not fm.indexed:
            return  # fast-only equality: non-scoring
        if ast.mode not in ("and", "or"):
            # phrase / bool_prefix: positional or prefix scoring — the
            # precomputed node's tf distribution is not in the term stats
            raise _Unboundable
        tokens = get_tokenizer(fm.tokenizer)(ast.text)
        out.extend((ast.field, t.text, boost) for t in tokens)
        return
    # PhrasePrefix / Wildcard / Regex / unknown: scoring we cannot bound
    raise _Unboundable


def term_score_bound(num_docs: int, df: int, max_tf: int,
                     boost: float = 1.0) -> float:
    """Upper bound on one term's BM25 contribution to any doc in a split:
    tf at the split max, fieldnorm at its minimum (0)."""
    if df <= 0 or max_tf <= 0:
        return 0.0  # term absent from the split: matches nothing
    return (boost * idf(num_docs, df) * (K1 + 1.0) * max_tf
            / (max_tf + K1 * (1.0 - B)))


def split_score_upper_bound(
        terms: list[tuple[str, str, float]], num_docs: int,
        stats: Callable[[str, str], Optional[tuple[int, int]]],
) -> Optional[float]:
    """Σ per-term bounds over the query's scoring terms. `stats` maps
    (field, term) → (df, max_tf[, score_cap]) or None when unknown; any
    unknown term makes the split unboundable (None → run it). When the
    3rd element (exact impact block-max cap, format v3) is present it is
    used directly — boost scales linearly through the whole BM25 formula,
    so `boost * cap` stays an upper bound."""
    total = 0.0
    for field, term, boost in terms:
        st = stats(field, term)
        if st is None:
            return None
        if len(st) > 2 and st[2] is not None:
            total += boost * st[2]
        else:
            total += term_score_bound(num_docs, st[0], st[1], boost)
    return total


def record_split_term_stats(cache: ScoreBoundCache, split_id: str, reader,
                            terms: list[tuple[str, str, float]]) -> None:
    """At split open: look up df/max-tf for the query's scoring terms and
    remember them so FUTURE queries can bound this split before opening it
    (the reference persists absence proofs the same way)."""
    for field, term, _boost in terms:
        if cache.get(split_id, field, term) is not None:
            continue
        df, max_tf = reader.term_stats(field, term)
        cache.record(split_id, field, term, df, max_tf,
                     reader.term_score_cap(field, term))


# --------------------------------------------------------------------------
# per-request pruning context + per-split bounds

class PruningContext:
    """Resolved per-request pruning mode, or inert when the sort kind is
    not prunable. `mode` is one of "timestamp" | "fast_field" | "score" |
    None."""

    __slots__ = ("mode", "sort", "terms", "timestamp_field")

    def __init__(self, mode: Optional[str], sort: Optional[SortField],
                 terms: Optional[list] = None,
                 timestamp_field: Optional[str] = None):
        self.mode = mode
        self.sort = sort
        self.terms = terms          # scoring terms (score mode)
        self.timestamp_field = timestamp_field


def pruning_context(request: SearchRequest,
                    doc_mapper: DocMapper) -> PruningContext:
    """Decide whether (and how) this request's pending splits can be pruned
    by a collected-Kth-value threshold."""
    inert = PruningContext(None, None)
    if request.max_hits <= 0 or request.aggs:
        # count/agg-only requests must visit every split in full
        return inert
    if not request.sort_fields:
        return inert
    if string_sort_of(request, doc_mapper) is not None:
        return inert  # split-local ordinals: no cross-split bound
    sort = request.sort_fields[0]
    if sort.field == "_doc":
        return inert
    if sort.field == "_score":
        if sort.order != "desc":
            return inert  # asc: best internal key is trivially 0, useless
        terms = scoring_terms(request.query_ast, doc_mapper)
        if terms is None:
            return inert
        return PruningContext("score", sort, terms=terms)
    fm = doc_mapper.field(sort.field)
    if fm is None or not fm.fast:
        return inert
    if doc_mapper.timestamp_field == sort.field:
        return PruningContext("timestamp", sort,
                              timestamp_field=sort.field)
    if fm.type in (FieldType.I64, FieldType.U64, FieldType.F64,
                   FieldType.DATETIME, FieldType.BOOL):
        return PruningContext("fast_field", sort)
    return inert


def _internal_bound(lo, hi, descending: bool) -> Optional[float]:
    """Best achievable internal key for a value range [lo, hi]."""
    if descending:
        return None if hi is None else float(hi)
    return None if lo is None else -float(lo)


def split_best_internal_key(ctx: PruningContext, split,
                            field_meta_fn=None,
                            score_stats_fn=None) -> Optional[float]:
    """Upper bound on the internal sort key any doc of `split` can reach,
    or None when unknown (split must run).

    `field_meta_fn()` lazily supplies the split footer's FieldMeta for
    fast-field mode (None when the reader is cold and opening it would cost
    more than the kernel it might save); `score_stats_fn(field, term)`
    supplies (df, max_tf) for score mode.
    """
    if ctx.mode == "timestamp":
        tr = split.time_range
        if tr is None:
            return None
        return _internal_bound(tr[0], tr[1], ctx.sort.order == "desc")
    if ctx.mode == "fast_field":
        meta = field_meta_fn() if field_meta_fn is not None else None
        if not meta:
            return None
        bound = _internal_bound(meta.get("min_value"), meta.get("max_value"),
                                ctx.sort.order == "desc")
        if bound is None:
            return None
        # docs missing the value key at the sentinel — below any finite
        # bound, so max() only matters when every doc lacks the field
        return max(bound, MISSING_VALUE_SENTINEL)
    if ctx.mode == "score":
        if score_stats_fn is None:
            return None
        return split_score_upper_bound(ctx.terms, max(split.num_docs, 1),
                                       score_stats_fn)
    return None


# --------------------------------------------------------------------------
# request downgrade + wire seeding

def downgrade_to_count(request: SearchRequest) -> SearchRequest:
    """Count-only form of `request` for a threshold-pruned split when exact
    counts are required: max_hits=0 normalizes the sort to doc order
    (SearchRequest.__post_init__), riding count-from-metadata for match-all
    and the k==0 no-sort/no-top-k kernel otherwise. The time filter MUST be
    carried — counts respect it."""
    return SearchRequest(
        index_ids=request.index_ids,
        query_ast=request.query_ast,
        max_hits=0,
        start_offset=0,
        aggs=None,
        start_timestamp=request.start_timestamp,
        end_timestamp=request.end_timestamp,
        count_hits_exact=True,
        search_after=None,
        snippet_fields=(),
    )


def threshold_from_response(request: SearchRequest, doc_mapper: DocMapper,
                            response: LeafSearchResponse) -> Optional[float]:
    """Seed threshold (internal encoding) from an earlier partial response:
    the Kth sort value once the top window is full. Used by the root's
    retry path so round 2 starts pruning where round 1 left off."""
    needed = request.start_offset + request.max_hits
    if request.max_hits <= 0:
        return None
    if string_sort_of(request, doc_mapper) is not None:
        return None
    if request.sort_fields and request.sort_fields[0].field == "_doc":
        return None
    hits = response.partial_hits
    if len(hits) < needed:
        return None
    return hits[needed - 1].sort_value
