"""Jitted plan execution — the TPU leaf-search hot loop.

Role of the reference's `searcher.search(&query, &collector)` box
(`leaf.rs:853-875`: posting decode → boolean combine → BM25 → top-K +
aggregations on a rayon pool): here the whole box is **one XLA program**
assembled from the LoweredPlan:

    masks = scatter(postings)         # ops/masks.py
    scores = scatter-add(bm25(tfs))   # ops/bm25.py
    bool combine = elementwise VPU ops
    top-k = lax.top_k over dense keys # ops/topk.py
    aggs = scatter-add bucket states  # ops/aggs.py

Compiled executables are cached by plan *structure* signature — the arrays,
idf/bound scalars, and doc counts are traced inputs, so two different term
queries with the same shape reuse one compilation.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..common.clock import monotonic as _clock_monotonic
from ..index.format import ZONEMAP_BLOCK
from ..observability.profile import (
    PHASE_COMPILE, PHASE_EXECUTE, current_profile,
)
from ..ops import aggs as agg_ops
from ..ops import masks as mask_ops
from ..ops import topk as topk_ops
from ..observability import flight
from ..observability.metrics import SEARCH_KERNEL_LAUNCHES_TOTAL
from ..ops.bm25 import dequantize_block_bounds, score_postings
from .plan import (
    PRESENT_FROM_VALUES, BucketAggExec, CompositeAggExec, LoweredPlan,
    MetricAggExec, PBool, PMaskRef, PMatchAll, PMatchNone, PNormPresence,
    PPostings, PPresence, PRange, SortExec,
)

_JIT_CACHE: dict[tuple, Callable] = {}

# device-resident scalar tuples keyed by (plan signature, values): repeated
# queries skip the host->device scalar upload entirely — under a remote
# tunnel every upload RTT would otherwise double the steady-state latency.
# LRU (move-to-end on hit), matching the other caches: a hot scalar tuple
# re-used every query must not be evicted just because it was inserted first.
_SCALAR_CACHE: "OrderedDict[tuple, Any]" = OrderedDict()
_SCALAR_CACHE_CAP = 512


# qwlint: disable-next-line=QW001 - .item() on host numpy scalars builds
# the value-keyed upload-cache key; no device arrays are touched here
def _device_scalars(plan: LoweredPlan) -> tuple[Any, Any]:
    """(device_scalars, device_num_docs), one batched transfer on miss."""
    # value+dtype keyed: two plans with identical scalar tuples can share
    # the same device buffers — the content is the content
    key = (plan.num_docs,
           tuple((s.dtype.str, s.item()) for s in map(np.asarray, plan.scalars)))
    cached = _SCALAR_CACHE.get(key)
    if cached is None:
        moved = jax.device_put(list(plan.scalars) + [np.int32(plan.num_docs)])
        cached = (tuple(moved[:-1]), moved[-1])
        if len(_SCALAR_CACHE) >= _SCALAR_CACHE_CAP:
            _SCALAR_CACHE.popitem(last=False)
        _SCALAR_CACHE[key] = cached
    else:
        _SCALAR_CACHE.move_to_end(key)
    return cached


def _cardinality_hashes(met, arrays):
    """(hashes[uint64], present[bool]) per doc for a cardinality metric:
    text columns gather per-ordinal TERM hashes (cross-split identity),
    numeric columns mix the 64-bit value pattern. THE one derivation —
    the bucket, range, and top-level metric paths all call it."""
    if met.hash_slot >= 0:
        ordinals = arrays[met.values_slot]
        present = ordinals >= 0
        hashes = arrays[met.hash_slot][jnp.clip(ordinals, 0, None)]
    else:
        values = arrays[met.values_slot]
        present = arrays[met.present_slot].astype(jnp.bool_)
        bits = (agg_ops.jax_bitcast_f64(values)
                if values.dtype == jnp.float64
                else values.astype(jnp.int64).astype(jnp.uint64))
        hashes = agg_ops._hll_mix64(bits)
    return hashes, present


def _bucket_tree_blocks_posting_space(children) -> bool:
    """True when a nested-bucket subtree needs arrays the _GatherView
    cannot serve (range bounds, multivalued pair arrays, per-ordinal
    hash tables) — shared by the plain and composite eligibility
    checks."""
    stack = list(children)
    while stack:
        child = stack.pop()
        if (child.kind in ("range", "terms_mv")
                or any(m.kind == "cardinality" for m in child.metrics)):
            return True
        stack.extend(child.subs)
    return False


def _bucket_idx(a: BucketAggExec, arrays, scalars, mask):
    """(idx, in_bucket_mask): per-doc bucket index with the out-of-range
    sentinel `num_buckets` for dropped docs."""
    values = arrays[a.values_slot]
    nb = a.num_buckets
    if a.kind == "terms":
        ordinals = values
        m = mask & (ordinals >= 0)
        idx = jnp.where(m, ordinals, jnp.int32(nb))
        return idx, m
    if a.kind == "terms_mv":
        # multivalued: values are (doc, ordinal) PAIR arrays — gather the
        # doc-level mask at each pair's doc id; padding pairs carry
        # ordinal -1 (dropped here) with doc 0 (in-bounds gather)
        pair_docs = arrays[a.present_slot]
        m = mask[pair_docs] & (values >= 0)
        idx = jnp.where(m, values, jnp.int32(nb))
        return idx, m
    present = arrays[a.present_slot].astype(jnp.bool_)
    m = mask & present
    origin = scalars[a.origin_slot]
    interval = scalars[a.interval_slot]
    if a.kind == "date_histogram":
        raw = (values - origin) // interval          # exact integer math
    else:
        raw = jnp.floor((values.astype(jnp.float64) - origin) / interval)
    idx = raw.astype(jnp.int32)
    m = m & (idx >= 0) & (idx < nb)
    return jnp.where(m, idx, jnp.int32(nb)), m


def _bucket_metrics(metric_slots, arrays, idx, m, nb):
    metrics: dict[str, Any] = {}
    for met in metric_slots:
        if met.kind == "cardinality":
            # per-bucket HLL registers (scatter-max)
            hashes, present = _cardinality_hashes(met, arrays)
            ok = m & present
            metrics[met.name] = {"hll": agg_ops.bucket_hll_registers(
                jnp.where(ok, idx, jnp.int32(nb)), hashes, ok, nb)}
            continue
        mv = arrays[met.values_slot].astype(jnp.float64)
        mp = arrays[met.present_slot].astype(jnp.bool_)
        # docs with mm==False get the sentinel index; both bucket-kernel
        # paths neutralize them, so mv needs no extra masking passes
        mm = m & mp
        midx = jnp.where(mm, idx, jnp.int32(nb))
        state: dict[str, Any] = {}
        need = met.kind
        if need == "percentiles":
            state["sketch"] = agg_ops.bucket_percentile_sketch(midx, mv, nb)
            metrics[met.name] = state
            continue
        if need in ("sum", "avg", "stats", "extended_stats"):
            state["sum"] = agg_ops.bucket_sum(midx, mv, nb)
        if need in ("avg", "stats", "extended_stats", "value_count"):
            state["count"] = agg_ops.bucket_counts(midx, nb).astype(jnp.int64)
        if need in ("min", "stats", "extended_stats"):
            state["min"] = agg_ops.bucket_min(midx, mv, nb)
        if need in ("max", "stats", "extended_stats"):
            state["max"] = agg_ops.bucket_max(midx, mv, nb)
        if need in ("stats", "extended_stats"):
            state["sum_sq"] = agg_ops.bucket_sum(midx, mv * mv, nb)
        metrics[met.name] = state
    return metrics


def _eval_range_agg(a: BucketAggExec, arrays, mask):
    """Range buckets may OVERLAP (ES counts a doc in every range it falls
    in), so each range gets its own mask instead of one bucket index."""
    nb = a.num_buckets
    values = arrays[a.values_slot].astype(jnp.float64)
    present = arrays[a.present_slot].astype(jnp.bool_)
    froms = arrays[a.froms_slot]
    tos = arrays[a.tos_slot]
    in_range = ((mask & present)[:, None]
                & (values[:, None] >= froms[None, :])
                & (values[:, None] < tos[None, :]))          # [D, nb]
    counts = jnp.sum(in_range, axis=0, dtype=jnp.int32)
    metrics: dict[str, Any] = {}
    for met in a.metrics:
        if met.kind == "cardinality":
            # overlapping ranges: per-range HLL registers (small nb
            # loop, like the percentile sketches below). c_present, not
            # `present`: the enclosing scope's present masks the RANGE
            # field and must not be shadowed
            hashes, c_present = _cardinality_hashes(met, arrays)
            metrics[met.name] = {"hll": jnp.stack([
                agg_ops.hll_registers(hashes, in_range[:, i] & c_present)
                for i in range(nb)])}
            continue
        mv = arrays[met.values_slot].astype(jnp.float64)
        mp = arrays[met.present_slot].astype(jnp.bool_)
        mm = in_range & mp[:, None]                          # [D, nb]
        state: dict[str, Any] = {}
        need = met.kind
        mvb = mv[:, None]
        if need == "percentiles":
            state["sketch"] = jnp.stack([
                agg_ops.percentile_sketch(mv, mp, in_range[:, i] & mask)
                for i in range(nb)])
            metrics[met.name] = state
            continue
        if need in ("sum", "avg", "stats", "extended_stats"):
            state["sum"] = jnp.sum(jnp.where(mm, mvb, 0.0), axis=0)
        if need in ("avg", "stats", "extended_stats", "value_count"):
            state["count"] = jnp.sum(mm, axis=0, dtype=jnp.int64)
        if need in ("min", "stats", "extended_stats"):
            state["min"] = jnp.min(jnp.where(mm, mvb, jnp.inf), axis=0)
        if need in ("max", "stats", "extended_stats"):
            state["max"] = jnp.max(jnp.where(mm, mvb, -jnp.inf), axis=0)
        if need in ("stats", "extended_stats"):
            state["sum_sq"] = jnp.sum(jnp.where(mm, mvb * mvb, 0.0), axis=0)
        metrics[met.name] = state
    return {"counts": counts, "metrics": metrics}


def _eval_bucket_agg(a: BucketAggExec, arrays, scalars, mask):
    if a.kind == "range":
        return _eval_range_agg(a, arrays, mask)
    idx, m = _bucket_idx(a, arrays, scalars, mask)
    return _eval_bucket_level(a, arrays, scalars, mask, idx, m,
                              a.num_buckets)


def _eval_bucket_level(a: BucketAggExec, arrays, scalars, mask, idx, m,
                       space: int):
    """One level of a nested bucket tree. `idx`/`m` are the FLATTENED
    bucket index (mixed-radix over all ancestors) and its validity mask;
    `space` is the flattened bucket count. Children extend the radix:
    child_flat = parent_flat * child_nb + child_local."""
    out: dict[str, Any] = {
        "counts": agg_ops.bucket_counts(jnp.where(m, idx, jnp.int32(space)),
                                        space),
        "metrics": _bucket_metrics(a.metrics, arrays, idx, m, space),
    }
    subs = []
    for child in a.subs:
        nb2 = child.num_buckets
        idx2, m2 = _bucket_idx(child, arrays, scalars, mask)
        both = m & m2
        combined = jnp.where(both, idx * nb2 + idx2, jnp.int32(space * nb2))
        subs.append(_eval_bucket_level(child, arrays, scalars, mask,
                                       combined, both, space * nb2))
    if subs:
        out["subs"] = subs
    return out



def _keyed_for(by, descending, values_slot, present_slot, view, mask,
               scores, doc_key):
    """Higher-is-better f64 key for one sort part (missing column values get
    the finite bottom sentinel, non-matching docs -inf). `view` is either the
    arrays tuple (dense path) or a _GatherView (posting space); `doc_key` is
    the per-element doc id source for "doc" sorts."""
    if by == "score":
        key = scores.astype(jnp.float64)
        if not descending:
            key = -key
        return jnp.where(mask, key, -jnp.inf)
    if by == "column":
        key = view[values_slot].astype(jnp.float64)
        if not descending:
            key = -key
        if present_slot == PRESENT_FROM_VALUES:
            present = view[values_slot] >= 0  # ordinal columns: -1 = missing
        else:
            present = view[present_slot].astype(jnp.bool_)
        has_value = mask & present
        return jnp.where(
            has_value, key,
            jnp.where(mask, jnp.float64(topk_ops.MISSING_VALUE_SENTINEL),
                      -jnp.inf))
    # "doc"
    key = doc_key.astype(jnp.float64)
    return jnp.where(mask, key if descending else -key, -jnp.inf)


def _global_doc_ids(plan, scalars, padded):
    """Per-lane GLOBAL doc ids: the plain iota for whole-split plans; the
    chunk's traced doc offset shifts it for chunked dense sub-plans
    (search/chunkexec.py) so doc-keyed comparisons match the fused path."""
    docs = jnp.arange(padded, dtype=jnp.int32)
    if plan.doc_base_slot >= 0:
        docs = docs + scalars[plan.doc_base_slot].astype(jnp.int32)
    return docs


def _apply_search_after(plan, keyed, keyed2, scalars, padded):
    """Restrict top-k eligibility per the search_after marker (counts/aggs
    keep full-query semantics). With a secondary key the comparison is
    lexicographic."""
    relation = plan.search_after_relation
    marker = scalars[plan.sa_value_slot]
    if keyed2 is None:
        if relation == "lt":
            eligible = keyed < marker
        elif relation == "le":
            eligible = keyed <= marker
        else:  # "lt_tie"
            marker_doc = scalars[plan.sa_doc_slot]
            docs = _global_doc_ids(plan, scalars, padded)
            eligible = (keyed < marker) | ((keyed == marker) &
                                           (docs > marker_doc))
        return jnp.where(eligible, keyed, -jnp.inf), None
    marker2 = scalars[plan.sa_value2_slot]
    lt = (keyed < marker) | ((keyed == marker) & (keyed2 < marker2))
    tie = (keyed == marker) & (keyed2 == marker2)
    if relation == "lt":
        eligible = lt
    elif relation == "le":
        eligible = lt | tie
    else:  # "lt_tie"
        marker_doc = scalars[plan.sa_doc_slot]
        docs = _global_doc_ids(plan, scalars, padded)
        eligible = lt | (tie & (docs > marker_doc))
    return (jnp.where(eligible, keyed, -jnp.inf),
            jnp.where(eligible, keyed2, -jnp.inf))


def _posting_space_eligible(plan: LoweredPlan) -> bool:
    """Single-term queries (no boolean structure, no NOT semantics) can
    execute entirely over the [P] posting arrays instead of [N] dense docs —
    the role of the reference's specialized single-term scorer, with P often
    orders of magnitude below the doc count.

    Aggregations whose auxiliary arrays are NOT doc-space (range bounds,
    multivalued pair arrays, per-ordinal hash tables) cannot ride the
    _GatherView (it gathers every slot at per-posting doc ids) — those
    plans take the dense path."""
    if not (isinstance(plan.root, PPostings)
            and plan.search_after_relation == "none"):
        return False
    if plan.root.impact_ordered and plan.sort.by not in ("score", "doc"):
        # impact-ordered postings (format v3) break posting-index ==
        # doc-order; a field-primary key's lowest-index-wins ties would
        # diverge from the doc-ordered seed. Score keys are safe (equal-
        # score groups stay contiguous and doc-ascending by the writer's
        # sort contract) and "doc" keys are unique. The dense path below
        # scatters into doc space, which is order-independent.
        return False
    for a in plan.aggs:
        if isinstance(a, BucketAggExec):
            if _bucket_tree_blocks_posting_space([a]):
                return False
        elif isinstance(a, CompositeAggExec):
            # composite CHILDREN are normal nested buckets and carry the
            # same gather-view restrictions
            if _bucket_tree_blocks_posting_space(a.subs):
                return False
        elif isinstance(a, MetricAggExec):
            if a.metric.kind == "cardinality":
                return False
    return True


class _RebaseView:
    """arrays[slot] with FOR-packed slots reconstructed in-register:
    `delta * for_scale + for_min` in the column's integer domain (see
    LoweredPlan.rebase), so sort keys, metric inputs and cardinality
    hashes observe full-width values while HBM holds the narrow lanes.
    Absent lanes reconstruct to for_min rather than the raw layout's 0 —
    invisible downstream because every consumer masks by the present
    column."""

    def __init__(self, arrays, scalars, rebase):
        self.arrays = arrays
        self.scalars = scalars
        self.rebase = rebase

    def __getitem__(self, slot: int):
        arr = self.arrays[slot]
        rb = self.rebase.get(slot)
        if rb is None:
            return arr
        scale, fmin = self.scalars[rb[0]], self.scalars[rb[1]]
        return arr.astype(scale.dtype) * scale + fmin


class _GatherView:
    """arrays[slot] gathered at per-posting doc ids — lets the bucket-agg
    evaluator run unchanged in posting space. FOR-packed slots rebase
    AFTER the gather: the [P]-sized reconstruction is cheaper than
    materializing the full-width doc-space column first."""

    def __init__(self, arrays, safe_ids, scalars=None, rebase=None):
        self.arrays = arrays
        self.safe_ids = safe_ids
        self.scalars = scalars
        self.rebase = rebase or {}

    def __getitem__(self, slot: int):
        g = self.arrays[slot][self.safe_ids]
        rb = self.rebase.get(slot)
        if rb is None:
            return g
        scale, fmin = self.scalars[rb[0]], self.scalars[rb[1]]
        return g.astype(scale.dtype) * scale + fmin


def _build_posting_space(plan: LoweredPlan, k: int,
                         exact: bool = False) -> Callable:
    root, sort, aggs = plan.root, plan.sort, plan.aggs
    padded = plan.num_docs_padded

    def fn(arrays, scalars, num_docs):
        ids = arrays[root.ids_slot]
        tfs = arrays[root.tfs_slot]
        num_postings = ids.shape[0]
        valid = ids < num_docs
        count = jnp.sum(valid.astype(jnp.int32))
        safe_ids = jnp.clip(ids, 0, padded - 1)
        if k == 0:  # count/agg-only: no scoring, no top-k
            gathered = _GatherView(arrays, safe_ids, scalars, plan.rebase)
            agg_out = _eval_aggs(aggs, gathered, scalars, valid)
            return (jnp.zeros((0,), jnp.float64), None,
                    jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.float32),
                    count, jnp.float64(1.0), tuple(agg_out))
        from ..ops.pallas import fused_score_topk, pallas_available
        if (sort.by == "score" and sort.by2 == "none" and root.scoring
                and pallas_available() and k <= 64
                and plan.threshold_slot < 0):
            # QW_PALLAS=1: fused scoring + phase-1 top-k — the dense [P]
            # scores array never materializes; hit scores come straight from
            # the kernel's winners
            vals_f32, pos = fused_score_topk(
                ids, tfs, arrays[root.norm_slot][safe_ids],
                scalars[root.idf_slot], scalars[root.avg_len_slot],
                num_docs, k=min(k, num_postings),
                interpret=jax.default_backend() == "cpu")
            sort_vals = vals_f32.astype(jnp.float64)
            doc_ids = ids[pos]
            hit_scores = jnp.where(mask_ops.dead_lane_mask(vals_f32),
                                   0.0, vals_f32)
            gathered = _GatherView(arrays, safe_ids, scalars, plan.rebase)
            agg_out = _eval_aggs(aggs, gathered, scalars, valid)
            return sort_vals, None, doc_ids.astype(jnp.int32), hit_scores, \
                count, jnp.float64(1.0), tuple(agg_out)
        if root.scoring:
            scores = score_postings(
                tfs, ids, arrays[root.norm_slot],
                scalars[root.avg_len_slot], scalars[root.idf_slot])
        else:
            scores = jnp.zeros(num_postings, dtype=jnp.float32)
        gathered = _GatherView(arrays, safe_ids, scalars, plan.rebase)
        # "doc" sorts key on the posting's doc id (ascending already)
        keyed = _keyed_for(sort.by, sort.descending, sort.values_slot,
                           sort.present_slot, gathered, valid, scores, ids)
        if plan.threshold_slot >= 0:
            # dynamic pruning pushdown: counts/aggs above keep full-query
            # semantics; only top-k eligibility is restricted
            keyed = topk_ops.apply_threshold_mask(
                keyed, scalars[plan.threshold_slot])
            if (root.impact_bmax_slot >= 0 and sort.by == "score"
                    and sort.descending):
                # impact block-max early exit (format v3): whole 128-posting
                # blocks whose quantized score bound cannot reach the
                # threshold mask without scoring — a no-op for results
                # (the bound is sound, so every masked lane was already
                # below the threshold mask above)
                bounds = dequantize_block_bounds(
                    arrays[root.impact_bmax_slot],
                    scalars[root.impact_scale_slot])
                keyed = topk_ops.block_max_threshold_mask(
                    keyed, bounds, scalars[plan.threshold_slot])
        kk = min(k, num_postings)
        topk_safe = jnp.float64(1.0)
        if sort.by2 == "none":
            if exact:
                sort_vals, pos = topk_ops.exact_topk(keyed, kk)
            else:
                sort_vals, pos, topk_safe = topk_ops.guided_topk(keyed, kk)
            sort_vals2 = None
        else:
            keyed2 = _keyed_for(sort.by2, sort.descending2, sort.values2_slot,
                                sort.present2_slot, gathered, valid, scores,
                                ids)
            if plan.threshold_slot >= 0:
                keyed2 = mask_ops.propagate_dead_lanes(keyed, keyed2)
            sort_vals, sort_vals2, pos = topk_ops.exact_topk_2key(
                keyed, keyed2, kk)
        doc_ids = ids[pos]
        hit_scores = scores[pos]
        agg_out = _eval_aggs(aggs, gathered, scalars, valid)
        return sort_vals, sort_vals2, doc_ids.astype(jnp.int32), hit_scores, \
            count, topk_safe, tuple(agg_out)

    return fn


def _eval_composite_agg(a: CompositeAggExec, arrays, scalars, mask):
    """Composite buckets TPU-first: one multi-key lexicographic sort over
    the doc space, run-boundary detection, and the first `size` distinct
    key tuples read back with exact counts — no dynamic hash tables.

    Per-source i32 keys use the order-preserving encoding documented on
    CompositeSourceExec (missing=0, value=(idx+1)*2, after markers odd)."""
    num = mask.shape[0]
    m = mask
    keys = []
    for s in a.sources:
        if s.kind == "terms_ord":
            ordinals = arrays[s.values_slot]
            present = ordinals >= 0
            key = (ordinals.astype(jnp.int32) + 1) * 2
        else:
            values = arrays[s.values_slot]
            present = arrays[s.present_slot].astype(jnp.bool_)
            origin = scalars[s.origin_slot]
            interval = scalars[s.interval_slot]
            if s.kind == "date_histogram":
                idx = ((values - origin) // interval).astype(jnp.int32)
            else:
                idx = jnp.floor((values.astype(jnp.float64) - origin)
                                / interval).astype(jnp.int32)
            key = (idx + 1) * 2
        if s.missing_bucket:
            key = jnp.where(present, key, jnp.int32(0))
        else:
            m = m & present
        keys.append(key)
    if a.has_after:
        # strict lexicographic tuple > after, cascaded per source
        gt = jnp.zeros(num, dtype=jnp.bool_)
        eq = jnp.ones(num, dtype=jnp.bool_)
        for key, s in zip(keys, a.sources):
            marker = scalars[s.after_slot]
            gt = gt | (eq & (key > marker))
            eq = eq & (key == marker)
        m = m & gt
    sentinel = jnp.int32(2**31 - 1)
    keys = [jnp.where(m, key, sentinel) for key in keys]
    # metric operands ride the same sort so per-run (bucket) metric
    # states segment-reduce over contiguous ranges; the position index
    # rides along too, recovering the permutation that lets bucket
    # CHILDREN evaluate back in doc space
    metric_ops: list = []
    for met in a.metrics:
        mv = arrays[met.values_slot].astype(jnp.float64)
        mp = arrays[met.present_slot].astype(jnp.bool_)
        metric_ops.extend([mv, mp & m])
    positions = jnp.arange(num, dtype=jnp.int32)
    sorted_all = jax.lax.sort(tuple(keys) + (positions,) + tuple(metric_ops),
                              num_keys=len(keys))
    if not isinstance(sorted_all, (tuple, list)):
        sorted_all = (sorted_all,)
    sorted_keys = sorted_all[: len(keys)]
    perm = sorted_all[len(keys)]
    sorted_metrics = sorted_all[len(keys) + 1:]
    valid_total = jnp.sum(m.astype(jnp.int32))
    idxs = jnp.arange(num, dtype=jnp.int32)
    diff = jnp.zeros(max(num - 1, 0), dtype=jnp.bool_)
    for sk in sorted_keys:
        diff = diff | (sk[1:] != sk[:-1])
    is_start = jnp.concatenate(
        [jnp.ones(min(num, 1), dtype=jnp.bool_), diff])
    is_start = is_start & (idxs < valid_total)
    start_pos = jnp.where(is_start, idxs, jnp.int32(num))
    k_runs = min(a.size, num)
    neg_top, _ = jax.lax.top_k(-start_pos, min(k_runs + 1, num))
    starts = -neg_top                       # ascending run starts
    if starts.shape[0] < k_runs + 1:
        starts = jnp.concatenate(
            [starts, jnp.full(k_runs + 1 - starts.shape[0], num, jnp.int32)])
    safe = jnp.clip(starts[:k_runs], 0, num - 1)
    run_keys = jnp.stack([sk[safe] for sk in sorted_keys])   # [S, k_runs]
    ends = jnp.minimum(starts[1:], valid_total)
    counts = jnp.where(starts[:k_runs] < valid_total,
                       ends - starts[:k_runs], jnp.int32(0))
    out = {"run_keys": run_keys, "counts": counts}
    # per-position run id = rank of this position's run among the first
    # k_runs (positions past them segment-drop)
    run_id = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    in_range = (idxs < valid_total) & (run_id >= 0) & (run_id < k_runs)
    if a.subs:
        # scatter each doc's run id back to its original position: bucket
        # children then evaluate with the normal nested machinery, the
        # composite acting as the outermost radix level
        run_id_doc = jnp.full(num, k_runs, jnp.int32).at[perm].set(
            jnp.where(in_range, run_id, jnp.int32(k_runs)))
        in_run = run_id_doc < k_runs
        subs = []
        for child in a.subs:
            nb2 = child.num_buckets
            idx2, m2 = _bucket_idx(child, arrays, scalars, mask)
            both = in_run & m2
            combined = jnp.where(both, run_id_doc * nb2 + idx2,
                                 jnp.int32(k_runs * nb2))
            subs.append(_eval_bucket_level(child, arrays, scalars, mask,
                                           combined, both, k_runs * nb2))
        out["subs"] = subs
    if a.metrics:
        metrics: dict[str, Any] = {}
        for mi, met in enumerate(a.metrics):
            mv = sorted_metrics[2 * mi]
            mp = sorted_metrics[2 * mi + 1].astype(jnp.bool_)
            seg = jnp.where(in_range & mp, run_id, jnp.int32(k_runs))
            state: dict[str, Any] = {}
            need = met.kind
            if need in ("sum", "avg", "stats", "extended_stats"):
                state["sum"] = jax.ops.segment_sum(
                    jnp.where(in_range & mp, mv, 0.0), seg,
                    num_segments=k_runs + 1)[:k_runs]
            if need in ("avg", "stats", "extended_stats", "value_count"):
                state["count"] = jax.ops.segment_sum(
                    (in_range & mp).astype(jnp.int64), seg,
                    num_segments=k_runs + 1)[:k_runs]
            if need in ("min", "stats", "extended_stats"):
                state["min"] = jax.ops.segment_min(
                    jnp.where(in_range & mp, mv, jnp.inf), seg,
                    num_segments=k_runs + 1)[:k_runs]
            if need in ("max", "stats", "extended_stats"):
                state["max"] = jax.ops.segment_max(
                    jnp.where(in_range & mp, mv, -jnp.inf), seg,
                    num_segments=k_runs + 1)[:k_runs]
            if need in ("stats", "extended_stats"):
                state["sum_sq"] = jax.ops.segment_sum(
                    jnp.where(in_range & mp, mv * mv, 0.0), seg,
                    num_segments=k_runs + 1)[:k_runs]
            metrics[met.name] = state
        out["metrics"] = metrics
    return out


def _eval_aggs(aggs, gathered, scalars, valid):
    agg_out = []
    for a in aggs:
        if isinstance(a, CompositeAggExec):
            agg_out.append(_eval_composite_agg(a, gathered, scalars, valid))
        elif isinstance(a, BucketAggExec):
            agg_out.append(_eval_bucket_agg(a, gathered, scalars, valid))
        elif isinstance(a, MetricAggExec):
            met = a.metric
            if met.kind == "cardinality":
                hashes, present = _cardinality_hashes(met, gathered)
                agg_out.append(
                    {"hll": agg_ops.hll_registers(hashes, valid & present)})
                continue
            mv = gathered[met.values_slot]
            mp = gathered[met.present_slot]
            if met.kind == "percentiles":
                agg_out.append({"sketch": agg_ops.percentile_sketch(mv, mp, valid)})
            else:
                agg_out.append({"stats": agg_ops.stats_state(mv, mp, valid)})
        else:
            raise TypeError(f"unknown agg exec {type(a).__name__}")
    return agg_out


def _pack_mask(mask, padded: int):
    """Big-endian bit pack of a [padded] bool mask into [ceil(padded/8)]
    uint8 — np.packbits bit order, so a device-computed mask and a host
    np.packbits of the same booleans are byte-identical (the mask-cache
    equivalence tests lean on this)."""
    nbytes = (padded + 7) // 8
    bits = jnp.zeros((nbytes * 8,), dtype=jnp.uint32)
    bits = bits.at[:padded].set(mask.astype(jnp.uint32))
    weights = jnp.array([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint32)
    return jnp.sum(bits.reshape(nbytes, 8) * weights, axis=1).astype(jnp.uint8)


def _unpack_mask(packed, padded: int):
    """Inverse of `_pack_mask`: [nbytes] uint8 -> [padded] bool."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts[None, :]) & jnp.uint8(1)
    return bits.reshape(-1)[:padded].astype(jnp.bool_)


def _node_evaluator(padded: int) -> Callable:
    """The predicate-tree evaluator, shared by the full search kernel
    (`_build`) and the mask-fill kernel (`compute_packed_mask`) — one
    implementation, so a cached mask is bit-identical to inline evaluation
    by construction (zonemaps, FOR-packed compares, msm semantics and
    all)."""

    def eval_node(node, arrays, scalars):
        """Returns (mask[padded] bool, scores[padded] f32 | None)."""
        if isinstance(node, PMatchAll):
            return jnp.ones(padded, dtype=jnp.bool_), None
        if isinstance(node, PMatchNone):
            return jnp.zeros(padded, dtype=jnp.bool_), None
        if isinstance(node, PMaskRef):
            # Tier A hit: the whole predicate is the cached packed bitmask
            return _unpack_mask(arrays[node.packed_slot], padded), None
        if isinstance(node, PPostings):
            ids = arrays[node.ids_slot]
            mask = mask_ops.mask_from_postings(ids, padded)
            if not node.scoring:
                return mask, None
            partial = score_postings(
                arrays[node.tfs_slot], ids, arrays[node.norm_slot],
                scalars[node.avg_len_slot], scalars[node.idf_slot])
            scores = mask_ops.dense_from_postings(ids, partial, padded)
            return mask, scores
        if isinstance(node, PRange):
            values = arrays[node.values_slot]
            if values.dtype.kind == "u" and values.dtype.itemsize <= 4:
                # FOR-packed lanes compare as scaled deltas in i32 — the
                # lowering caps the span so span + 1 (the never-matching
                # bound) stays representable
                values = values.astype(jnp.int32)
            return mask_ops.range_mask(
                values, arrays[node.present_slot],
                scalars[node.lo_slot] if node.lo_slot >= 0 else 0,
                scalars[node.hi_slot] if node.hi_slot >= 0 else 0,
                node.lo_incl, node.hi_incl,
                node.lo_slot >= 0, node.hi_slot >= 0,
                zmin=(arrays[node.zmin_slot]
                      if node.zmin_slot >= 0 else None),
                zmax=(arrays[node.zmax_slot]
                      if node.zmax_slot >= 0 else None),
                zonemap_block=ZONEMAP_BLOCK), None
        if isinstance(node, PPresence):
            col = arrays[node.present_slot]
            return (col >= 0) if node.is_ordinal else col.astype(jnp.bool_), None
        if isinstance(node, PNormPresence):
            return arrays[node.norm_slot] > 0, None
        if isinstance(node, PBool):
            return eval_bool(node, arrays, scalars)
        raise TypeError(f"unknown plan node {type(node).__name__}")

    def eval_bool(node: PBool, arrays, scalars):
        score_parts = []
        conj = None
        for child in list(node.must) + list(node.filter):
            m, s = eval_node(child, arrays, scalars)
            conj = m if conj is None else (conj & m)
            if s is not None:
                score_parts.append(s)
        should_masks = []
        for child in node.should:
            m, s = eval_node(child, arrays, scalars)
            should_masks.append(m)
            if s is not None:
                score_parts.append(s)
        mask = conj
        if should_masks:
            if node.minimum_should_match:
                msm = mask_ops.minimum_should_match_mask(
                    should_masks, node.minimum_should_match)
                mask = msm if mask is None else (mask & msm)
            elif mask is None:
                mask = mask_ops.or_masks(*should_masks)
            # should with must present: purely optional (scoring only)
        if mask is None:
            mask = jnp.ones(padded, dtype=jnp.bool_)
        for child in node.must_not:
            m, _ = eval_node(child, arrays, scalars)
            mask = mask & ~m
        scores = None
        if score_parts:
            scores = score_parts[0]
            for s in score_parts[1:]:
                scores = scores + s
        return mask, scores

    return eval_node


def _build(plan: LoweredPlan, k: int, exact: bool = False) -> Callable:
    if _posting_space_eligible(plan):
        return _build_posting_space(plan, k, exact)
    padded = plan.num_docs_padded
    root, sort, aggs = plan.root, plan.sort, plan.aggs
    eval_node = _node_evaluator(padded)

    def fn(arrays, scalars, num_docs):
        # predicate evaluation reads the raw (possibly packed-delta) arrays;
        # value consumers go through the rebasing view
        view = _RebaseView(arrays, scalars, plan.rebase)
        mask, scores = eval_node(root, arrays, scalars)
        mask = mask & mask_ops.valid_docs_mask(num_docs, padded)
        if scores is None:
            scores = jnp.zeros(padded, dtype=jnp.float32)
        if k == 0:  # count/agg-only: no keying, no top-k
            count = jnp.sum(mask.astype(jnp.int32))
            agg_out = _eval_aggs(aggs, view, scalars, mask)
            return (jnp.zeros((0,), jnp.float64), None,
                    jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.float32),
                    count, jnp.float64(1.0), tuple(agg_out))
        doc_key = _global_doc_ids(plan, scalars, padded)
        keyed = _keyed_for(sort.by, sort.descending, sort.values_slot,
                           sort.present_slot, view, mask, scores, doc_key)
        keyed2 = None
        if sort.by2 != "none":
            keyed2 = _keyed_for(sort.by2, sort.descending2, sort.values2_slot,
                                sort.present2_slot, view, mask, scores,
                                doc_key)
        # search_after pushdown: restrict top-k eligibility, NOT counts/aggs
        # (ES semantics: totals and aggregations cover the full query)
        if plan.search_after_relation != "none":
            keyed, keyed2 = _apply_search_after(plan, keyed, keyed2, scalars,
                                                padded)
        if plan.threshold_slot >= 0:
            # dynamic-pruning threshold: same eligibility-only contract
            keyed = topk_ops.apply_threshold_mask(
                keyed, scalars[plan.threshold_slot])
            if keyed2 is not None:
                keyed2 = mask_ops.propagate_dead_lanes(keyed, keyed2)
        topk_safe = jnp.float64(1.0)
        if keyed2 is None:
            if exact:
                sort_vals, doc_ids = topk_ops.exact_topk(keyed, k)
            else:
                sort_vals, doc_ids, topk_safe = topk_ops.guided_topk(keyed, k)
            sort_vals2 = None
        else:
            sort_vals, sort_vals2, doc_ids = topk_ops.exact_topk_2key(
                keyed, keyed2, k)
        doc_ids = doc_ids.astype(jnp.int32)
        count = jnp.sum(mask.astype(jnp.int32))
        hit_scores = scores[jnp.clip(doc_ids, 0, padded - 1)]
        agg_out = _eval_aggs(aggs, view, scalars, mask)
        return sort_vals, sort_vals2, doc_ids, hit_scores, count, topk_safe, \
            tuple(agg_out)

    return fn


def get_executor(plan: LoweredPlan, k: int, exact: bool = False) -> Callable:
    key = (plan.signature(k), exact)
    cached = _JIT_CACHE.get(key)
    if cached is None:
        cached = jax.jit(_build(plan, k, exact))
        _JIT_CACHE[key] = cached
    return cached


# --- packed readback ---------------------------------------------------------
#
# The result tree has O(10) leaves (hits, count, per-agg counts/metric
# states). Under a remote-tunnel PJRT backend every leaf readback pays
# several ms of per-transfer overhead, so the packed executor concatenates
# every leaf into ONE f64 device array — one transfer per query — and the
# host unpacks by the (treedef, shapes, dtypes) spec captured at trace
# time. f64 packing is exact for every output dtype in use: counts are
# doc-bounded (< 2^53), sums are f64 already, f32↔f64 is exact.

_PACKED_CACHE: dict[tuple, tuple] = {}


def _get_packed_executor(plan: LoweredPlan, k: int, example_args,
                         exact: bool = False, key: tuple = None):
    if key is None:
        key = (plan.signature(k), exact)
    cached = _PACKED_CACHE.get(key)
    if cached is None:
        fn = _build(plan, k, exact)
        shaped = jax.eval_shape(fn, *example_args)
        treedef = jax.tree_util.tree_structure(shaped)
        leaves = jax.tree_util.tree_leaves(shaped)
        spec = [(leaf.shape, leaf.dtype) for leaf in leaves]

        def packed(arrays, scalars, num_docs):
            out = fn(arrays, scalars, num_docs)
            flat = [leaf.reshape(-1).astype(jnp.float64)
                    for leaf in jax.tree_util.tree_leaves(out)]
            return jnp.concatenate(flat) if flat else jnp.zeros((0,))

        cached = (jax.jit(packed), treedef, spec)
        _PACKED_CACHE[key] = cached
    return cached


# qwlint: disable-next-line=QW001 - operates on the ALREADY-transferred
# host buffer from the packed seam; np.prod here is shape math, not I/O
def _unpack_result(packed: np.ndarray, treedef, spec):
    leaves = []
    offset = 0
    for shape, dtype in spec:
        size = int(np.prod(shape)) if shape else 1
        chunk = packed[offset: offset + size]
        offset += size
        leaf = chunk.astype(dtype).reshape(shape)
        leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# --- multi-query dispatch ----------------------------------------------------
#
# B queries that share one plan STRUCTURE (same signature: shapes, agg tree,
# sort spec) and one split's device arrays execute as ONE XLA program — vmap
# over the stacked per-query scalars with the arrays broadcast — and return
# as ONE packed [B, total] readback. Measured on the real chip (see
# tools/profile_tunnel.py): every dispatch round through the axon tunnel
# costs a fixed ~60-65 ms wall regardless of program content and pipelining
# depth cannot amortize it, while work INSIDE one dispatch runs at full
# device speed (~2 ms/query). Batching concurrent queries per dispatch is
# also the reference's own shape: leaf requests are batched per node
# (`quickwit-search/src/leaf.rs:81` greedy_batch_split).

_MULTI_CACHE: dict[tuple, tuple] = {}
_MULTI_SCALAR_CACHE: dict[tuple, Any] = {}
_MULTI_SCALAR_CACHE_CAP = 128


def _batch_bucket(n: int) -> int:
    """Round a convoy size up to the next power of two: arbitrary convoy
    sizes (2..max_batch) would each compile their own vmapped program —
    seconds of stall per new size over a remote transport. Bucketing
    bounds the distinct programs per signature to ~log2(max_batch);
    surplus lanes repeat the last query and are dropped at readback."""
    b = 1
    while b < n:
        b *= 2
    return b


# qwlint: disable-next-line=QW001 - np.asarray on host scalar tuples for
# jax.eval_shape (trace-time, no data movement)
def _get_packed_multi_executor(plan: LoweredPlan, k: int, batch: int,
                               device_arrays, exact: bool = False,
                               key: tuple = None):
    if key is None:
        key = (plan.signature(k), batch, exact)
    cached = _MULTI_CACHE.get(key)
    if cached is None:
        fn = _build(plan, k, exact)
        # eval_shape only consumes shapes/dtypes — numpy example scalars
        # avoid touching the device (a device upload here would cost the
        # very transfer round this path exists to avoid)
        example_args = (tuple(device_arrays),
                        tuple(np.asarray(s) for s in plan.scalars),
                        np.int32(plan.num_docs))
        shaped = jax.eval_shape(fn, *example_args)
        treedef = jax.tree_util.tree_structure(shaped)
        spec = [(leaf.shape, leaf.dtype)
                for leaf in jax.tree_util.tree_leaves(shaped)]

        def multi(arrays, scal_b, nd_b):
            out = jax.vmap(lambda s, n: fn(arrays, s, n),
                           in_axes=(0, 0))(scal_b, nd_b)
            flat = [leaf.reshape(leaf.shape[0], -1).astype(jnp.float64)
                    for leaf in jax.tree_util.tree_leaves(out)]
            return (jnp.concatenate(flat, axis=1) if flat
                    else jnp.zeros((batch, 0)))

        cached = (jax.jit(multi), treedef, spec)
        _MULTI_CACHE[key] = cached
    return cached


# qwlint: disable-next-line=QW001 - host-side scalar staging (stack +
# single device_put); asarray/.item() run on numpy inputs pre-upload
def _device_multi_scalars(plan: LoweredPlan, scalar_sets, use_cache=True):
    """Stacked per-slot [B] scalar arrays + per-lane num_docs, one batched
    H2D transfer, content-cached (repeated batches skip the upload RTT).
    `use_cache=False` forces the upload — the bench uses it so measured
    numbers include the per-batch transfer a mixed workload pays."""
    batch = len(scalar_sets)
    key = None
    if use_cache:
        key = (plan.num_docs, batch,
               tuple(tuple((s.dtype.str, s.item())
                           for s in map(np.asarray, qs))
                     for qs in scalar_sets))
        cached = _MULTI_SCALAR_CACHE.get(key)
        if cached is not None:
            return cached
    stacked = [np.stack([np.asarray(qs[slot]) for qs in scalar_sets])
               for slot in range(len(plan.scalars))]
    nd_b = np.full((batch,), plan.num_docs, np.int32)
    moved = jax.device_put(stacked + [nd_b])
    cached = (tuple(moved[:-1]), moved[-1])
    if key is not None:
        if len(_MULTI_SCALAR_CACHE) >= _MULTI_SCALAR_CACHE_CAP:
            _MULTI_SCALAR_CACHE.pop(next(iter(_MULTI_SCALAR_CACHE)))
        _MULTI_SCALAR_CACHE[key] = cached
    return cached


def dispatch_plan_multi(plan: LoweredPlan, k: int,
                        device_arrays: list[jax.Array],
                        scalar_sets: list, cache_scalars: bool = True,
                        exact: bool = False) -> tuple:
    """Async dispatch of len(scalar_sets) same-structure queries as ONE
    XLA program + ONE packed readback buffer. Each element of
    `scalar_sets` is a full per-query scalar tuple (plan.scalars layout).
    The lane count is padded to a power-of-two bucket (surplus lanes
    repeat the last query and are discarded at readback)."""
    k = max(0, min(k, plan.num_docs_padded))
    SEARCH_KERNEL_LAUNCHES_TOTAL.inc()
    batch = len(scalar_sets)
    bucket = _batch_bucket(batch)
    padded_sets = list(scalar_sets) + [scalar_sets[-1]] * (bucket - batch)
    scal_b, nd_b = _device_multi_scalars(plan, padded_sets,
                                         use_cache=cache_scalars)
    profile = current_profile()
    recording = flight.recording()
    # shared once-per-dispatch cache key (see dispatch_plan)
    key = (plan.signature(k), bucket, exact) \
        if (recording or profile is not None) else None
    if recording:
        hit = key in _MULTI_CACHE
        flight.emit("compile.hit" if hit else "compile.miss",
                    attrs={"path": "multi", "bucket": bucket})
        flight.emit("dispatch.launch",
                    attrs={"path": "multi", "lanes": batch})
    if profile is None:
        executor, treedef, spec = _get_packed_multi_executor(
            plan, k, bucket, device_arrays, exact, key=key)
        out = executor(tuple(device_arrays), scal_b, nd_b)
    else:
        # same lazy-jit attribution as dispatch_plan, keyed per batch
        # bucket (each bucket size compiles its own vmapped program)
        hit = key in _MULTI_CACHE
        profile.add("compile_cache_hits" if hit else "compile_cache_misses")
        with profile.phase(PHASE_EXECUTE if hit else PHASE_COMPILE,
                           stage="dispatch_multi"):
            executor, treedef, spec = _get_packed_multi_executor(
                plan, k, bucket, device_arrays, exact, key=key)
            out = executor(tuple(device_arrays), scal_b, nd_b)
    if hasattr(out, "copy_to_host_async"):
        out.copy_to_host_async()
    return out, treedef, spec, batch, (plan, k, device_arrays,
                                       list(scalar_sets), cache_scalars)


# qwlint: disable-next-line=QW001 - THE sanctioned packed-readback seam:
# the one deliberate device->host transfer per dispatch, profiled as the
# readback stage (ROADMAP item 1 measures exactly this)
def _profiled_device_get(packed):
    profile = current_profile()
    if not flight.recording():
        if profile is None:
            return jax.device_get(packed)
        with profile.phase(PHASE_EXECUTE, stage="readback"):
            return jax.device_get(packed)
    t0 = _clock_monotonic()
    try:
        if profile is None:
            return jax.device_get(packed)
        with profile.phase(PHASE_EXECUTE, stage="readback"):
            return jax.device_get(packed)
    finally:
        flight.emit("dispatch.readback", attrs={
            "dur_ms": round((_clock_monotonic() - t0) * 1000.0, 3)})


# qwlint: disable-next-line=QW001 - batch variant of the sanctioned seam;
# one transfer for the whole batch, then host-side unpack
def readback_plan_multi(dispatched) -> list[dict[str, Any]]:
    """ONE device→host transfer for the whole batch; per-lane unpack.

    Lanes whose guided top-k screen reports `safe == 0` (an f32 boundary
    tie that could reorder f64 winners — see ops/topk.py:guided_topk) are
    re-dispatched as one exact batch and spliced back in."""
    packed, treedef, spec, batch, redispatch = dispatched
    host = np.asarray(_profiled_device_get(packed))
    results = []
    unsafe_lanes = []
    for lane in range(batch):
        sort_vals, sort_vals2, doc_ids, hit_scores, count, topk_safe, \
            agg_out = _unpack_result(host[lane], treedef, spec)
        if float(topk_safe) < 1.0:
            unsafe_lanes.append(lane)
        results.append({
            "sort_values": sort_vals,
            "sort_values2": sort_vals2,
            "doc_ids": doc_ids,
            "scores": hit_scores,
            "count": int(count),
            "aggs": list(agg_out),
        })
    if unsafe_lanes:
        plan, k, device_arrays, scalar_sets, cache_scalars = redispatch
        _note_guided_fallback(len(unsafe_lanes))
        exact = readback_plan_multi(dispatch_plan_multi(
            plan, k, device_arrays,
            [scalar_sets[lane] for lane in unsafe_lanes],
            cache_scalars=cache_scalars, exact=True))
        for lane, res in zip(unsafe_lanes, exact):
            results[lane] = res
    return results


# --- stacked query-group dispatch (ROADMAP item 2) ---------------------------
#
# N DISTINCT queries that share one plan STRUCTURE (same signature: shapes,
# agg tree, sort spec, threshold/search_after presence) over one split's
# resident arrays execute as ONE XLA program: operand slots whose cache key
# matches across every query (columns, norms, shared postings) stay a single
# broadcast buffer served from the ResidentColumnStore; slots whose key
# differs (per-query postings, predicate masks) are stacked into a leading
# [Q] query axis AT TRACE TIME (jnp.stack inside the jitted body — the
# stack fuses into the program, so the group still costs exactly one device
# dispatch). Per-query scalars — including each query's killing threshold
# from its own ThresholdBox (`plan.threshold_slot` becomes a [Q] lane
# vector) — ride the same stacked scalar path as the convoy batcher, and a
# [Q] validity mask zeroes the packed rows of lanes shed AFTER group
# formation (cancel/deadline) without changing the program shape: masking a
# rider never recompiles.

_STACKED_CACHE: dict[tuple, tuple] = {}


def stacked_slot_split(plans) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Partition array slots into (shared, stacked) by per-slot cache-key
    agreement across the group. Array keys are content-addressed within a
    split (``col.ts``, ``post.body=alpha#…``, ``mask.<digest>``), and a
    group is only formed over one split (the grouping key carries the
    split identity), so key equality at a slot ⇒ the queries reference the
    same staged device buffer ⇒ the slot broadcasts; disagreement ⇒ the
    slot gets the leading query axis."""
    keys0 = plans[0].array_keys
    shared, stacked = [], []
    for slot, key in enumerate(keys0):
        if all(p.array_keys[slot] == key for p in plans[1:]):
            shared.append(slot)
        else:
            stacked.append(slot)
    return tuple(shared), tuple(stacked)


# qwlint: disable-next-line=QW001 - np.asarray on host scalar tuples for
# jax.eval_shape (trace-time, no data movement)
def _get_packed_stacked_executor(plan: LoweredPlan, k: int, bucket: int,
                                 stacked_slots: tuple[int, ...],
                                 device_arrays, exact: bool = False,
                                 key: tuple = None):
    if key is None:
        key = (plan.signature(k), bucket, stacked_slots, exact)
    cached = _STACKED_CACHE.get(key)
    if cached is None:
        fn = _build(plan, k, exact)
        nslots = len(plan.arrays)
        shared_slots = tuple(s for s in range(nslots)
                             if s not in stacked_slots)
        example_args = (tuple(device_arrays),
                        tuple(np.asarray(s) for s in plan.scalars),
                        np.int32(plan.num_docs))
        shaped = jax.eval_shape(fn, *example_args)
        treedef = jax.tree_util.tree_structure(shaped)
        spec = [(leaf.shape, leaf.dtype)
                for leaf in jax.tree_util.tree_leaves(shaped)]

        def assemble(shared_arrays, lane_arrays):
            arrays = [None] * nslots
            for i, s in enumerate(shared_slots):
                arrays[s] = shared_arrays[i]
            for i, s in enumerate(stacked_slots):
                arrays[s] = lane_arrays[i]
            return tuple(arrays)

        def stacked(shared_arrays, lane_stacks, scal_b, nd_b, valid_b):
            st = tuple(jnp.stack(qs) for qs in lane_stacks)
            out = jax.vmap(
                lambda lane, s, n: fn(assemble(shared_arrays, lane), s, n),
                in_axes=(0, 0, 0))(st, scal_b, nd_b)
            flat = [leaf.reshape(leaf.shape[0], -1).astype(jnp.float64)
                    for leaf in jax.tree_util.tree_leaves(out)]
            packed = (jnp.concatenate(flat, axis=1) if flat
                      else jnp.zeros((bucket, 0)))
            # masked lanes zero via where, NOT multiply: sort lanes hold
            # -inf pads and -inf * 0 is NaN
            return jnp.where(valid_b[:, None], packed, 0.0)

        cached = (jax.jit(stacked), treedef, spec)
        _STACKED_CACHE[key] = cached
    return cached


# qwlint: disable-next-line=QW001 - host-side scalar staging (stack +
# single device_put); asarray/.item() run on numpy inputs pre-upload
def _device_group_scalars(plans, use_cache=True):
    """Per-slot [Q] scalar stacks + per-lane num_docs for a query group —
    each query contributes its OWN scalar values (threshold, search_after
    markers, rebase scale/min), stacked into query-axis lane vectors and
    moved in one batched H2D transfer. Shares `_MULTI_SCALAR_CACHE` with
    the convoy path (same content-addressed key space)."""
    batch = len(plans)
    key = None
    if use_cache:
        key = ("group", tuple(p.num_docs for p in plans), batch,
               tuple(tuple((s.dtype.str, s.item())
                           for s in map(np.asarray, p.scalars))
                     for p in plans))
        cached = _MULTI_SCALAR_CACHE.get(key)
        if cached is not None:
            return cached
    stacked = [np.stack([np.asarray(p.scalars[slot]) for p in plans])
               for slot in range(len(plans[0].scalars))]
    nd_b = np.asarray([p.num_docs for p in plans], np.int32)
    moved = jax.device_put(stacked + [nd_b])
    cached = (tuple(moved[:-1]), moved[-1])
    if key is not None:
        if len(_MULTI_SCALAR_CACHE) >= _MULTI_SCALAR_CACHE_CAP:
            _MULTI_SCALAR_CACHE.pop(next(iter(_MULTI_SCALAR_CACHE)))
        _MULTI_SCALAR_CACHE[key] = cached
    return cached


def dispatch_plan_stacked(plans, k: int, arrays_list, valid=None,
                          cache_scalars: bool = True,
                          exact: bool = False) -> tuple:
    """Async dispatch of len(plans) shape-compatible DISTINCT queries as
    ONE XLA program + ONE packed [Q, total] readback buffer. `plans[i]`
    and `arrays_list[i]` are query i's lowered plan and staged device
    arrays; all plans must share `signature(k)` (the QueryGroupPlanner
    guarantees this). `valid[i] = False` masks lane i out of the readback
    (zeroed row) without changing the compiled program — the late-shed
    rider path. Lane count pads to a power-of-two bucket (surplus lanes
    repeat the last query, pre-masked invalid)."""
    base = plans[0]
    k = max(0, min(k, base.num_docs_padded))
    SEARCH_KERNEL_LAUNCHES_TOTAL.inc()
    batch = len(plans)
    bucket = _batch_bucket(batch)
    if valid is None:
        valid = [True] * batch
    pad = bucket - batch
    plans_b = list(plans) + [plans[-1]] * pad
    arrays_b = list(arrays_list) + [arrays_list[-1]] * pad
    valid_b = np.zeros(bucket, np.bool_)
    valid_b[:batch] = list(valid)
    shared_slots, stacked_slots = stacked_slot_split(plans_b)
    scal_b, nd_b = _device_group_scalars(plans_b, use_cache=cache_scalars)
    shared_arrays = tuple(arrays_b[0][s] for s in shared_slots)
    lane_stacks = tuple(tuple(arrays_b[q][s] for q in range(bucket))
                        for s in stacked_slots)
    valid_dev = jax.device_put(valid_b)
    profile = current_profile()
    recording = flight.recording()
    # shared once-per-dispatch cache key (see dispatch_plan)
    key = (base.signature(k), bucket, stacked_slots, exact) \
        if (recording or profile is not None) else None
    if recording:
        f_hit = key in _STACKED_CACHE
        flight.emit("compile.hit" if f_hit else "compile.miss",
                    attrs={"path": "stacked", "bucket": bucket})
        flight.emit("dispatch.launch",
                    attrs={"path": "stacked", "lanes": batch})
    if profile is None:
        executor, treedef, spec = _get_packed_stacked_executor(
            base, k, bucket, stacked_slots, arrays_b[0], exact, key=key)
        out = executor(shared_arrays, lane_stacks, scal_b, nd_b, valid_dev)
    else:
        hit = key in _STACKED_CACHE
        profile.add("compile_cache_hits" if hit else "compile_cache_misses")
        with profile.phase(PHASE_EXECUTE if hit else PHASE_COMPILE,
                           stage="dispatch_stacked"):
            executor, treedef, spec = _get_packed_stacked_executor(
                base, k, bucket, stacked_slots, arrays_b[0], exact, key=key)
            out = executor(shared_arrays, lane_stacks, scal_b, nd_b,
                           valid_dev)
    if hasattr(out, "copy_to_host_async"):
        out.copy_to_host_async()
    return out, treedef, spec, batch, (list(plans), k, list(arrays_list),
                                       list(valid), cache_scalars)


# qwlint: disable-next-line=QW001 - stacked variant of the sanctioned
# packed-readback seam; one transfer for the whole query group
def readback_plan_stacked(dispatched) -> list:
    """ONE device→host transfer for the whole query group; per-lane
    unpack. Masked lanes come back as None (their packed row was zeroed on
    device). Valid lanes whose guided top-k screen reports `safe == 0`
    are re-dispatched as one exact stacked group and spliced back in —
    per-query tie-breaks therefore stay bit-identical to solo execution."""
    packed, treedef, spec, batch, redispatch = dispatched
    plans, k, arrays_list, valid, cache_scalars = redispatch
    host = np.asarray(_profiled_device_get(packed))
    results: list = []
    unsafe_lanes = []
    for lane in range(batch):
        if not valid[lane]:
            results.append(None)
            continue
        sort_vals, sort_vals2, doc_ids, hit_scores, count, topk_safe, \
            agg_out = _unpack_result(host[lane], treedef, spec)
        if float(topk_safe) < 1.0:
            unsafe_lanes.append(lane)
        results.append({
            "sort_values": sort_vals,
            "sort_values2": sort_vals2,
            "doc_ids": doc_ids,
            "scores": hit_scores,
            "count": int(count),
            "aggs": list(agg_out),
        })
    if unsafe_lanes:
        _note_guided_fallback(len(unsafe_lanes))
        exact = readback_plan_stacked(dispatch_plan_stacked(
            [plans[lane] for lane in unsafe_lanes], k,
            [arrays_list[lane] for lane in unsafe_lanes],
            cache_scalars=cache_scalars, exact=True))
        for lane, res in zip(unsafe_lanes, exact):
            results[lane] = res
    return results


def dispatch_plan(plan: LoweredPlan, k: int,
                  device_arrays: list[jax.Array], exact: bool = False):
    """Async dispatch: returns (packed_device_array, treedef, spec, ...)
    WITHOUT reading back — the pipelining seam (dispatch query i+1 before
    the readback of query i so concurrent queries amortize the host↔device
    RTT). The whole result tree rides ONE device array (see the packed-
    readback block above); `copy_to_host_async` starts the D2H transfer so
    the later blocking readback only waits out the remainder."""
    k = max(0, min(k, plan.num_docs_padded))
    SEARCH_KERNEL_LAUNCHES_TOTAL.inc()
    scalars, num_docs = _device_scalars(plan)
    args = (tuple(device_arrays), scalars, num_docs)
    profile = current_profile()
    recording = flight.recording()
    # plan.signature() walks the whole plan tree — compute the cache key
    # at most once per dispatch and share it between the flight event, the
    # profile attribution and the executor getter
    key = (plan.signature(k), exact) \
        if (recording or profile is not None) else None
    if recording:
        f_hit = key in _PACKED_CACHE
        flight.emit("compile.hit" if f_hit else "compile.miss",
                    attrs={"path": "solo"})
        flight.emit("dispatch.launch", attrs={"path": "solo", "lanes": 1})
    if profile is None:
        executor, treedef, spec = _get_packed_executor(plan, k, args, exact,
                                                       key=key)
        out = executor(*args)
    else:
        # Compile-vs-execute attribution: jax.jit compiles lazily on first
        # call, so on a packed-cache MISS this dispatch's wall time is
        # trace+XLA-compile (the dispatch itself is an async enqueue); on a
        # HIT it is a cheap enqueue counted toward execute. The
        # approximation is documented in docs/observability.md.
        hit = key in _PACKED_CACHE
        profile.add("compile_cache_hits" if hit else "compile_cache_misses")
        with profile.phase(PHASE_EXECUTE if hit else PHASE_COMPILE,
                           stage="dispatch"):
            executor, treedef, spec = _get_packed_executor(
                plan, k, args, exact, key=key)
            out = executor(*args)
    if hasattr(out, "copy_to_host_async"):
        out.copy_to_host_async()
    return out, treedef, spec, (plan, k, device_arrays)


def _note_guided_fallback(n: int = 1) -> None:
    """Count guided-top-k exact re-dispatches (f32 screen tie detected)."""
    from ..observability.metrics import METRICS
    METRICS.counter("qw_topk_guided_fallback_total").inc(n)


# qwlint: disable-next-line=QW001 - the sanctioned seam's single-plan
# entry point; the blocking device_get IS the measured readback
def readback_plan_result(dispatched) -> dict[str, Any]:
    """ONE device→host transfer for the entire result tree, unpacked by
    the trace-time spec. A guided top-k lane reporting `safe == 0` is
    re-executed with the exact blockwise kernel before returning."""
    packed, treedef, spec, redispatch = dispatched
    profile = current_profile()
    t0 = _clock_monotonic() if flight.recording() else 0.0
    if profile is None:
        host = jax.device_get(packed)
    else:
        # the blocking readback absorbs the device execution time
        with profile.phase(PHASE_EXECUTE, stage="readback"):
            host = jax.device_get(packed)
    if flight.recording():
        flight.emit("dispatch.readback", attrs={
            "dur_ms": round((_clock_monotonic() - t0) * 1000.0, 3)})
    sort_vals, sort_vals2, doc_ids, hit_scores, count, topk_safe, agg_out = \
        _unpack_result(host, treedef, spec)
    if float(topk_safe) < 1.0:
        plan, k, device_arrays = redispatch
        _note_guided_fallback()
        return readback_plan_result(
            dispatch_plan(plan, k, device_arrays, exact=True))
    return {
        "sort_values": sort_vals,
        "sort_values2": sort_vals2,
        "doc_ids": doc_ids,
        "scores": hit_scores,
        "count": int(count),
        "aggs": list(agg_out),
    }


def execute_plan(plan: LoweredPlan, k: int,
                 device_arrays: list[jax.Array]) -> dict[str, Any]:
    """Run the plan; returns host-side numpy results."""
    return readback_plan_result(dispatch_plan(plan, k, device_arrays))


def executor_cache_size() -> int:
    return len(_JIT_CACHE)


# --- static-audit hooks (tools/qwir) -----------------------------------------
#
# The auditor (`python -m tools.qwir audit`) abstract-evals the SAME
# closures the dispatch paths jit — `_build`, the vmapped multi-query
# wrapper, the mask-fill kernel — over ShapeDtypeStructs. The audited
# jaxpr therefore IS the program the compile caches key (modulo the packed
# f64 readback concat, which is audited separately as the sanctioned
# seam), with zero compilation, zero data movement, and zero devices
# touched. The `*_cache_key` mirrors must stay in lockstep with the
# dict-key expressions in `get_executor` / `_get_packed_executor` /
# `_get_packed_multi_executor` / `_get_packed_stacked_executor` /
# `compute_packed_mask` — the R1 closure certificate is only a proof if
# the audited key IS the cache key.

def program_cache_key(plan: LoweredPlan, k: int, exact: bool = False) -> tuple:
    """The `_JIT_CACHE`/`_PACKED_CACHE` key for this plan, post k-clamp."""
    k = max(0, min(k, plan.num_docs_padded))
    return (plan.signature(k), exact)


def multi_program_cache_key(plan: LoweredPlan, k: int, batch: int,
                            exact: bool = False) -> tuple:
    """The `_MULTI_CACHE` key (batch already bucketed by the caller)."""
    k = max(0, min(k, plan.num_docs_padded))
    return (plan.signature(k), batch, exact)


def stacked_program_cache_key(plans, k: int, bucket=None,
                              exact: bool = False) -> tuple:
    """The `_STACKED_CACHE` key for a query group — MUST stay in lockstep
    with the dict-key expression in `_get_packed_stacked_executor` (same
    R1 lockstep contract as the other mirrors above)."""
    base = plans[0]
    k = max(0, min(k, base.num_docs_padded))
    if bucket is None:
        bucket = _batch_bucket(len(plans))
    _, stacked_slots = stacked_slot_split(plans)
    return (base.signature(k), bucket, stacked_slots, exact)


def abstract_stacked_program(plans, k: int, bucket=None,
                             exact: bool = False):
    """ClosedJaxpr of the stacked query-group program for one batch bucket
    (the closure `_get_packed_stacked_executor` jits, minus the packed f64
    concat — audited separately as the sanctioned seam; the validity mask
    is applied per-leaf so the zeroed-readback semantics stay in the
    audited body)."""
    base = plans[0]
    k = max(0, min(k, base.num_docs_padded))
    if bucket is None:
        bucket = _batch_bucket(len(plans))
    fn = _build(base, k, exact)
    nslots = len(base.arrays)
    shared_slots, stacked_slots = stacked_slot_split(plans)
    arrays, scalars, _ = _abstract_inputs(base)
    shared = tuple(arrays[s] for s in shared_slots)
    lane_stacks = tuple(tuple(arrays[s] for _ in range(bucket))
                        for s in stacked_slots)
    scal_b = tuple(jax.ShapeDtypeStruct((bucket,) + s.shape, s.dtype)
                   for s in scalars)
    nd_b = jax.ShapeDtypeStruct((bucket,), np.int32)
    valid_b = jax.ShapeDtypeStruct((bucket,), np.bool_)

    def assemble(shared_arrays, lane_arrays):
        out = [None] * nslots
        for i, s in enumerate(shared_slots):
            out[s] = shared_arrays[i]
        for i, s in enumerate(stacked_slots):
            out[s] = lane_arrays[i]
        return tuple(out)

    def stacked(shared_arrays, lane_stacks, scal_b, nd_b, valid_b):
        st = tuple(jnp.stack(qs) for qs in lane_stacks)
        out = jax.vmap(
            lambda lane, s, n: fn(assemble(shared_arrays, lane), s, n),
            in_axes=(0, 0, 0))(st, scal_b, nd_b)
        return jax.tree_util.tree_map(
            lambda leaf: jnp.where(
                valid_b.reshape((bucket,) + (1,) * (leaf.ndim - 1)),
                leaf, jnp.zeros_like(leaf)),
            out)

    return jax.make_jaxpr(stacked)(shared, lane_stacks, scal_b, nd_b,
                                   valid_b)


def mask_fill_cache_key(plan: LoweredPlan) -> tuple:
    """The `_MASK_FILL_CACHE` key for this plan's predicate-only kernel."""
    return (plan.root.sig(),
            tuple((a.shape, str(a.dtype)) for a in plan.arrays),
            tuple(str(s.dtype) for s in map(np.asarray, plan.scalars)),
            plan.num_docs_padded)


def _abstract_inputs(plan: LoweredPlan):
    arrays = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                   for a in plan.arrays)
    scalars = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                    for s in map(np.asarray, plan.scalars))
    return arrays, scalars, jax.ShapeDtypeStruct((), np.int32)


def abstract_program(plan: LoweredPlan, k: int, exact: bool = False):
    """ClosedJaxpr of the single-split leaf program — traced, never run."""
    k = max(0, min(k, plan.num_docs_padded))
    fn = _build(plan, k, exact)
    arrays, scalars, num_docs = _abstract_inputs(plan)
    return jax.make_jaxpr(fn)(arrays, scalars, num_docs)


def abstract_multi_program(plan: LoweredPlan, k: int, batch: int,
                           exact: bool = False):
    """ClosedJaxpr of the vmapped multi-query program for one batch bucket
    (the closure `_get_packed_multi_executor` jits, minus the packed
    concat)."""
    k = max(0, min(k, plan.num_docs_padded))
    fn = _build(plan, k, exact)
    arrays, scalars, _ = _abstract_inputs(plan)
    scal_b = tuple(jax.ShapeDtypeStruct((batch,) + s.shape, s.dtype)
                   for s in scalars)
    nd_b = jax.ShapeDtypeStruct((batch,), np.int32)

    def multi(arrays, scal_b, nd_b):
        return jax.vmap(lambda s, n: fn(arrays, s, n),
                        in_axes=(0, 0))(scal_b, nd_b)

    return jax.make_jaxpr(multi)(arrays, scal_b, nd_b)


def abstract_mask_fill(plan: LoweredPlan):
    """ClosedJaxpr of the Tier-A predicate-mask fill kernel
    (`compute_packed_mask`'s jitted body)."""
    padded = plan.num_docs_padded
    root = plan.root
    eval_node = _node_evaluator(padded)

    def mask_fn(arrays, scalars, num_docs):
        mask, _ = eval_node(root, arrays, scalars)
        mask = mask & mask_ops.valid_docs_mask(num_docs, padded)
        return _pack_mask(mask, padded)

    arrays, scalars, num_docs = _abstract_inputs(plan)
    return jax.make_jaxpr(mask_fn)(arrays, scalars, num_docs)


# qwir R2 certification registry: functions in THIS module allowed to mint
# doc-scale f64 lanes or feed f64 sorts. Keys are function qualnames as
# they appear in jaxpr eqn source frames; values are the justification the
# audit report carries. Keep justifications concrete — they are the
# "inline justified suppression" the acceptance gate requires.
QWIR_CERTIFIED_F64 = {
    "_keyed_for": (
        "the unified sort key IS f64 by contract: it must represent i64 "
        "column values and epoch-micros exactly (f32 collapses distinct "
        "timestamps). The corpus-scale-sort hazard this feeds is screened "
        "by guided_topk's f32 path; exact f64 sorts are certified at "
        "their ops/topk.py sites."),
    "_apply_search_after": (
        "search_after eligibility rewrites the f64 key lanes in place "
        "(same dtype in, same dtype out) — no new f64 surface beyond "
        "_keyed_for's certified key."),
}


# --- predicate-mask fill (Tier A, search/mask_cache.py) ----------------------

_MASK_FILL_CACHE: dict[tuple, Callable] = {}


def compute_packed_mask(
        plan: LoweredPlan,
        device_arrays: list[jax.Array]) -> tuple[np.ndarray, jax.Array]:
    """Evaluate ONLY the plan's predicate root over already-staged device
    arrays and return `(host_packed, device_packed)` — the uint8 bitmask in
    np.packbits bit order, both as the host copy destined for the cache tier
    and as the still-device-resident original so callers can seed it into a
    warm split's residency cache without a round trip.

    Runs as its own tiny jitted kernel right after the main execute, while
    the split's arrays are still pinned — so a fill costs one extra launch
    plus a padded/8-byte readback, not a re-staging. Reuses the SAME
    `_node_evaluator` as the search kernel: the cached mask is bit-identical
    to inline evaluation by construction. Callers must gate on
    `plan.count_override is None` — an impact-prefix-truncated plan
    (format v3) never saw the posting tail, so its mask would be
    incomplete."""
    padded = plan.num_docs_padded
    root = plan.root
    key = (root.sig(),
           tuple((a.shape, str(a.dtype)) for a in plan.arrays),
           tuple(str(s.dtype) for s in plan.scalars),
           padded)
    fill = _MASK_FILL_CACHE.get(key)
    if fill is None:
        eval_node = _node_evaluator(padded)

        def mask_fn(arrays, scalars, num_docs):
            mask, _ = eval_node(root, arrays, scalars)
            mask = mask & mask_ops.valid_docs_mask(num_docs, padded)
            return _pack_mask(mask, padded)

        fill = jax.jit(mask_fn)
        _MASK_FILL_CACHE[key] = fill
    scalars, num_docs = _device_scalars(plan)
    SEARCH_KERNEL_LAUNCHES_TOTAL.inc()
    packed = fill(tuple(device_arrays), scalars, num_docs)
    # qwlint: disable-next-line=QW001 - deliberate padded/8-byte readback of
    # the freshly computed mask into the host-side cache tier
    return np.asarray(jax.device_get(packed), dtype=np.uint8), packed
