"""Device-resident column store: warm splits' packed columns stay in HBM.

Role of the reference's fast-field cache stack, lifted to the device: the
seed engine cached device arrays per open `SplitReader`
(`reader._device_array_cache`), so residency died whenever the reader LRU
closed — and every reopen re-paid the full host→device staging. The
`ResidentColumnStore` keys residency by **split id** instead: a
`SplitColumns` owner object with a stable identity survives reader churn,
so a warm repeat query stages ZERO column bytes (the
`qw_resident_staging_cache_hits_total` counter is the test-asserted proof).

Byte accounting is NOT duplicated: `SplitColumns` quacks like a reader
(it carries `_device_array_cache`), so `HbmBudget`'s existing pinned →
resident flow, LRU eviction, and tenant-DRR admission all see resident
column bytes through the same seam they always did. Eviction arrives via
`HbmBudget._evict_locked()` calling `cache.clear()` — the notifying dict
reports it here (metrics + `residency.evict` fault point) before dropping
the refs.

Eviction cannot corrupt an in-flight query: `warmup_device_arrays` hands
the executor a plain list of device-array references, so a concurrent
`clear()` only unpins HBM once the kernel's own references die. The
`residency.evict` chaos point injects failures INTO the eviction
notification to prove exactly that; injected errors are absorbed (an
eviction-side fault must never fail an innocent query that merely
triggered LRU pressure).

Mesh-resident stacks (`mesh_stack_id`): the multi-chip collective root
merge (parallel/fanout.py) stages STACKED column families — one
[n_splits, padded] array per column slot, sharded over the
("splits", "docs") mesh — whose content is query-independent given the
split set. They ride this same store as synthetic "splits" keyed by
`mesh_stack_id(...)`: the owner's `device_bytes` tracks the PER-DEVICE
shard footprint (what each chip's HBM actually holds), admission and LRU
eviction flow through the identical `HbmBudget` owner seam, and a warm
multi-split query uploads zero column bytes to ANY chip
(`qw_resident_staging_cache_hits_total` counts whole-stack hits just as
it counts whole-plan hits on the per-split path).
"""

from __future__ import annotations

import logging
import weakref
from typing import Any, Optional

from ..common.faults import InjectedFault
from ..observability import flight
from ..observability.metrics import METRICS
from ..common import sync

logger = logging.getLogger(__name__)

RESIDENT_COLUMN_HITS = METRICS.counter(
    "qw_resident_column_hits_total",
    "Columns served from the device-resident store (no device_put)")
RESIDENT_COLUMN_MISSES = METRICS.counter(
    "qw_resident_column_misses_total",
    "Columns staged cold (one batched device_put per warmup)")
RESIDENT_STAGING_CACHE_HITS = METRICS.counter(
    "qw_resident_staging_cache_hits_total",
    "Warmups fully served from the resident store: zero column device_put")
RESIDENT_EVICTIONS = METRICS.counter(
    "qw_resident_evictions_total",
    "Resident split column sets evicted (HbmBudget LRU pressure)")
RESIDENT_BYTES = METRICS.gauge(
    "qw_resident_bytes",
    "Device bytes currently held by the resident column store")
RESIDENT_READBACKS_SHED = METRICS.counter(
    "qw_resident_readbacks_shed_total",
    "Async readbacks skipped because every rider's deadline had expired")


def note_group_shared_staging(plans, live_lanes: int) -> int:
    """Residency accounting for a stacked multi-query dispatch
    (search/batcher.py): operand slots whose cache key agrees across the
    group are staged ONCE and broadcast to every lane — each such slot is
    a (live_lanes - 1)-fold device_put the resident store did not have to
    absorb. Records the avoided bytes under the qbatch family and the
    per-column hit counters (the shared slots ARE resident-store serves:
    identical keys alias the same staged buffer), returns the byte
    count."""
    if live_lanes <= 1 or not plans:
        return 0
    from .executor import stacked_slot_split
    shared_slots, _stacked = stacked_slot_split(plans)
    if not shared_slots:
        return 0
    nbytes = sum(plans[0].arrays[s].nbytes for s in shared_slots) \
        * (live_lanes - 1)
    from ..observability.metrics import QBATCH_SHARED_BYTES_AVOIDED_TOTAL
    QBATCH_SHARED_BYTES_AVOIDED_TOTAL.inc(nbytes)
    RESIDENT_COLUMN_HITS.inc(len(shared_slots) * (live_lanes - 1))
    return nbytes


def mesh_stack_id(split_ids, num_docs_padded: int, mesh) -> str:
    """Stable residency key for one mesh-stacked column set.

    Identity is (ordered split set, padded doc count, mesh shape): batch
    lanes are pinned to split_id order by the service, the padded size
    fixes every stacked array's shape, and arrays committed for one mesh
    sharding must never be fed to an executor compiled for another (the
    same rule `stage_device_inputs` keys its per-request cache on). The
    digest keeps the id bounded for wide fan-outs."""
    import hashlib
    ident = repr((tuple(split_ids), num_docs_padded,
                  tuple(mesh.axis_names), tuple(mesh.devices.shape)))
    digest = hashlib.blake2b(ident.encode(), digest_size=12).hexdigest()
    return f"meshstack:{digest}"


class _NotifyingCache(dict):
    """`_device_array_cache`-shaped dict whose `clear()` tells the store.

    `HbmBudget._evict_locked` evicts residency by calling `cache.clear()`
    on the owner's `_device_array_cache` — subclassing dict turns that
    pre-existing call into the store's eviction notification with zero
    changes to the admission layer."""

    __slots__ = ("_store_ref", "_split_id")

    def __init__(self, store: "ResidentColumnStore", split_id: str):
        super().__init__()
        self._store_ref = weakref.ref(store)
        self._split_id = split_id

    def clear(self) -> None:  # noqa: A003 - dict interface
        store = self._store_ref()
        if store is not None and self:
            store._on_evict(self._split_id)
        super().clear()


class SplitColumns:
    """HbmBudget owner for one split's device-resident columns.

    Identity (not the reader's) is what admission pins and residency keys
    on, so reopening a split's reader neither loses the resident bytes nor
    re-admits them."""

    __slots__ = ("split_id", "_device_array_cache", "device_bytes",
                 "__weakref__")

    def __init__(self, store: "ResidentColumnStore", split_id: str):
        self.split_id = split_id
        self._device_array_cache = _NotifyingCache(store, split_id)
        self.device_bytes = 0


class ResidentColumnStore:
    """Per-device map split_id → `SplitColumns`, metrics, chaos hook.

    The store holds the STRONG reference to each `SplitColumns`;
    `HbmBudget._resident` holds only a weakref. On eviction the store
    drops its entry, the owner dies, and the budget's weakref callback
    cleans up the residency row — the same lifecycle readers already had.
    """

    def __init__(self, fault_injector=None):
        self._lock = sync.lock("ResidentColumnStore._lock")
        sync.register_shared(self, "ResidentColumnStore")
        self._by_split: dict[str, SplitColumns] = {}
        self._bytes = 0
        self.fault_injector = fault_injector
        self.enabled = True

    # ------------------------------------------------------------------
    def columns_for(self, split_id: str) -> SplitColumns:
        with self._lock:
            cols = self._by_split.get(split_id)
            if cols is None:
                cols = self._by_split[split_id] = SplitColumns(self, split_id)
            return cols

    def peek(self, split_id: str) -> Optional[SplitColumns]:
        with self._lock:
            return self._by_split.get(split_id)

    def note_upload(self, split_id: str, nbytes: int, columns: int) -> None:
        """Record a cold staging: `columns` columns, `nbytes` landed."""
        RESIDENT_COLUMN_MISSES.inc(columns)
        if flight.recording():
            flight.emit("staging.upload",
                        attrs={"split": split_id, "bytes": nbytes,
                               "columns": columns})
        with self._lock:
            cols = self._by_split.get(split_id)
            if cols is not None:
                cols.device_bytes += nbytes
            self._bytes += nbytes
            RESIDENT_BYTES.set(self._bytes)

    def note_hits(self, columns: int, full: bool) -> None:
        """Record `columns` columns served resident; `full` means the whole
        warmup needed zero device_put (the warm-repeat-query proof)."""
        if columns:
            RESIDENT_COLUMN_HITS.inc(columns)
            if flight.recording():
                flight.emit("staging.resident_hit",
                            attrs={"columns": columns, "full": int(full)})
        if full:
            RESIDENT_STAGING_CACHE_HITS.inc()

    # ------------------------------------------------------------------
    def _on_evict(self, split_id: str) -> None:
        """Called from `_NotifyingCache.clear()` — i.e. from inside
        `HbmBudget._evict_locked` under the budget lock. Must not call back
        into the budget, and must absorb injected faults: an eviction-side
        failure may lose residency (re-staged next query) but must never
        propagate into whichever query's admission triggered the LRU."""
        try:
            if self.fault_injector is not None:
                self.fault_injector.perturb("residency.evict")
        except InjectedFault as exc:
            logger.warning("residency.evict fault absorbed for split %s: %s",
                           split_id, exc)
        finally:
            with self._lock:
                cols = self._by_split.pop(split_id, None)
                freed = cols.device_bytes if cols is not None else 0
                if cols is not None:
                    cols.device_bytes = 0
                self._bytes -= freed
                RESIDENT_BYTES.set(self._bytes)
            RESIDENT_EVICTIONS.inc()
            if flight.recording():
                flight.emit("staging.evict",
                            attrs={"split": split_id, "bytes": freed})
            logger.info("resident columns evicted: split=%s bytes=%d",
                        split_id, freed)

    # --- observability ------------------------------------------------
    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "splits": len(self._by_split),
                "bytes": self._bytes,
                "by_split": {sid: cols.device_bytes
                             for sid, cols in self._by_split.items()},
            }
