"""Leaf search: one split, end to end on device.

Role of the reference's `leaf_search_single_split` (`quickwit-search/src/
leaf.rs:657`): open the split (footer GET → reader), lower the query
(`doc_mapper.query` analogue), warm up (fetch + device-transfer exactly the
arrays the plan needs), execute the jitted kernel, and emit a mergeable
`LeafSearchResponse`.

Device-array residency is cached per split reader (the role of the
fast-field/hotcache byte caches): repeated queries touching the same
postings/columns skip both storage IO (ByteRangeCache) and host→HBM copies.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
import numpy as np

from ..models.doc_mapper import DocMapper, FieldType
from ..index.reader import SplitReader
from ..observability.profile import (
    PHASE_PLAN_BUILD, PHASE_STAGING_CACHE_HIT, PHASE_STAGING_UPLOAD,
    PHASE_TOPK_MERGE, current_profile, profile_add, profiled_phase,
)
from ..observability.metrics import (
    PREDICATE_STAGED_BYTES_TOTAL, STAGING_BYTES_TOTAL,
)
from ..ops.aggs import PCTL_NUM_BUCKETS
from ..query.aggregations import parse_aggs
from .executor import execute_plan
from .models import LeafSearchResponse, PartialHit, SearchRequest, SplitSearchError
from .plan import (BucketAggExec, CompositeAggExec, MetricAggExec,
                   lower_request, predicate_only_slots)


from ..ops.topk import MISSING_VALUE_SENTINEL
from .hostdecode import host_array, host_float, host_int, host_list


def decode_raw_sort_value(internal: float, sort_field: str, sort_order: str,
                          sort_is_int: bool, score: float, doc_id: int):
    """Internal higher-is-better key → displayed raw sort value.

    Shared by the single-split and batched decode paths so the sort-key
    encoding lives in exactly one place."""
    if sort_field == "_score":
        return host_float(score)
    if sort_field == "_doc":
        return doc_id
    if internal <= MISSING_VALUE_SENTINEL:
        return None
    raw = internal if sort_order == "desc" else -internal
    return host_int(raw) if sort_is_int else raw


def decode_sort_value_exact(internal: float, sort_field: str,
                            sort_order: str, sort_is_int: bool,
                            score: float, doc_id: int, exact_col):
    """`decode_raw_sort_value` + the exact 64-bit column re-read for int
    sorts (internal f64 keys round at 2^53) — the one decode used for
    primary AND secondary keys on both the per-split and batched paths."""
    raw = decode_raw_sort_value(internal, sort_field, sort_order,
                                sort_is_int, score, doc_id)
    if raw is not None and sort_is_int and exact_col is not None:
        # exact_col is the reader's mmap'd host column, never device data
        return host_int(exact_col[doc_id])
    return raw


def _device_cache(reader: SplitReader) -> dict[str, Any]:
    cache = getattr(reader, "_device_array_cache", None)
    if cache is None:
        cache = reader._device_array_cache = {}
    return cache


def warmup_device_arrays(reader: SplitReader, plan, budget=None,
                         store=None, split_id: Optional[str] = None
                         ) -> tuple[list, int, Any]:
    """Host→device transfer of the plan's arrays, with cross-query reuse
    (role of `warmup`, `leaf.rs:304`). With an `HbmBudget`, the exact NEW
    transfer bytes are admitted (blocking while over budget) BEFORE any
    device_put — the byte-accurate SearchPermitProvider role. FOR-packed
    columns (format v2) reach this point as their narrow u8/u16/u32 delta
    lanes, so `arr.nbytes` admits the COMPACT device footprint — the
    packing's HBM win flows through admission with no special casing.

    With a `ResidentColumnStore` (`store` + `split_id`), residency keys on
    the split id — the `SplitColumns` owner survives reader reopens, warm
    repeat queries perform ZERO column device_put (profiled as the
    `staging_cache_hit` phase), and only cold columns ride one batched
    `device_put` (`staging_upload`). Without a store, the legacy
    per-reader cache applies and residency dies with the reader.

    Returns (device_arrays, admitted_bytes, owner); the caller releases
    `owner` (NOT necessarily the reader) after execution. The returned
    list holds plain references, so a concurrent LRU eviction clearing the
    cache cannot corrupt this query's execution."""
    if store is not None and split_id is not None:
        owner = store.columns_for(split_id)
        cache = owner._device_array_cache
    else:
        owner = reader
        cache = _device_cache(reader)
    missing = [(slot, key, arr)
               for slot, (key, arr) in enumerate(zip(plan.array_keys,
                                                     plan.arrays))
               if key not in cache]
    staging_bytes = sum(arr.nbytes for _, _, arr in missing)
    if missing:
        STAGING_BYTES_TOTAL.inc(staging_bytes)
        # predicate-only attribution: the bytes a mask-cache hit avoids.
        # The bench's "zero predicate staging when warm" invariant asserts
        # on exactly this counter (tools/bench.py::c11_dashboard_qps).
        pred_slots = predicate_only_slots(plan)
        predicate_bytes = sum(arr.nbytes for slot, _, arr in missing
                              if slot in pred_slots)
        if predicate_bytes:
            PREDICATE_STAGED_BYTES_TOTAL.inc(predicate_bytes)
            profile_add("predicate_staging_bytes", predicate_bytes)
    admitted = 0
    if budget is not None:
        # pins the owner even when nothing is missing (zero-byte
        # admission): its cached device arrays are in use and must not be
        # evicted mid-query
        admitted = budget.admit(owner, staging_bytes)
    try:
        if missing:
            # one batched host→device transfer (each separate device_put
            # pays a full RTT under the axon tunnel). The staging phase
            # times the transfer DISPATCH (device_put is async; completion
            # overlaps into the execute phase by design).
            with profiled_phase(PHASE_STAGING_UPLOAD) as rec:
                if rec is not None:
                    rec["bytes"] = staging_bytes
                    rec["arrays"] = len(missing)
                transferred = jax.device_put([arr for _, _, arr in missing])
            profile_add("staging_bytes", staging_bytes)
            for (_, key, _), dev in zip(missing, transferred):
                cache[key] = dev
            if store is not None and split_id is not None:
                store.note_upload(split_id, staging_bytes, len(missing))
                store.note_hits(len(plan.array_keys) - len(missing),
                                full=False)
        else:
            # the whole plan is device-resident: no transfer, no staging —
            # the phase records the skip (bytes served, none moved)
            with profiled_phase(PHASE_STAGING_CACHE_HIT) as rec:
                if rec is not None:
                    rec["bytes"] = 0
                    rec["bytes_resident"] = sum(a.nbytes
                                                for a in plan.arrays)
                    rec["arrays"] = len(plan.array_keys)
            if store is not None and split_id is not None:
                store.note_hits(len(plan.array_keys), full=True)
        return [cache[key] for key in plan.array_keys], admitted, owner
    except BaseException:
        if budget is not None:
            budget.release(owner, admitted, to_resident=False)
        raise


def prepare_plan_only(
    request: SearchRequest,
    doc_mapper: DocMapper,
    reader: SplitReader,
    split_id: str,
    absence_sink=None,
    sort_value_threshold: Optional[float] = None,
    aggs_override: Optional[dict] = None,
    mask_override=None,
    mask_key: Optional[str] = None,
):
    """Stage 1a: storage byte-range IO + plan lowering WITHOUT the device
    transfer. The service's per-split path defers H2D to the execute
    stage so each split's admit→transfer→execute→release cycle runs
    alone — a whole group admitted up front could exceed the budget and
    starve itself.

    `sort_value_threshold` (internal higher-is-better key) is pushed into
    the plan as a traced scalar masking sub-threshold docs before top_k
    (search/pruning.py); the plan signature only encodes its PRESENCE, so
    compiled executables are reused across threshold values.

    Hierarchical-cache hooks (search/service.py::_consult_split_caches):
    `aggs_override` replaces the request's agg dict — the partial-agg tier
    passes only the aggs it MISSED, so cached ones are neither lowered nor
    staged nor computed ({} lowers none at all). `mask_override`/`mask_key`
    forward a cached packed predicate mask to `lower_request`, which then
    skips query lowering and every predicate column."""
    aggs_dict = request.aggs if aggs_override is None else aggs_override
    agg_specs = parse_aggs(aggs_dict) if aggs_dict else []
    sort = request.sort_fields[0] if request.sort_fields else None
    sort_field = sort.field if sort else "_score"
    sort_order = sort.order if sort else "desc"
    sort2 = request.sort_fields[1] if len(request.sort_fields) > 1 else None
    # plan_build covers storage byte-range IO (footer/postings/column
    # reads surface as storage_read_* counters) plus the lowering itself
    with profiled_phase(PHASE_PLAN_BUILD) as rec:
        if rec is not None:
            rec["split_id"] = split_id
        return lower_request(
            request.query_ast, doc_mapper, reader, agg_specs,
            sort_field=sort_field, sort_order=sort_order,
            sort2_field=sort2.field if sort2 else None,
            sort2_order=sort2.order if sort2 else "desc",
            start_timestamp=request.start_timestamp,
            end_timestamp=request.end_timestamp,
            search_after=search_after_marker(request, split_id, sort_field,
                                             sort_order, sort2,
                                             doc_mapper=doc_mapper,
                                             reader=reader),
            absence_sink=absence_sink,
            sort_value_threshold=sort_value_threshold,
            mask_override=mask_override,
            mask_key=mask_key,
        )


def prepare_single_split(
    request: SearchRequest,
    doc_mapper: DocMapper,
    reader: SplitReader,
    split_id: str,
    absence_sink=None,
    budget=None,
    store=None,
) -> tuple[Any, list, int]:
    """Stage 1 of leaf search — everything up to (and including) starting
    the host→device transfer: storage byte-range IO via the reader, plan
    lowering, and the async `device_put`. Runs on a prefetch thread so the
    next split batch's IO overlaps the current batch's kernel execution
    (SURVEY hard-part #4: warmup/compute pipelining)."""
    plan = prepare_plan_only(request, doc_mapper, reader, split_id,
                             absence_sink)
    # device_put is async: the transfer proceeds while the caller executes
    # the previous batch's kernel
    device_arrays, admitted, _owner = warmup_device_arrays(
        reader, plan, budget, store=store, split_id=split_id)
    return plan, device_arrays, admitted


def leaf_search_single_split(
    request: SearchRequest,
    doc_mapper: DocMapper,
    reader: SplitReader,
    split_id: str,
) -> LeafSearchResponse:
    plan, device_arrays, _ = prepare_single_split(request, doc_mapper,
                                                  reader, split_id)
    return execute_prepared_split(request, doc_mapper, reader, split_id,
                                  plan, device_arrays)


def execute_prepared_split(
    request: SearchRequest,
    doc_mapper: DocMapper,
    reader: SplitReader,
    split_id: str,
    plan: Any,
    device_arrays: list,
    batcher=None,
    threshold_box=None,
    fault_injector=None,
) -> LeafSearchResponse:
    """Stage 2: jitted kernel execution + the single batched readback.
    With a `QueryBatcher`, concurrent same-structure queries on this split
    share one vmapped dispatch (see search/batcher.py). Work that profiles
    past the chunk-sizer target runs as a resumable chunked scan instead
    (search/chunkexec.py): cancellable/preemptable at every chunk boundary,
    with cross-chunk early termination fed by `threshold_box`."""
    from ..common.deadline import current_deadline
    from ..tenancy.context import effective_tenant
    from .chunkexec import PREEMPT_GATE, maybe_execute_chunked
    ambient = current_deadline()
    if ambient is not None:
        # shed before launching a kernel whose result nobody can use; the
        # service turns this into a typed, retryable SplitSearchError
        ambient.check(f"leaf split {split_id} execute")
    t0 = time.monotonic()
    sort = request.sort_fields[0] if request.sort_fields else None
    sort_field = sort.field if sort else "_score"
    sort_order = sort.order if sort else "desc"
    sort2 = request.sort_fields[1] if len(request.sort_fields) > 1 else None
    # k=0 (count/agg-only): the executor skips keying and top-k entirely
    k = request.start_offset + request.max_hits
    if plan.threshold_slot >= 0:
        from ..observability.metrics import SEARCH_KERNEL_THRESHOLD_TOTAL
        SEARCH_KERNEL_THRESHOLD_TOTAL.inc()
        profile_add("kernel_threshold_pushdowns")
    # fused splits register with the preempt gate too: their presence is
    # what tells a running chunked scan that interactive work is waiting
    with PREEMPT_GATE.running(effective_tenant().priority):
        from .batcher import qbatch_enabled
        if batcher is not None and qbatch_enabled():
            # query-axis stacking: the batcher must see the query BEFORE
            # the chunked check so distinct shape-compatible queries can
            # group; solo riders and formed groups both keep resumable
            # chunked semantics inside the batcher (execute_group_chunked
            # / maybe_execute_chunked)
            result = batcher.execute(plan, k, device_arrays,
                                     split_key=id(reader),
                                     threshold_box=threshold_box,
                                     fault_injector=fault_injector)
        else:
            result = maybe_execute_chunked(plan, k, device_arrays,
                                           threshold_box=threshold_box,
                                           fault_injector=fault_injector)
            if result is None:
                if batcher is not None:
                    result = batcher.execute(plan, k, device_arrays,
                                             split_key=id(reader))
                else:
                    result = execute_plan(plan, k, device_arrays)
    # cancelled mid-scan with partial_on_cancel: keep the chunks already
    # merged, flag the split so the root's response carries cancelled=true
    # qwlint: disable-next-line=QW001 - "partial" is a host bool stamped by
    # the chunked scan's boundary loop, never a device value
    partial_cancel = bool(result.get("partial"))

    count = result["count"]
    if getattr(plan, "count_override", None) is not None:
        # impact prefix cutoff (plan.py): the kernel only saw the live
        # prefix of a single bare term's postings, so its count is a
        # truncation artifact — the exact match count is the term's df
        count = plan.count_override
    profile = current_profile()
    t_merge = time.monotonic()
    num_hits_returned = min(k, count)
    partial_hits = []
    # text-field sort: internal keys are split-local dictionary ordinals —
    # decode to term strings here (the reference's leaf likewise returns
    # term bytes); collector merges on the strings
    text_dict = (reader.column_dict(plan.sort_text_field)
                 if plan.sort_text_field else None)
    sort_is_int = _sort_values_are_int(doc_mapper, sort_field)
    sort2_is_int = (_sort_values_are_int(doc_mapper, sort2.field)
                    if sort2 else False)
    # exact 64-bit display values: internal keys are f64 (2^53 mantissa),
    # so i64/u64 values near ±2^63 round — re-read the exact column value
    # host-side for the k returned hits (the reference returns exact
    # tantivy column values in hits[].sort)
    exact_col = (reader.column_values(sort_field)[0]
                 if sort_is_int and text_dict is None else None)
    exact_col2 = (reader.column_values(sort2.field)[0]
                  if sort2 is not None and sort2_is_int else None)
    # bulk .tolist() pre-decode: the packed readback already pulled these
    # to host, so ONE conversion per array replaces a per-hit int()/float()
    # in the loop below (everything past here touches Python scalars only)
    sort_values = host_list(result["sort_values"][:num_hits_returned])
    doc_ids = host_list(result["doc_ids"][:num_hits_returned])
    scores = host_list(result["scores"][:num_hits_returned])
    values2 = result.get("sort_values2")
    if values2 is not None:
        values2 = host_list(values2[:num_hits_returned])
    for i in range(num_hits_returned):
        internal = sort_values[i]
        if internal == float("-inf"):
            break  # fewer eligible hits than k (search_after pushdown)
        doc_id = doc_ids[i]
        if text_dict is not None:
            if internal == MISSING_VALUE_SENTINEL:
                raw = None
            else:
                ordinal = host_int(internal if sort_order == "desc"
                                   else -internal)
                raw = text_dict[ordinal]
        else:
            raw = decode_sort_value_exact(
                internal, sort_field, sort_order, sort_is_int,
                scores[i], doc_id, exact_col)
        internal2, raw2 = 0.0, None
        if sort2 is not None and values2 is not None:
            internal2 = values2[i]
            raw2 = decode_sort_value_exact(
                internal2, sort2.field, sort2.order, sort2_is_int,
                scores[i], doc_id, exact_col2)
        partial_hits.append(PartialHit(
            sort_value=internal, split_id=split_id, doc_id=doc_id,
            raw_sort_value=raw, sort_value2=internal2, raw_sort_value2=raw2))

    intermediate_aggs = _intermediate_aggs(plan, result["aggs"])
    if profile is not None:
        # host-side top-K decode + agg-state extraction for this split
        profile.record_phase(PHASE_TOPK_MERGE,
                             time.monotonic() - t_merge, start=t_merge,
                             split_id=split_id, hits=len(partial_hits))
    # qwlint: disable-next-line=QW001 - time.monotonic() arithmetic, host
    elapsed = int((time.monotonic() - t0) * 1e6)
    return LeafSearchResponse(
        num_hits=count,
        partial_hits=partial_hits,
        # a partial-on-cancel split still counts as successful (its hits are
        # real and mergeable); the cancel marker below is what flips the
        # root response to cancelled=true without tripping the
        # every-split-failed guard
        num_attempted_splits=1,
        num_successful_splits=1,
        failed_splits=([SplitSearchError(
            split_id=split_id,
            error="query cancelled: progressive partial results up to the "
                  "last completed chunk boundary",
            retryable=False)] if partial_cancel else []),
        intermediate_aggs=intermediate_aggs,
        resource_stats={"cpu_micros": elapsed},
    )


def search_after_marker(request: SearchRequest, split_id: str,
                        sort_field: str, sort_order: str, sort2=None,
                        doc_mapper=None, reader=None):
    """(internal_value, internal_value2|None, relation, marker_doc) for this
    split, or None.

    A hit qualifies iff key < m, or key == m and (split, doc) > (m_split,
    m_doc); the split relation is static per split:
      split < m_split  → strictly-less ("lt")
      split == m_split → less-or-doc-tie ("lt_tie")
      split > m_split  → less-or-equal ("le")

    String markers (text-field sorts): internal keys are SPLIT-LOCAL
    dictionary ordinals, so the raw term string translates per split via
    binary search in the column dict; a term absent from this split maps
    to the half-ordinal between its neighbors (f64 keys compare exactly),
    with tie relations impossible by construction.
    """
    if not request.search_after:
        return None
    sa = list(request.search_after)
    # search_after markers are request-JSON scalars (wire data, never
    # device arrays) — decode through the audited host seam
    if sort2 is not None and len(sa) == 4:
        raw, raw2, m_split, m_doc = sa[0], sa[1], sa[2], host_int(sa[3])
    else:
        raw, raw2, m_split, m_doc = sa[0], None, sa[1], host_int(sa[2])
    if m_split is not None:
        m_split = str(m_split)

    string_sort = None
    if doc_mapper is not None:
        from .models import string_sort_of
        string_sort = string_sort_of(request, doc_mapper)

    def encode_string(value: str, order: str) -> float:
        import bisect
        terms = reader.column_dict(sort_field)
        index = bisect.bisect_left(terms, value)
        if index < len(terms) and terms[index] == value:
            ordinal = host_float(index)     # exact: tie relations apply
        else:
            ordinal = index - 0.5           # between neighbors: no ties
        return ordinal if order == "desc" else -ordinal

    def encode(value, field, order):
        if value is None:
            return MISSING_VALUE_SENTINEL
        if string_sort is not None and field == sort_field \
                and isinstance(value, str):
            return encode_string(value, order)
        return (host_float(value) if order == "desc"
                else -host_float(value))

    internal = encode(raw, sort_field, sort_order)
    internal2 = (encode(raw2, sort2.field, sort2.order)
                 if sort2 is not None else None)
    if m_split is None:
        # value-only ES marker: strictly after the value in every split
        relation = "lt"
    elif split_id < m_split:
        relation = "lt"
    elif split_id == m_split:
        relation = "lt_tie"
    else:
        relation = "le"
    return (internal, internal2, relation, m_doc)


def _sort_values_are_int(doc_mapper: DocMapper, sort_field: str) -> bool:
    fm = doc_mapper.field(sort_field)
    return fm is not None and fm.type in (
        FieldType.I64, FieldType.U64, FieldType.DATETIME, FieldType.BOOL, FieldType.IP)


def _truncate_terms_state(state: dict[str, Any]) -> None:
    """Per-split `split_size` truncation (reference/tantivy shard_size
    semantics): forward only the top-N buckets by count; the largest
    dropped count becomes this split's doc_count_error_upper_bound
    contribution (error bounds sum at merge)."""
    counts = host_array(state["counts"])
    split_size = host_int(state["split_size"])
    nonzero = host_int((counts > 0).sum())
    if nonzero <= split_size:
        state["error_bound"] = 0
        return
    order = np.argsort(-counts, kind="stable")
    dropped_max = host_int(counts[order[split_size]])
    kept = np.zeros_like(counts)
    kept_idx = order[:split_size]
    kept[kept_idx] = counts[kept_idx]
    state["error_bound"] = dropped_max
    # ES/tantivy compute sum_other_doc_count from the FULL per-split doc
    # total, not just forwarded buckets — carry the dropped mass
    state["other_docs"] = host_int(counts.sum() - kept.sum())
    state["counts"] = kept


def _sub_state(child, res) -> dict[str, Any]:
    """Mergeable state of one nested bucket child: counts/metrics over
    the FLATTENED (ancestor-radix) space, plus its own children."""
    state = {
        "name": child.name,
        "kind": "terms" if child.kind == "terms_mv" else child.kind,
        "nb": child.num_buckets,
        "counts": host_array(res["counts"]),
        "metrics": {name: {k: host_array(v) for k, v in m.items()}
                    for name, m in res["metrics"].items()},
        "metric_kinds": {m.name: m.kind for m in child.metrics},
        "metric_percents": {m.name: list(m.percents) for m in child.metrics
                            if m.kind == "percentiles"},
        "metric_keyed": {m.name: m.keyed for m in child.metrics},
        **child.host_info,
    }
    if child.subs and "subs" in res:
        state["subs"] = [_sub_state(grandchild, grand_res)
                        for grandchild, grand_res
                        in zip(child.subs, res["subs"])]
    return state


def _intermediate_aggs(plan, agg_results: list) -> dict[str, Any]:
    """Device outputs + host_info → the mergeable intermediate agg states
    (role of the reference's serialized intermediate aggregation results)."""
    out: dict[str, Any] = {}
    for a, res in zip(plan.aggs, agg_results):
        if isinstance(a, BucketAggExec):
            state: dict[str, Any] = {
                # terms_mv is an execution detail; the mergeable state is a
                # plain terms state (counts over the ordinal space)
                "kind": "terms" if a.kind == "terms_mv" else a.kind,
                "counts": host_array(res["counts"]),
                "metrics": {name: {k: host_array(v) for k, v in m.items()}
                            for name, m in res["metrics"].items()},
                "metric_kinds": {m.name: m.kind for m in a.metrics},
                "metric_percents": {m.name: list(m.percents) for m in a.metrics
                                    if m.kind == "percentiles"},
                "metric_keyed": {m.name: m.keyed for m in a.metrics},
                **a.host_info,
            }
            if (a.kind == "terms" and state.get("split_size")
                    and state.get("order_target", "_count") == "_count"):
                # split_size truncation keeps top-N by count — unsound
                # under _key/metric ordering (the globally-first bucket
                # could rank low by count in every split), so those
                # orders forward exact per-split states instead
                _truncate_terms_state(state)
            if a.subs and "subs" in res:
                state["subs"] = [_sub_state(child, child_res)
                                 for child, child_res
                                 in zip(a.subs, res["subs"])]
            out[a.name] = state
        elif isinstance(a, CompositeAggExec):
            run_keys = host_array(res["run_keys"])       # [S, k_runs]
            counts = host_array(res["counts"])
            src_infos = a.host_info["sources"]
            metric_kinds = a.host_info.get("metric_kinds", {})
            res_metrics = {name: {k: host_array(v) for k, v in m.items()}
                           for name, m in res.get("metrics", {}).items()}
            buckets = []
            for j in range(run_keys.shape[1]):
                if counts[j] <= 0:
                    continue
                values = []
                for si, info in enumerate(src_infos):
                    enc = host_int(run_keys[si, j])
                    if enc == 0:
                        values.append(None)
                        continue
                    idx = enc // 2 - 1
                    if info["kind"] == "terms":
                        values.append(info["keys"][idx])
                    else:  # histogram kinds decode to absolute keys
                        values.append(info["origin"] + idx * info["interval"])
                entry = [values, host_int(counts[j])]
                if res_metrics or a.subs:
                    entry.append({
                        name: {k: (host_float(v[j]) if k != "count"
                                   else host_int(v[j]))
                               for k, v in state.items()}
                        for name, state in res_metrics.items()})
                if a.subs:
                    # run index: the collector decodes this bucket's
                    # children out of the flattened child states below
                    entry.append(j)
                buckets.append(entry)
            state_out = {
                "kind": "composite", "buckets": buckets,
                "size": a.host_info["size"],
                "metric_kinds": dict(metric_kinds),
                "sources": [{"name": i["name"], "kind": i["kind"]}
                            for i in src_infos],
            }
            if a.subs and "subs" in res:
                state_out["subs"] = [
                    _sub_state(child, child_res)
                    for child, child_res in zip(a.subs, res["subs"])]
            out[a.name] = state_out
        elif isinstance(a, MetricAggExec):
            met = a.metric
            if met.kind == "percentiles":
                out[a.name] = {"kind": "percentiles",
                               "sketch": host_array(res["sketch"]),
                               "percents": list(met.percents),
                               "keyed": met.keyed}
            elif met.kind == "cardinality":
                out[a.name] = {"kind": "cardinality",
                               "hll": host_array(res["hll"])}
            else:
                out[a.name] = {"kind": met.kind, "state": host_array(res["stats"])}
    return out
