from .models import (
    LeafSearchResponse, PartialHit, SearchRequest, SearchResponse, SortField,
    SplitIdAndFooter, SplitSearchError,
)
from .leaf import leaf_search_single_split
from .collector import IncrementalCollector, finalize_aggregations

__all__ = [
    "SearchRequest", "SearchResponse", "LeafSearchResponse", "PartialHit",
    "SortField", "SplitIdAndFooter", "SplitSearchError",
    "leaf_search_single_split", "IncrementalCollector", "finalize_aggregations",
]
