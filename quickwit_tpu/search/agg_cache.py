"""Tier B — partial-aggregation cache.

Memoizes one split's COUNT and per-aggregation intermediate states keyed
`(split_id, canonical_filter_digest, agg_shape_digest)`. A dashboard of N
panels sharing one filter but fanning out over N distinct agg shapes
warms this cache on the first pass; subsequent passes collapse to
root-side merges of the cached partials — zero column staging, zero
kernel launches for the cached (split, agg) pairs.

Why this is sound: splits are immutable, and the executor computes
`count` and agg states from the FULL filter mask — search_after and
sort-value threshold pushdown restrict top-K *eligibility* only, never
counts/aggs (search/executor.py, the `fn` kernel). So a state filled
during a thresholded or paginated query is bit-identical to one filled
cold. The stored value IS the mergeable `intermediate_aggs`
representation the root collector consumes (search/collector.py), so a
hit plugs straight into the merge. States are stored pickled and
unpickled per hit — the collector merge MUTATES states, so every hit
must hand it a fresh copy.

`agg_shape_digest` hashes the aggregation SPEC (not its name): two panels
naming the same `{"terms": {"field": "severity"}}` differently still
share one entry.

Chaos points mirror search/mask_cache.py: `cache.mask_corrupt` on a hit
degrades to recompute; `cache.evict` on a put force-clears the calling
tenant's partition first. Both are absorbed — the triggering query never
fails.
"""

from __future__ import annotations

import hashlib
import json
import pickle
from typing import Any, Optional

from ..common.faults import InjectedFault
from ..observability.metrics import (
    AGG_CACHE_EVICTED_BYTES_TOTAL, AGG_CACHE_HITS_TOTAL,
    AGG_CACHE_MISSES_TOTAL,
)
from .tenant_cache import TenantPartitionedCache


def agg_shape_digest(spec: dict) -> str:
    """Digest of one aggregation's spec dict (name-independent)."""
    return hashlib.blake2b(
        json.dumps(spec, sort_keys=True).encode(), digest_size=16).hexdigest()


class PartialAggCache:
    def __init__(self, capacity_bytes: int = 32 << 20, fault_injector=None):
        self._cache = TenantPartitionedCache(
            capacity_bytes,
            on_evict=AGG_CACHE_EVICTED_BYTES_TOTAL.inc,
            tier="partial_agg")
        self.fault_injector = fault_injector

    def _get(self, key: str) -> Optional[bytes]:
        raw = self._cache.get(key)
        if raw is not None and self.fault_injector is not None:
            try:
                self.fault_injector.perturb("cache.mask_corrupt")
            except InjectedFault:
                # injected corruption: drop the entry, degrade to recompute
                self._cache.delete(key)
                raw = None
        if raw is None:
            AGG_CACHE_MISSES_TOTAL.inc()
            return None
        AGG_CACHE_HITS_TOTAL.inc()
        return raw

    def _put(self, key: str, raw: bytes) -> None:
        if self.fault_injector is not None:
            try:
                self.fault_injector.perturb("cache.evict")
            except InjectedFault:
                # injected eviction storm: clear this tenant's partition,
                # then land the put — the triggering query is unharmed
                self._cache.clear_current_partition()
        self._cache.put(key, raw)

    def get_count(self, split_id: str, digest: str) -> Optional[int]:
        raw = self._get(f"{split_id}:{digest}:count")
        # qwlint: disable-next-line=QW001 - int() parses cached host BYTES
        # (the b"%d" put_count wrote), never a device value
        return None if raw is None else int(raw)

    def put_count(self, split_id: str, digest: str, count: int) -> None:
        self._put(f"{split_id}:{digest}:count", b"%d" % count)

    def get_agg(self, split_id: str, digest: str,
                shape_digest: str) -> Optional[Any]:
        raw = self._get(f"{split_id}:{digest}:agg:{shape_digest}")
        return None if raw is None else pickle.loads(raw)

    def put_agg(self, split_id: str, digest: str, shape_digest: str,
                state: Any) -> None:
        self._put(f"{split_id}:{digest}:agg:{shape_digest}",
                  pickle.dumps(state))

    @property
    def stats(self) -> dict:
        return self._cache.stats
