"""Snippet (highlight) generation for fetched docs.

Role of tantivy's SnippetGenerator used by the reference's fetch-docs phase:
extract a fragment of each requested field around query-term matches and
wrap matches in <em> tags (ES highlight convention).
"""

from __future__ import annotations

import re
from typing import Any

from ..query import ast as Q
from ..query.tokenizers import get_tokenizer

MAX_FRAGMENT_CHARS = 150


def _terms_for_field(ast: Q.QueryAst, field: str, out: set[str]) -> None:
    if isinstance(ast, Q.Term) and ast.field == field:
        out.add(ast.value.lower())
    elif isinstance(ast, Q.FullText) and ast.field == field:
        for token in get_tokenizer("default")(ast.text):
            out.add(token.text)
    elif isinstance(ast, Q.TermSet):
        for term in ast.terms_per_field.get(field, ()):
            out.add(term.lower())
    elif isinstance(ast, Q.Bool):
        for child in ast.must + ast.should + ast.filter:
            _terms_for_field(child, field, out)
    elif isinstance(ast, Q.Boost):
        _terms_for_field(ast.underlying, field, out)


def generate_snippets(doc: dict[str, Any], fields: tuple[str, ...],
                      ast: Q.QueryAst) -> dict[str, list[str]]:
    snippets: dict[str, list[str]] = {}
    for field in fields:
        value = doc
        for key in field.split("."):
            if not isinstance(value, dict) or key not in value:
                value = None
                break
            value = value[key]
        if not isinstance(value, str):
            continue
        terms: set[str] = set()
        _terms_for_field(ast, field, terms)
        if not terms:
            continue
        pattern = re.compile(
            r"\b(" + "|".join(re.escape(t) for t in sorted(terms)) + r")\b",
            re.IGNORECASE)
        match = pattern.search(value)
        if match is None:
            continue
        start = max(0, match.start() - MAX_FRAGMENT_CHARS // 2)
        end = min(len(value), start + MAX_FRAGMENT_CHARS)
        fragment = value[start:end]
        highlighted = pattern.sub(lambda m: f"<em>{m.group(0)}</em>", fragment)
        snippets[field] = [highlighted]
    return snippets
