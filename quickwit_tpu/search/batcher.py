"""Cross-query dispatch coalescing.

Concurrent queries that lower to the SAME plan structure (signature) and
the SAME device arrays on one split differ only in their traced scalars
(term idf, range bounds, agg origins, markers). The batcher executes such
queries as ONE vmapped XLA program via `executor.dispatch_plan_multi` —
one dispatch round + one packed readback for the whole batch.

Why this exists (measured; tools/profile_tunnel.py): each dispatch round
through a remote-TPU transport costs a fixed wall-clock overhead that
pipelining depth cannot amortize, while work inside one dispatch runs at
device speed. Batching concurrent requests per dispatch is also the
reference's own shape — leaf requests are batched per node
(`quickwit-search/src/leaf.rs:81` greedy_batch_split).

Batching is convoy-style: dispatches for one key are serialized by a
per-key lock, so queries arriving while a dispatch is in flight pile up
and ride the next dispatch together. A lone query pays ZERO added
latency — the lock is free and it dispatches immediately."""

from __future__ import annotations

import threading
from typing import Any

from . import executor


class _Pending:
    __slots__ = ("scalars", "event", "result", "error")

    def __init__(self, scalars):
        self.scalars = scalars
        self.event = threading.Event()
        self.result: Any = None
        self.error: Exception | None = None


class QueryBatcher:
    """Groups concurrent same-(signature, arrays, split) queries into one
    multi-query dispatch. Thread-safe; every caller blocks only for its
    own result."""

    def __init__(self, max_batch: int = 16):
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._queues: dict[tuple, list[_Pending]] = {}
        # per-key dispatch serialization, refcounted so the dict cannot
        # grow without bound across query shapes / reader reopens
        self._dispatch_locks: dict[tuple, list] = {}  # key -> [lock, refs]
        # observability: dispatches vs queries served (batching efficiency)
        self.num_dispatches = 0
        self.num_queries = 0

    def execute(self, plan, k: int, device_arrays, split_key) -> dict[str, Any]:
        """Run one query, possibly riding a shared dispatch. `split_key`
        must uniquely identify the split (reader identity); the key also
        carries the plan's array cache keys, so queries sharing a dispatch
        are guaranteed to read the very same device arrays (two terms of
        equal posting shape lower to the same signature but DIFFERENT
        arrays — they must not share)."""
        key = (plan.signature(k), tuple(plan.array_keys), split_key)
        me = _Pending(plan.scalars)
        my_queue = None
        with self._lock:
            self.num_queries += 1
            queue = self._queues.get(key)
            if queue is not None and len(queue) < self.max_batch:
                queue.append(me)          # follower: the leader serves us
            else:
                # new (or full) queue: lead a FRESH list. A full previous
                # list stays owned by its own leader (it is popped by
                # identity below), so its followers are never orphaned.
                my_queue = [me]
                self._queues[key] = my_queue
                entry = self._dispatch_locks.setdefault(
                    key, [threading.Lock(), 0])
                entry[1] += 1
                dispatch_lock = entry[0]
        if my_queue is None:
            me.event.wait()
            if me.error is not None:
                raise _waiter_error(me.error)
            return me.result
        # serialize dispatches per key: while a previous dispatch is in
        # flight this blocks, and our queue keeps accumulating followers —
        # the batching window emerges from real dispatch latency instead of
        # a configured sleep
        try:
            with dispatch_lock:
                with self._lock:
                    if self._queues.get(key) is my_queue:
                        del self._queues[key]
                    batch = my_queue
                    self.num_dispatches += 1
                try:
                    if len(batch) == 1:
                        results = [executor.execute_plan(plan, k,
                                                         device_arrays)]
                    else:
                        results = executor.readback_plan_multi(
                            executor.dispatch_plan_multi(
                                plan, k, device_arrays,
                                [p.scalars for p in batch]))
                    for pending, result in zip(batch, results):
                        pending.result = result
                        pending.event.set()
                except Exception as exc:  # noqa: BLE001 - fan to waiters
                    for pending in batch:
                        pending.error = exc
                        pending.event.set()
        finally:
            with self._lock:
                entry = self._dispatch_locks.get(key)
                if entry is not None:
                    entry[1] -= 1
                    if entry[1] <= 0:
                        del self._dispatch_locks[key]
        if me.error is not None:
            raise me.error
        return me.result


def _waiter_error(err: Exception) -> Exception:
    """A fresh per-waiter exception chained to the shared dispatch error:
    many waiter threads re-raising the SAME instance would race on its
    __traceback__ and leak handler-side mutations across queries."""
    try:
        copy = type(err)(*err.args)
    except Exception:  # noqa: BLE001 - exotic constructor signatures
        copy = RuntimeError(f"batched dispatch failed: {err!r}")
    copy.__cause__ = err
    return copy
