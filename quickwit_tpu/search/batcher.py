"""Cross-query dispatch coalescing and device-side multi-query batching.

Two grouping regimes share this module's convoy machinery:

* Convoy coalescing (the seed behavior, and the whole behavior under
  `QW_DISABLE_QBATCH`): concurrent queries that lower to the SAME plan
  structure (signature) and the SAME device arrays on one split differ
  only in their traced scalars (term idf, range bounds, agg origins,
  markers). The batcher executes such queries as ONE vmapped XLA program
  via `executor.dispatch_plan_multi` — one dispatch round + one packed
  readback for the whole batch.

* Query-axis stacking (ROADMAP item 2, default): the `QueryGroupPlanner`
  widens the grouping key to the STRUCTURAL signature only — N DISTINCT
  queries (different terms, filters, thresholds, sort markers) over one
  split group together as long as their lowered plans share a structure
  digest. The group executes as ONE stacked dispatch
  (`executor.dispatch_plan_stacked`): operand slots whose cache key
  agrees across the group broadcast from the ResidentColumnStore, the
  rest gain a leading query axis, per-query scalars (including each
  query's killing threshold) ride [Q] lane vectors, and a validity mask
  lane-zeroes riders shed AFTER group formation — a late cancel or
  deadline never rebuilds or recompiles the group. Groups compose with
  chunked execution (`chunkexec.execute_group_chunked`: carried state
  grows a query dim, per-query masks at chunk boundaries).

Why this exists (measured; tools/profile_tunnel.py): each dispatch round
through a remote-TPU transport costs a fixed wall-clock overhead that
pipelining depth cannot amortize, while work inside one dispatch runs at
device speed. Batching concurrent requests per dispatch is also the
reference's own shape — leaf requests are batched per node
(`quickwit-search/src/leaf.rs:81` greedy_batch_split).

Batching is convoy-style: dispatches for one key are serialized by a
per-key lock, so queries arriving while a dispatch is in flight pile up
and ride the next dispatch together. A lone query pays ZERO added
latency — the lock is free and it dispatches immediately.

Deadline behavior: every rider carries its ambient deadline. Followers
wait bounded (never past their own expiry plus a small leader-signal
slack); at dispatch time the leader sheds already-expired riders with
`DeadlineExceeded` but still dispatches for the live ones — a leader must
never orphan its followers."""

from __future__ import annotations

import heapq
import itertools
import os
import time
from typing import Any, Optional

from ..common import sync
from ..common.deadline import (
    CancellationToken, CancelledQuery, Deadline, DeadlineExceeded,
    current_cancel_token, current_deadline,
)
from ..observability import flight
from ..observability.metrics import (
    QBATCH_GROUPS_TOTAL, QBATCH_INCOMPATIBLE_TOTAL,
    QBATCH_MASKED_RIDERS_TOTAL, QBATCH_QUERIES_PER_DISPATCH,
    SEARCH_BATCHER_DISPATCHES_TOTAL, SEARCH_BATCHER_QUERIES_TOTAL,
    SEARCH_BATCHER_QUEUE_WAIT, SEARCH_BATCHER_RATIO, SEARCH_SHED_TOTAL,
)
from ..observability.profile import (
    PHASE_BATCHER_QUEUE, PHASE_QBATCH_GROUP, current_profile,
)
from ..tenancy.context import effective_tenant
from ..tenancy.overload import OVERLOAD, OverloadShed
from ..tenancy.registry import GLOBAL_TENANCY
from . import chunkexec, executor

# Extra follower wait beyond its own deadline: the leader may be setting the
# event at this very moment — shedding exactly at expiry would discard a
# result that is already computed.
_FOLLOWER_SLACK_SECS = 0.05

# A rider with a CancellationToken polls its event in slices of this size so
# a mid-wait cancel is observed promptly instead of after the full batch
# round-trip (the shed-before-readback gap).
_CANCEL_POLL_SECS = 0.05


def qbatch_enabled() -> bool:
    """Query-axis stacking kill switch: `QW_DISABLE_QBATCH=1` restores the
    convoy-only seed behavior byte for byte (grouping key, dispatch path,
    and metrics all revert). Read per call so tests and operators can flip
    it without rebuilding the batcher."""
    return os.environ.get("QW_DISABLE_QBATCH", "").strip().lower() not in (
        "1", "true", "yes", "on")


class _PriorityLock:
    """Per-key dispatch lock with priority-ordered handoff.

    `threading.Lock` hands contended acquisitions to an arbitrary waiter;
    here, when several convoy leaders for the same key are queued behind an
    in-flight dispatch, the leader from the highest-priority tenant
    dispatches next (FIFO within a priority band). With a single waiter —
    or all waiters at equal priority — behavior is indistinguishable from
    the plain lock this replaces."""

    def __init__(self):
        self._cond = sync.condition(name="batcher_dispatch_cv")
        self._held = False
        self._waiters: list[tuple[int, int]] = []  # heap: (-priority, seq)
        self._seq = itertools.count()

    def acquire(self, priority: int = 0) -> None:
        with self._cond:
            entry = (-priority, next(self._seq))
            heapq.heappush(self._waiters, entry)
            while self._held or self._waiters[0] != entry:
                self._cond.wait()
            heapq.heappop(self._waiters)
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()


class _Pending:
    __slots__ = ("plan", "arrays", "scalars", "tbox", "tenant", "event",
                 "result", "error", "deadline", "enqueued_at", "profile",
                 "cancel")

    def __init__(self, scalars, deadline: Optional[Deadline] = None,
                 profile=None, cancel: Optional[CancellationToken] = None,
                 plan=None, arrays=None, tbox=None, tenant=None):
        self.scalars = scalars
        # query-axis stacking: each rider carries its OWN lowered plan and
        # staged device arrays (distinct queries in one group), plus its
        # ThresholdBox for per-lane tightening in chunked group scans
        self.plan = plan
        self.arrays = arrays
        self.tbox = tbox
        self.tenant = tenant
        self.event = sync.event()
        self.result: Any = None
        self.error: Exception | None = None
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        # each rider's ambient QueryProfile (or None): the leader reports
        # every rider's queue wait into ITS profile at dispatch time
        self.profile = profile
        # the rider's ambient CancellationToken (or None): consulted by
        # both the rider's own wait and the leader's shed points, so a
        # cancelled rider neither blocks on nor is served by the batch
        self.cancel = cancel


class QueryGroupPlanner:
    """Grouping rules for query-axis stacking (docs/query-batching.md).

    Buckets queued queries by the STRUCTURAL compatibility signature —
    `plan.structure_digest(k)` covers node sigs, sort spec, agg shape,
    array shapes/dtypes (and therefore the padding bucket and column
    families), scalar dtypes, and threshold/search_after/rebase PRESENCE —
    plus the split identity. Queries agreeing on that key stack into one
    dispatch regardless of their terms, filter bounds, threshold values,
    or sort markers; per-slot shared-vs-stacked operand placement is
    decided later from array cache keys (executor.stacked_slot_split).

    Also the accounting point for why queries did NOT stack: reject
    reasons are a bounded enum (`plan_shape` — an open group exists for
    the same split with a different structure; `group_full` — the open
    group hit max_batch), exported as qw_qbatch_incompatible_total."""

    def __init__(self, max_batch: int = 16):
        self.max_batch = max_batch

    @staticmethod
    def key_for(plan, k: int, split_key, stacking: bool) -> tuple:
        group_key = getattr(plan, "group_key", None)
        if stacking and group_key is not None:
            return group_key(k, split_key)
        # convoy key (seed behavior): the key carries the plan's array
        # cache keys, so queries sharing a dispatch are guaranteed to read
        # the very same device arrays (two terms of equal posting shape
        # lower to the same signature but DIFFERENT arrays — under the
        # kill switch they must not share)
        return (plan.signature(k), tuple(plan.array_keys), split_key)

    @staticmethod
    def note_reject(open_queues, key, stacking: bool) -> None:
        """Called (under the batcher lock) when a query LEADS a fresh
        queue: attribute why it could not join an existing group."""
        if not stacking:
            return
        full = open_queues.get(key)
        if full:
            QBATCH_INCOMPATIBLE_TOTAL.inc(reason="group_full")
            return
        split_key = key[2]
        if any(other[0] == "qb" and other[2] == split_key
               and other != key for other in open_queues):
            QBATCH_INCOMPATIBLE_TOTAL.inc(reason="plan_shape")



class QueryBatcher:
    """Groups concurrent compatible queries into one device dispatch —
    same-plan convoys always, DISTINCT shape-compatible queries when
    query-axis stacking is enabled. Thread-safe; every caller blocks only
    for its own result."""

    def __init__(self, max_batch: int = 16, fault_injector=None):
        self.max_batch = max_batch
        self.planner = QueryGroupPlanner(max_batch)
        self._lock = sync.lock("QueryBatcher._lock")
        sync.register_shared(self, "QueryBatcher")
        self._queues: dict[tuple, list[_Pending]] = {}
        # per-key dispatch serialization, refcounted so the dict cannot
        # grow without bound across query shapes / reader reopens
        self._dispatch_locks: dict[tuple, list] = {}  # key -> [lock, refs]
        # observability: dispatches vs queries served (batching efficiency)
        self.num_dispatches = 0
        self.num_queries = 0
        # chaos hook: perturbs "batcher.dispatch" before each real dispatch
        self.fault_injector = fault_injector

    @staticmethod
    def _abort_wait(me: _Pending, reason: str) -> None:
        if me.profile is not None:
            me.profile.record_phase(
                PHASE_BATCHER_QUEUE,
                time.monotonic() - me.enqueued_at,
                start=me.enqueued_at, aborted=True)
            me.profile.mark_partial(reason)

    def _follower_wait(self, me: _Pending) -> None:
        """Block until the leader serves `me`, bounded by the rider's own
        deadline AND its cancel token. A rider without a token waits in one
        shot (the seed path); with one, the wait polls in short slices so a
        mid-flight cancel is observed promptly instead of after the full
        batch round-trip (the shed-before-readback gap)."""
        bounded = me.deadline is not None and me.deadline.bounded
        if me.cancel is None:
            if not bounded:
                me.event.wait()
                return
            if me.event.wait(me.deadline.remaining() + _FOLLOWER_SLACK_SECS):
                return
            # the leader (stuck in a slow dispatch) outlived our budget;
            # abandon the ride — our scalars may still be computed, the
            # result is simply unclaimed
            SEARCH_SHED_TOTAL.inc(stage="batcher_wait")
            self._abort_wait(me, "shed: batcher wait")
            raise DeadlineExceeded("batched dispatch wait")
        while True:
            if me.cancel.cancelled:
                SEARCH_SHED_TOTAL.inc(stage="batcher_cancel")
                self._abort_wait(me, "cancelled: batcher wait")
                raise CancelledQuery("batched dispatch wait",
                                     me.cancel.reason)
            if bounded:
                remaining = me.deadline.remaining() + _FOLLOWER_SLACK_SECS
                if remaining <= 0:
                    SEARCH_SHED_TOTAL.inc(stage="batcher_wait")
                    self._abort_wait(me, "shed: batcher wait")
                    raise DeadlineExceeded("batched dispatch wait")
                slice_secs = min(_CANCEL_POLL_SECS, remaining)
            else:
                slice_secs = _CANCEL_POLL_SECS
            if me.event.wait(slice_secs):
                return

    def execute(self, plan, k: int, device_arrays, split_key,
                threshold_box=None, fault_injector=None) -> dict[str, Any]:
        """Run one query, possibly riding a shared dispatch. `split_key`
        must uniquely identify the split (reader identity). With stacking
        enabled the grouping key is the structural digest — distinct
        queries group; under `QW_DISABLE_QBATCH` the key also carries the
        plan's array cache keys, restoring the convoy-only behavior.
        `threshold_box`/`fault_injector` thread the chunked-execution
        context through group dispatches (leaf.py routes through the
        batcher BEFORE the chunked check when stacking is on)."""
        stacking = qbatch_enabled()
        key = self.planner.key_for(plan, k, split_key, stacking)
        tenant = effective_tenant()
        # overload checkpoint: under sustained queue-wait pressure the
        # lowest-priority tenants are bounced before taking a batch slot
        if OVERLOAD.should_shed(tenant.priority):
            SEARCH_SHED_TOTAL.inc(stage="overload_batcher")
            GLOBAL_TENANCY.note_shed(tenant.tenant_id, stage="batcher")
            raise OverloadShed("batcher", OVERLOAD.retry_after_secs())
        cancel = current_cancel_token()
        if cancel is not None:
            # already-cancelled queries never take a batch slot
            cancel.check("batcher enqueue")
        me = _Pending(plan.scalars, current_deadline(), current_profile(),
                      cancel, plan=plan, arrays=device_arrays,
                      tbox=threshold_box, tenant=tenant)
        my_queue = None
        with self._lock:
            sync.note_write(self, "queues")
            self.num_queries += 1
            SEARCH_BATCHER_QUERIES_TOTAL.inc()
            queue = self._queues.get(key)
            if queue is not None and len(queue) < self.max_batch:
                queue.append(me)          # follower: the leader serves us
            else:
                # new (or full) queue: lead a FRESH list. A full previous
                # list stays owned by its own leader (it is popped by
                # identity below), so its followers are never orphaned.
                self.planner.note_reject(self._queues, key, stacking)
                my_queue = [me]
                self._queues[key] = my_queue
                entry = self._dispatch_locks.setdefault(
                    key, [_PriorityLock(), 0])
                entry[1] += 1
                dispatch_lock = entry[0]
        if my_queue is None:
            self._follower_wait(me)
            if me.error is not None:
                raise _waiter_error(me.error)
            return me.result
        # serialize dispatches per key: while a previous dispatch is in
        # flight this blocks, and our queue keeps accumulating followers —
        # the batching window emerges from real dispatch latency instead of
        # a configured sleep. Contended handoff is priority-ordered: a
        # higher-class tenant's convoy dispatches before a lower one's.
        try:
            dispatch_lock.acquire(tenant.priority)
            try:
                with self._lock:
                    sync.note_write(self, "queues")
                    if self._queues.get(key) is my_queue:
                        del self._queues[key]
                    batch = my_queue
                # riders whose budget ran out — or who were cancelled —
                # while queued are shed NOW: dispatching for them wastes
                # device time nobody can use
                expired = [p for p in batch
                           if p.deadline is not None and p.deadline.expired]
                cancelled = [p for p in batch
                             if p not in expired and p.cancel is not None
                             and p.cancel.cancelled]
                alive = [p for p in batch
                         if p not in expired and p not in cancelled]
                now = time.monotonic()
                for pending in expired:
                    SEARCH_SHED_TOTAL.inc(stage="batcher_dispatch")
                    flight.emit("batcher.shed",
                                query_id=(pending.profile.query_id
                                          if pending.profile else ""))
                    if pending.profile is not None:
                        pending.profile.record_phase(
                            PHASE_BATCHER_QUEUE, now - pending.enqueued_at,
                            start=pending.enqueued_at, aborted=True)
                        pending.profile.mark_partial("shed: batcher dispatch")
                    pending.error = DeadlineExceeded("batched dispatch")
                    pending.event.set()
                for pending in cancelled:
                    SEARCH_SHED_TOTAL.inc(stage="batcher_cancel")
                    flight.emit("batcher.cancelled",
                                query_id=(pending.profile.query_id
                                          if pending.profile else ""))
                    if pending.profile is not None:
                        pending.profile.record_phase(
                            PHASE_BATCHER_QUEUE, now - pending.enqueued_at,
                            start=pending.enqueued_at, aborted=True)
                        pending.profile.mark_partial(
                            "cancelled: batcher dispatch")
                    pending.error = CancelledQuery("batched dispatch",
                                                   pending.cancel.reason)
                    pending.event.set()
                readback_fn = None
                readback_targets = alive
                try:
                    if alive:
                        grouped = stacking and len(batch) > 1
                        phase = (PHASE_QBATCH_GROUP if grouped
                                 else PHASE_BATCHER_QUEUE)
                        now = time.monotonic()
                        for pending in alive:
                            wait = now - pending.enqueued_at
                            SEARCH_BATCHER_QUEUE_WAIT.observe(wait)
                            OVERLOAD.note_wait(wait)
                            if pending.profile is not None:
                                pending.profile.record_phase(
                                    phase, wait,
                                    start=pending.enqueued_at,
                                    riders=len(alive))
                        with self._lock:
                            self.num_dispatches += 1
                            SEARCH_BATCHER_DISPATCHES_TOTAL.inc()
                            SEARCH_BATCHER_RATIO.set(
                                self.num_queries / self.num_dispatches)
                        if self.fault_injector is not None:
                            self.fault_injector.perturb("batcher.dispatch")
                        if len(batch) == 1 and alive[0] is me:
                            # lone query: nobody queues behind a convoy of
                            # one, so dispatch + readback run inline — the
                            # seed path. With stacking on the chunked check
                            # moved from the leaf into here (leaf routes
                            # through the batcher first), so the solo rider
                            # keeps its resumable scan.
                            result = None
                            if stacking and getattr(plan, "root",
                                                    None) is not None:
                                result = chunkexec.maybe_execute_chunked(
                                    plan, k, device_arrays,
                                    threshold_box=threshold_box,
                                    fault_injector=fault_injector)
                            if result is None:
                                result = executor.execute_plan(
                                    plan, k, device_arrays)
                            alive[0].result = result
                            alive[0].event.set()
                        elif grouped:
                            readback_targets, readback_fn = \
                                self._dispatch_group(
                                    batch, alive, k, fault_injector)
                        else:
                            dispatched = executor.dispatch_plan_multi(
                                plan, k, device_arrays,
                                [p.scalars for p in alive])
                            readback_fn = (lambda d=dispatched:
                                           executor.readback_plan_multi(d))
                # qwlint: disable-next-line=QW004 - the dispatch error is
                # fanned to every batched waiter and re-raised per-waiter
                # via _waiter_error; nothing is swallowed
                except Exception as exc:  # noqa: BLE001 - fan to waiters
                    for pending in alive:
                        pending.error = exc
                        pending.event.set()
            finally:
                # released after DISPATCH, before the blocking readback:
                # the next convoy for this key overlaps its dispatch with
                # our device->host wait (the async-readback pipeline)
                dispatch_lock.release()
            if readback_fn is not None:
                try:
                    still_wanted = [p for p in alive
                                    if (p.deadline is None
                                        or not p.deadline.expired)
                                    and (p.cancel is None
                                         or not p.cancel.cancelled)]
                    if not still_wanted:
                        # every rider's budget ran out (or was cancelled)
                        # while the kernel flew: nobody can use the answer,
                        # so the device->host transfer is never awaited
                        from .residency import RESIDENT_READBACKS_SHED
                        RESIDENT_READBACKS_SHED.inc()
                        for pending in alive:
                            if (pending.cancel is not None
                                    and pending.cancel.cancelled):
                                pending.error = CancelledQuery(
                                    "batched readback",
                                    pending.cancel.reason)
                            else:
                                pending.error = DeadlineExceeded(
                                    "batched readback shed")
                            pending.event.set()
                    else:
                        results = readback_fn()
                        for pending, result in zip(readback_targets,
                                                   results):
                            if pending.event.is_set():
                                # masked lane: its error was already fanned
                                # at the shed point (result is None/zeroed)
                                continue
                            if (pending.cancel is not None
                                    and pending.cancel.cancelled):
                                # cancelled after dispatch: the batch still
                                # flew for the live riders, but this one's
                                # answer is abandoned by contract
                                pending.error = CancelledQuery(
                                    "batched readback",
                                    pending.cancel.reason)
                            elif isinstance(result, Exception):
                                # per-lane typed outcome from a chunked
                                # group scan (lane cancel/deadline)
                                pending.error = result
                            else:
                                pending.result = result
                            pending.event.set()
                # qwlint: disable-next-line=QW004 - fanned to waiters and
                # re-raised per-waiter, same contract as the dispatch side
                except Exception as exc:  # noqa: BLE001 - fan to waiters
                    for pending in alive:
                        pending.error = exc
                        pending.event.set()
        finally:
            with self._lock:
                entry = self._dispatch_locks.get(key)
                if entry is not None:
                    entry[1] -= 1
                    if entry[1] <= 0:
                        del self._dispatch_locks[key]
        if me.error is not None:
            raise me.error
        return me.result

    def _dispatch_group(self, batch, alive, k, fault_injector):
        """One stacked dispatch for a formed query group. Shed riders stay
        IN the lane list with valid=False (masked, zeroed readback) so the
        compiled program is keyed only by the group's structure and
        bucket — launch count stays 1 whatever happens between formation
        and launch. Returns (readback_targets, readback_fn); the chunked
        composition reads back inside the scan, so its readback_fn just
        hands the per-lane outcomes through."""
        alive_set = set(id(p) for p in alive)
        valid = [id(p) in alive_set for p in batch]
        masked = len(batch) - len(alive)
        # a masked rider keeps ITS OWN operands in the stacked program
        # (identical shapes — that is the grouping invariant), so nothing
        # about the compiled program changes when it is shed; a rider with
        # no plan at all (test-planted sentinels) borrows a live donor's,
        # its lane being zeroed either way
        donor = alive[0]
        plans = [p.plan if p.plan is not None else donor.plan
                 for p in batch]
        arrays_list = [p.arrays if p.arrays is not None else donor.arrays
                       for p in batch]
        if len(alive) > 1:
            QBATCH_GROUPS_TOTAL.inc()
        QBATCH_QUERIES_PER_DISPATCH.observe(len(alive))
        if masked:
            QBATCH_MASKED_RIDERS_TOTAL.inc(masked)
        # group context onto every rider's profile: a slow stacked query's
        # slowlog entry names its group (size / lane / masked flag) so a
        # p99 outlier is attributable to group formation, not just itself
        for lane, pending in enumerate(batch):
            if pending.profile is not None:
                pending.profile.set_counter("qbatch_group_size",
                                            float(len(batch)))
                pending.profile.set_counter("qbatch_lane_index", float(lane))
                pending.profile.set_counter("qbatch_masked",
                                            0.0 if valid[lane] else 1.0)
        if flight.recording():
            flight.emit("batcher.group_formed",
                        attrs={"lanes": len(batch), "alive": len(alive),
                               "masked": masked})
        from .residency import note_group_shared_staging
        note_group_shared_staging(plans, len(alive))
        group_res = chunkexec.execute_group_chunked(
            plans, k, arrays_list, valid=valid,
            tboxes=[p.tbox for p in batch],
            deadlines=[p.deadline for p in batch],
            cancels=[p.cancel for p in batch],
            tenants=[p.tenant for p in batch],
            fault_injector=fault_injector)
        if group_res is not None:
            return batch, (lambda r=group_res: r)
        dispatched = executor.dispatch_plan_stacked(
            plans, k, arrays_list, valid=valid)
        return batch, (lambda d=dispatched:
                       executor.readback_plan_stacked(d))


def _waiter_error(err: Exception) -> Exception:
    """A fresh per-waiter exception chained to the shared dispatch error:
    many waiter threads re-raising the SAME instance would race on its
    __traceback__ and leak handler-side mutations across queries."""
    try:
        copy = type(err)(*err.args)
    # qwlint: disable-next-line=QW004 - reconstruction fallback: the
    # original error stays chained as __cause__ either way
    except Exception:  # noqa: BLE001 - exotic constructor signatures
        copy = RuntimeError(f"batched dispatch failed: {err!r}")
    copy.__cause__ = err
    return copy
