"""Statistical CPU profiler + flamegraph rendering (stdlib only).

Role of the reference's on-demand pprof endpoint
(`quickwit-serve/src/developer_api/pprof.rs:167`, pprof-rs flamegraphs):
a sampling thread snapshots every Python thread's stack via
`sys._current_frames()` at a fixed rate for a bounded duration, then the
samples render either as collapsed stacks (Brendan Gregg format — one
`frame;frame;frame count` line per unique stack, feedable to any
flamegraph toolchain) or as a self-contained SVG flamegraph.

Sampling is cooperative and safe: `_current_frames` is a consistent
point-in-time snapshot taken under the GIL, there is no signal handling,
and the profiler thread pays the only overhead (~hz stack walks/sec)."""

from __future__ import annotations

import html
import sys
import threading
from collections import Counter
from typing import Optional

from ..common.clock import monotonic, sleep


# serializes on-demand profiles (the REST endpoint takes it non-blocking)
# qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter updates
# only, no instrumented ops inside
PROFILE_LOCK = threading.Lock()


def _frame_label(frame) -> str:
    code = frame.f_code
    filename = code.co_filename
    # trim to the package-relative tail: keeps labels readable
    for marker in ("/quickwit_tpu/", "/tests/"):
        idx = filename.rfind(marker)
        if idx >= 0:
            filename = filename[idx + 1:]
            break
    else:
        filename = filename.rsplit("/", 1)[-1]
    return f"{code.co_name} ({filename}:{frame.f_lineno})"


def sample_stacks(duration_secs: float = 2.0, hz: float = 100.0,
                  exclude_thread_ids: Optional[set[int]] = None
                  ) -> Counter:
    """Counter of stack tuples (root→leaf) across all threads."""
    interval = 1.0 / max(hz, 1.0)
    # clock seam: under the DST harness the sampling window runs on
    # virtual time (a FakeClock sleep advances it), so a profile taken
    # inside a simulated run neither stalls the scheduler nor burns wall
    # clock; in production the seam is the real clock
    deadline = monotonic() + max(duration_secs, 0.0)
    skip = set(exclude_thread_ids or ())
    skip.add(threading.get_ident())  # never profile the profiler
    counts: Counter = Counter()
    while monotonic() < deadline:
        for thread_id, frame in sys._current_frames().items():
            if thread_id in skip:
                continue
            stack = []
            while frame is not None:
                stack.append(_frame_label(frame))
                frame = frame.f_back
            if stack:
                counts[tuple(reversed(stack))] += 1
        sleep(interval)
    return counts


def collapse(counts: Counter) -> str:
    """Brendan Gregg collapsed-stack format (semicolon-joined frames)."""
    lines = [f"{';'.join(stack)} {count}"
             for stack, count in sorted(counts.items())]
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# SVG flamegraph

_ROW_H = 16
_MIN_W = 0.1          # % width below which frames are elided
_PALETTE = ("#e06c2b", "#e28743", "#d9903f", "#cc7a2e", "#e8a05c",
            "#d67f35", "#e0893a", "#ca6f28")


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.children: dict[str, _Node] = {}


def _build_tree(counts: Counter) -> _Node:
    root = _Node("all")
    for stack, count in counts.items():
        root.value += count
        node = root
        for frame in stack:
            child = node.children.get(frame)
            if child is None:
                child = node.children[frame] = _Node(frame)
            child.value += count
            node = child
    return root


def render_svg(counts: Counter, title: str = "quickwit-tpu flamegraph",
               width: int = 1200) -> str:
    """Self-contained SVG flamegraph (no scripts; <title> tooltips)."""
    root = _build_tree(counts)
    total = max(root.value, 1)
    rects: list[str] = []
    max_depth = [0]

    def emit(node: _Node, depth: int, x_pct: float) -> None:
        child_x = x_pct
        for name in sorted(node.children):
            child = node.children[name]
            w_pct = child.value * 100.0 / total
            if w_pct >= _MIN_W:
                max_depth[0] = max(max_depth[0], depth + 1)
                color = _PALETTE[hash(name) % len(_PALETTE)]
                label = (name if w_pct > 8 else "")
                pct = child.value * 100.0 / total
                rects.append(
                    f'<g><title>{html.escape(name)} '
                    f'({child.value} samples, {pct:.1f}%)</title>'
                    f'<rect x="{child_x:.3f}%" y="{depth * _ROW_H}" '
                    f'width="{w_pct:.3f}%" height="{_ROW_H - 1}" '
                    f'fill="{color}" rx="1"/>'
                    + (f'<text x="{child_x + 0.2:.3f}%" '
                       f'y="{depth * _ROW_H + 11}" font-size="10" '
                       f'font-family="monospace">'
                       f'{html.escape(label[:120])}</text>'
                       if label else "")
                    + "</g>")
                emit(child, depth + 1, child_x)
            child_x += w_pct

    emit(root, 1, 0.0)
    height = (max_depth[0] + 2) * _ROW_H + 24
    header = (f'<text x="8" y="14" font-size="12" '
              f'font-family="sans-serif">{html.escape(title)} — '
              f'{total} samples</text>')
    body = "\n".join(rects)
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
            f'height="{height}" viewBox="0 0 {width} {height}">'
            f'<rect width="100%" height="100%" fill="#fdf6ee"/>'
            f'{header}\n{body}</svg>')
