"""Prometheus-style metrics registry.

Role of the reference's `quickwit-metrics` macro registry
(`quickwit-metrics/src/lib.rs:44-343`): lazily-registered counters, gauges
and histograms with labels, exposed in Prometheus text format on `/metrics`.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Optional, Sequence

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)


def _label_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline must be escaped or the exposition line is unparseable."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = {}
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, value in sorted(self._values.items()):
            lines.append(f"{self.name}{_format_labels(key)} {value:g}")
        return lines


class Gauge:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = {}
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def add(self, amount: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, value in sorted(self._values.items()):
            lines.append(f"{self.name}{_format_labels(key)} {value:g}")
        return lines


class Histogram:
    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_text
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        from bisect import bisect_left
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            # raw count at the first bucket with le >= value (cumulative form
            # is computed at exposition); larger values count only in +Inf
            slot = bisect_left(self.buckets, value)
            if slot < len(counts):
                counts[slot] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def percentile(self, q: float, **labels: str) -> Optional[float]:
        key = _label_key(labels)
        counts = self._counts.get(key)
        total = self._totals.get(key, 0)
        if not counts or total == 0:
            return None
        rank = q * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= rank:
                return self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
        return self.buckets[-1]

    def expose(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key in sorted(self._counts):
            counts = self._counts[key]
            cumulative = 0
            for bucket, count in zip(self.buckets, counts):
                cumulative += count
                label = dict(key)
                label["le"] = f"{bucket:g}"
                lines.append(
                    f"{self.name}_bucket{_format_labels(_label_key(label))} {cumulative}")
            label = dict(key)
            label["le"] = "+Inf"
            lines.append(
                f"{self.name}_bucket{_format_labels(_label_key(label))} "
                f"{self._totals[key]}")
            lines.append(f"{self.name}_sum{_format_labels(key)} "
                         f"{self._sums[key]:g}")
            lines.append(f"{self.name}_count{_format_labels(key)} "
                         f"{self._totals[key]}")
        return lines


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_text), Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_text), Gauge)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help_text, buckets), Histogram)

    def _get_or_create(self, name, factory, expected_type):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = factory()
                self._metrics[name] = metric
            elif not isinstance(metric, expected_type):
                raise TypeError(f"metric {name!r} already registered with another type")
            return metric

    def expose_text(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            lines.extend(metric.expose())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"


METRICS = MetricsRegistry()

# --- search-robustness metrics (deadline propagation / shedding) ----------
# Remaining query budget observed when a leaf search starts executing: a
# left-shifted distribution means queries burn their budget queueing.
SEARCH_DEADLINE_REMAINING = METRICS.histogram(
    "qw_search_deadline_remaining_seconds",
    "Remaining deadline budget when a leaf search begins execution")
# Work abandoned because the deadline had already passed, labeled by stage
# (admission queue, leaf group loop, batcher, ...).
SEARCH_SHED_TOTAL = METRICS.counter(
    "qw_search_shed_total",
    "Operations shed because the query deadline expired before they ran")
SEARCH_TIMED_OUT_TOTAL = METRICS.counter(
    "qw_search_timed_out_total",
    "Root searches that returned a timed_out partial response")
SEARCH_LEAF_RETRIES_TOTAL = METRICS.counter(
    "qw_search_leaf_retries_total",
    "Leaf requests retried on another node after a failure")
# Phase-2 doc fetches retried once on the next replica (root.py
# _fetch_docs_phase); the leaf retry budget above covers phase 1 only.
SEARCH_FETCH_DOCS_RETRIES_TOTAL = METRICS.counter(
    "qw_search_fetch_docs_retries_total",
    "Per-split doc fetches retried on another replica after a failure")

# --- query batcher (search/batcher.py) ------------------------------------
# Batching efficiency is queries/dispatches: 1.0 means no coalescing,
# higher means concurrent same-shape queries rode shared vmapped
# dispatches. Exported as two counters (PromQL rate-ratio friendly) plus
# a convenience gauge of the cumulative ratio.
SEARCH_BATCHER_QUERIES_TOTAL = METRICS.counter(
    "qw_search_batcher_queries_total",
    "Queries entering the cross-query dispatch batcher")
SEARCH_BATCHER_DISPATCHES_TOTAL = METRICS.counter(
    "qw_search_batcher_dispatches_total",
    "Device dispatch rounds issued by the batcher")
SEARCH_BATCHER_RATIO = METRICS.gauge(
    "qw_search_batcher_ratio",
    "Cumulative queries-per-dispatch coalescing ratio of the batcher")
# Time a rider spends queued between enqueue and its dispatch starting —
# the convoy window. Followers pay this to ride a shared dispatch.
SEARCH_BATCHER_QUEUE_WAIT = METRICS.histogram(
    "qw_search_batcher_queue_wait_seconds",
    "Wait between a query entering the batcher and its dispatch starting")

# --- query-group stacking (search/batcher.py QueryGroupPlanner) -----------
# DISTINCT shape-compatible queries stacked into one device dispatch along
# a query axis (ROADMAP item 2) — as opposed to the convoy counters above,
# which cover riders of one identical plan. Reject reasons are a bounded
# enum (plan_shape | group_full), never request-derived.
QBATCH_GROUPS_TOTAL = METRICS.counter(
    "qw_qbatch_groups_total",
    "Query groups (>1 distinct queries) executed as one stacked dispatch")
QBATCH_QUERIES_PER_DISPATCH = METRICS.histogram(
    "qw_qbatch_queries_per_dispatch",
    "Live query lanes per stacked group dispatch",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0))
QBATCH_INCOMPATIBLE_TOTAL = METRICS.counter(
    "qw_qbatch_incompatible_total",
    "Queries that could not join an open group, by bounded reject reason")
QBATCH_MASKED_RIDERS_TOTAL = METRICS.counter(
    "qw_qbatch_masked_riders_total",
    "Riders masked out of an already-formed group (validity lane zeroed) "
    "instead of forcing a group rebuild")
QBATCH_SHARED_BYTES_AVOIDED_TOTAL = METRICS.counter(
    "qw_qbatch_shared_bytes_avoided_total",
    "Operand bytes served once as broadcast slots instead of per-lane "
    "copies in stacked group dispatches")

# --- dynamic top-K split pruning (search/pruning.py) ----------------------
# Splits never executed because their sort-value/score upper bound could
# not beat the collector's Kth value (count_hits_exact=False).
SEARCH_SPLITS_PRUNED_TOTAL = METRICS.counter(
    "qw_search_splits_pruned_by_threshold_total",
    "Splits skipped because their sort bound cannot beat the top-K threshold")
# Splits that could not contribute hits but still owed an exact count:
# re-executed as count-only requests (max_hits=0 fast path).
SEARCH_SPLITS_DOWNGRADED_TOTAL = METRICS.counter(
    "qw_search_splits_downgraded_to_count_total",
    "Splits downgraded to count-only requests by the top-K threshold")
# Kernel dispatches that carried a threshold scalar (sub-threshold docs
# masked before top_k); batch dispatches count each real lane.
SEARCH_KERNEL_THRESHOLD_TOTAL = METRICS.counter(
    "qw_search_kernel_threshold_pushdown_total",
    "Plan executions dispatched with a pushed-down top-K threshold scalar")

# --- impact-ordered postings (format v3, index/impact.py) ------------------
# Host-side prefix-cutoff decisions made at plan lowering: how many
# 128-posting blocks of the sole scoring term stayed live vs were skipped
# (never staged to HBM) because their quantized block-max bound could not
# reach the pushed-down threshold.
IMPACT_BLOCKS_SCORED_TOTAL = METRICS.counter(
    "qw_impact_blocks_scored_total",
    "Impact posting blocks staged and scored (live prefix)")
IMPACT_BLOCKS_SKIPPED_TOTAL = METRICS.counter(
    "qw_impact_blocks_skipped_total",
    "Impact posting blocks skipped by the block-max prefix cutoff")
IMPACT_POSTINGS_BYTES_AVOIDED_TOTAL = METRICS.counter(
    "qw_impact_postings_bytes_avoided_total",
    "Posting bytes (ids+tfs) never staged thanks to the prefix cutoff")
IMPACT_PREFIX_CUTOFFS_TOTAL = METRICS.counter(
    "qw_impact_prefix_cutoffs_total",
    "Plan lowerings that truncated a term's postings to the live prefix")

# --- per-query execution profiles (observability/profile.py) ---------------
# Wall time per waterfall phase, labeled phase=<name> (plan_build,
# admission_wait, batcher_queue_wait, storage_read, staging, compile,
# execute, topk_merge, root_merge, fetch_docs, ...). Fed by every profiled
# query, so fleet-wide attribution is queryable without slowlog capture.
SEARCH_PHASE_SECONDS = METRICS.histogram(
    "qw_search_phase_seconds",
    "Wall time spent per query-execution phase (profile waterfall)")
SEARCH_PROFILED_QUERIES_TOTAL = METRICS.counter(
    "qw_search_profiled_queries_total",
    "Root searches that ran with an execution profile attached")
SEARCH_SLOWLOG_RECORDED_TOTAL = METRICS.counter(
    "qw_search_slowlog_recorded_total",
    "Queries captured into the slow-query ring buffer")

# --- multi-tenant workload isolation (tenancy/) ----------------------------
# All tenant labels pass through TenancyRegistry.metric_label, which hashes
# long ids and caps distinct label values, so cardinality stays bounded no
# matter what clients put in the tenant header.
TENANT_QUERIES_TOTAL = METRICS.counter(
    "qw_tenant_queries_total",
    "Root searches per tenant, labeled by completion status")
TENANT_SHED_TOTAL = METRICS.counter(
    "qw_tenant_shed_total",
    "Queries shed by the overload controller, per tenant and checkpoint")
TENANT_REJECTED_TOTAL = METRICS.counter(
    "qw_tenant_rejected_total",
    "Queries rejected by per-tenant token-bucket rate limits")
TENANT_STAGED_BYTES_TOTAL = METRICS.counter(
    "qw_tenant_staged_bytes_total",
    "HBM bytes admitted (staged) per tenant")
TENANT_EXECUTE_SECONDS_TOTAL = METRICS.counter(
    "qw_tenant_execute_seconds_total",
    "Execution wall time attributed to each tenant from query profiles")
TENANT_ADMISSION_WAIT = METRICS.histogram(
    "qw_tenant_admission_wait_seconds",
    "HBM admission queue wait per tenant")

# --- elastic leaf-search offload pool (offload/) ----------------------------
# One attempt = one leaf-search RPC to one worker. outcome is a small fixed
# enum (ok | error | backpressure | discarded); per-worker breakdowns live
# in WorkerPool.snapshot(), not in labels, so cardinality stays bounded
# however large the elastic fleet gets.
OFFLOAD_DISPATCHES_TOTAL = METRICS.counter(
    "qw_offload_dispatches_total",
    "Leaf-search dispatch attempts to offload workers, by outcome")
OFFLOAD_RETRIES_TOTAL = METRICS.counter(
    "qw_offload_retries_total",
    "Offload tasks re-dispatched to the next rendezvous-ranked worker "
    "after a failure")
OFFLOAD_HEDGES_TOTAL = METRICS.counter(
    "qw_offload_hedges_total",
    "Hedged (backup) dispatches launched against straggler workers, "
    "by outcome (won = the hedge's response was used)")
OFFLOAD_STEALS_TOTAL = METRICS.counter(
    "qw_offload_steals_total",
    "Queued offload tasks stolen from a busy worker's queue by an idle "
    "worker")
OFFLOAD_SPLITS_TOTAL = METRICS.counter(
    "qw_offload_splits_total",
    "Splits routed through the offload pool, by final outcome "
    "(remote = served by a worker, fallback_local = returned to the "
    "local execution path)")
OFFLOAD_POOL_WORKERS = METRICS.gauge(
    "qw_offload_pool_workers",
    "Registered offload workers by health state "
    "(healthy | suspect | ejected)")
OFFLOAD_QUEUE_DEPTH = METRICS.gauge(
    "qw_offload_queue_depth",
    "Offloaded splits currently queued or in flight on the worker pool")
OFFLOAD_DISPATCH_SECONDS = METRICS.histogram(
    "qw_offload_dispatch_seconds",
    "Latency of successful offload dispatch attempts (one worker RPC)")
OFFLOAD_AUTOSCALE_TOTAL = METRICS.counter(
    "qw_offload_autoscale_events_total",
    "Offload pool autoscaler resize events, by direction (up | down)")

# --- hierarchical leaf caches (search/cache.py, search/mask_cache.py,
#     search/agg_cache.py, search/predicate_cache.py) ------------------------
# Three result-reuse tiers over immutable splits plus the term-absence
# negative cache, each with hit/miss/evicted-bytes counters so cache health
# is visible on /metrics instead of only the REST developer endpoint. All
# four are tenant-partitioned (search/tenant_cache.py); these counters
# aggregate across partitions (per-tenant byte breakdowns stay on the
# developer endpoint to bound label cardinality).
LEAF_CACHE_HITS_TOTAL = METRICS.counter(
    "qw_leaf_cache_hits_total",
    "Whole-split LeafSearchResponse cache hits")
LEAF_CACHE_MISSES_TOTAL = METRICS.counter(
    "qw_leaf_cache_misses_total",
    "Whole-split LeafSearchResponse cache misses")
LEAF_CACHE_EVICTED_BYTES_TOTAL = METRICS.counter(
    "qw_leaf_cache_evicted_bytes_total",
    "Bytes evicted from the leaf response cache under capacity pressure")
PREDICATE_CACHE_HITS_TOTAL = METRICS.counter(
    "qw_predicate_cache_hits_total",
    "Splits proven empty by the term-absence negative cache")
PREDICATE_CACHE_MISSES_TOTAL = METRICS.counter(
    "qw_predicate_cache_misses_total",
    "Negative-cache consults that could not prove the split empty")
PREDICATE_CACHE_EVICTED_BYTES_TOTAL = METRICS.counter(
    "qw_predicate_cache_evicted_bytes_total",
    "Absence-proof bytes evicted from the predicate cache under its "
    "byte/entry bounds")
MASK_CACHE_HITS_TOTAL = METRICS.counter(
    "qw_mask_cache_hits_total",
    "Predicate-mask cache hits (filter bitmask reused across query shapes)")
MASK_CACHE_MISSES_TOTAL = METRICS.counter(
    "qw_mask_cache_misses_total",
    "Predicate-mask cache misses (filter evaluated on device)")
MASK_CACHE_EVICTED_BYTES_TOTAL = METRICS.counter(
    "qw_mask_cache_evicted_bytes_total",
    "Packed mask bytes evicted from the mask cache under capacity pressure")
AGG_CACHE_HITS_TOTAL = METRICS.counter(
    "qw_agg_cache_hits_total",
    "Partial-aggregation cache hits (count or intermediate agg state)")
AGG_CACHE_MISSES_TOTAL = METRICS.counter(
    "qw_agg_cache_misses_total",
    "Partial-aggregation cache misses")
AGG_CACHE_EVICTED_BYTES_TOTAL = METRICS.counter(
    "qw_agg_cache_evicted_bytes_total",
    "Intermediate-agg bytes evicted from the partial-agg cache under "
    "capacity pressure")
# Staging attribution for the mask tier's headline claim: total staged
# bytes, the subset staged ONLY for predicate evaluation (arrays no sort/
# agg consumer touches — a mask-cache hit stages zero of these), and total
# device kernel dispatches (a Tier-B short-circuit launches none).
STAGING_BYTES_TOTAL = METRICS.counter(
    "qw_staging_bytes_total",
    "Host-to-device bytes staged by leaf warmup")
PREDICATE_STAGED_BYTES_TOTAL = METRICS.counter(
    "qw_predicate_column_staged_bytes_total",
    "Staged bytes attributable only to predicate evaluation "
    "(postings/fieldnorm/filter-column arrays without a sort or agg "
    "consumer)")
SEARCH_KERNEL_LAUNCHES_TOTAL = METRICS.counter(
    "qw_search_kernel_launches_total",
    "Device kernel dispatches (single, multi-query, and mask-fill)")

# --- chaos / fault injection (common/faults.py) ----------------------------
# Every fault the injector actually fired, labeled op=<operation>
# kind=<latency|error|hang>: chaos runs are visible in /metrics instead of
# only in test assertions.
FAULTS_INJECTED_TOTAL = METRICS.counter(
    "qw_faults_injected_total",
    "Faults fired by the deterministic chaos FaultInjector")

# --- resumable chunked leaf kernels (search/chunkexec.py) -------------------
# One increment per compiled chunk program dispatched by the chunked scan;
# comparing against qw_search_kernel_launches_total shows how much of the
# kernel traffic runs under boundary control.
CHUNK_DISPATCHES_TOTAL = METRICS.counter(
    "qw_chunk_dispatches_total",
    "Chunk programs dispatched by the resumable chunked leaf scan")
# A chunked query that lost its carried state (parked-state eviction under
# byte pressure, or a kernel.chunk_yield fault) and re-executed from chunk 0.
CHUNK_RESTARTS_TOTAL = METRICS.counter(
    "qw_chunk_restarts_total",
    "Chunked queries that discarded carried state and re-executed from scratch")
# Cross-chunk block-max pruning: remaining chunks provably could not beat
# the current Kth value, so the scan stopped early.
CHUNK_EARLY_TERMINATIONS_TOTAL = METRICS.counter(
    "qw_chunk_early_terminations_total",
    "Chunked scans stopped early by the cross-chunk block-max bound")
# Host wall time between consecutive chunk boundaries (dispatch + readback
# + boundary checks): the preemption/cancellation latency bound. The chunk
# sizer steers this toward its target interval (~10ms class).
CHUNK_BOUNDARY_SECONDS = METRICS.histogram(
    "qw_chunk_boundary_seconds",
    "Wall time between consecutive chunk boundaries of the chunked scan")
# A lower-class query yielded at a chunk boundary because the overload
# ladder tripped while a higher-class query was running.
PREEMPT_TOTAL = METRICS.counter(
    "qw_preempt_total",
    "Chunked queries preempted at a boundary in favor of a higher class")
# Bytes of carried top-K/agg state currently parked by preempted queries
# (bounded by the per-tenant DRR quantum; evictions force restarts).
PREEMPT_PARKED_BYTES = METRICS.gauge(
    "qw_preempt_parked_bytes",
    "Carried chunk state bytes currently parked by preempted queries")
# REST DELETE /api/v1/search/<query_id> cancellations that found (and
# flipped) a live query's CancellationToken.
SEARCH_CANCEL_TOTAL = METRICS.counter(
    "qw_search_cancel_total",
    "Explicit query cancellations accepted via the REST cancel surface")

# --- multi-chip collective root merge (parallel/fanout.py mesh path) --------
# One dispatch = one whole-query shard_map program: per-device split shards
# score locally, exchange the running sort-value threshold (pmax), merge
# top-K (all_gather + re-top-k) and mergeable agg states (psum/pmin/pmax)
# on-mesh, and read back ONE packed scalar array.
MESH_DISPATCHES_TOTAL = METRICS.counter(
    "qw_mesh_dispatches_total",
    "Whole-query collective programs dispatched over a device mesh")
MESH_DEVICES = METRICS.gauge(
    "qw_mesh_devices",
    "Devices (splits axis x docs axis) of the most recent mesh dispatch")
# Logical payload bytes, not wire bytes: each collective's operand size
# summed once per dispatch (all_gather candidates + psum/pmin/pmax agg,
# count, and certificate payloads + the threshold-exchange scalar). Wire
# amplification is topology-dependent and deliberately out of scope.
MESH_COLLECTIVE_BYTES_TOTAL = METRICS.counter(
    "qw_mesh_collective_bytes_total",
    "Logical payload bytes moved by on-mesh collectives per dispatch")
MESH_THRESHOLD_EXCHANGE_ROUNDS_TOTAL = METRICS.counter(
    "qw_mesh_threshold_exchange_rounds_total",
    "Cross-device sort-threshold all-reduce (pmax) rounds executed")

# --- flight recorder (observability/flight.py) ------------------------------
# The always-on device-timeline black box: typed lifecycle events from
# every hot subsystem into bounded per-thread rings. `subsystem` is the
# dotted-kind prefix (batcher, staging, compile, dispatch, chunk, mesh,
# cache, drr, overload, cancel, query, ...) — a small closed vocabulary
# fixed by the emit sites, never request-derived.
FLIGHT_EVENTS_TOTAL = METRICS.counter(
    "qw_flight_events_total",
    "Flight-recorder events recorded, by emitting subsystem")
FLIGHT_DROPPED_EVENTS = METRICS.gauge(
    "qw_flight_dropped_events",
    "Flight-recorder events overwritten by ring wrap (refreshed on export)")
FLIGHT_THREADS = METRICS.gauge(
    "qw_flight_threads",
    "Threads that have registered a flight-recorder ring")
FLIGHT_EXPORTS_TOTAL = METRICS.counter(
    "qw_flight_exports_total",
    "Chrome trace-event exports served (REST endpoint + CLI)")

# --- per-tenant SLO burn accounting (observability/slo.py) ------------------
# Per-priority-class latency objectives over the flight-recorder event
# stream: every root query completion is judged against its class
# objective; the burn rate is the windowed breach fraction over the class
# error budget (burn > 1.0 means the budget is being spent faster than
# the objective allows).
SLO_QUERIES_TOTAL = METRICS.counter(
    "qw_slo_queries_total",
    "Root queries judged against their class SLO, by verdict (ok|breach)")
SLO_BURN_RATE = METRICS.gauge(
    "qw_slo_burn_rate",
    "Windowed SLO burn rate per priority class (breach rate over budget)")
SLO_OBJECTIVE_LATENCY_MS = METRICS.gauge(
    "qw_slo_objective_latency_ms",
    "Configured per-priority-class latency objective (milliseconds)")
